"""resilience: retry budgets, circuit breaking, reconnecting admin backend,
solver device-failover, and the /health probe plumbing.

``configure(config)`` is called once from ``build_app`` (mirroring obsvc):
it snapshots the ``resilience.*`` config keys into a process-wide
:class:`ResilienceSettings` and materializes every ``Resilience.*`` sensor
so the docs/SENSORS.md drift guard sees them from boot.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from cruise_control_tpu.common.metrics import registry
from cruise_control_tpu.resilience.circuit import (STATE_VALUE, CircuitBreaker,
                                                   CircuitState)
from cruise_control_tpu.resilience.failover import (SOLVER_FAILOVER_SENSOR,
                                                    cpu_fallback,
                                                    is_device_failure)
from cruise_control_tpu.resilience.reconnect import (RECONNECTS_SENSOR,
                                                     TRANSPORT_ERRORS_SENSOR,
                                                     BackendCircuitOpenError,
                                                     ReconnectingBackend)
from cruise_control_tpu.resilience.retry import (RETRY_ATTEMPTS_SENSOR,
                                                 RetryBudgetExhausted,
                                                 RetryPolicy, call_with_retry)

ADMISSION_REJECTIONS_SENSOR = "Resilience.admission-rejections"
CIRCUIT_STATE_SENSOR = "Resilience.backend.circuit-state"


@dataclass(frozen=True)
class ResilienceSettings:
    retry_max_attempts: int = 4
    retry_base_delay_ms: int = 100
    retry_max_delay_ms: int = 5_000
    retry_deadline_ms: int = 30_000
    circuit_failure_threshold: int = 5
    circuit_reset_timeout_ms: int = 10_000
    reconnect_enabled: bool = True
    journal_path: str = ""
    journal_adoption_timeout_ms: int = 30_000
    health_retry_after_s: int = 30

    def retry_policy(self) -> RetryPolicy:
        return RetryPolicy(max_attempts=self.retry_max_attempts,
                           base_delay_s=self.retry_base_delay_ms / 1000.0,
                           max_delay_s=self.retry_max_delay_ms / 1000.0,
                           deadline_s=self.retry_deadline_ms / 1000.0)

    def circuit(self, name: str = "backend") -> CircuitBreaker:
        return CircuitBreaker(
            name,
            failure_threshold=self.circuit_failure_threshold,
            reset_timeout_s=self.circuit_reset_timeout_ms / 1000.0)


_settings = ResilienceSettings()
_backend_circuit: Optional[CircuitBreaker] = None
_lock = threading.Lock()


def settings() -> ResilienceSettings:
    return _settings


def set_backend_circuit(circuit: Optional[CircuitBreaker]) -> None:
    """Publish the executor admin backend's breaker for the circuit-state
    gauge and the /health backend probe."""
    global _backend_circuit
    with _lock:
        _backend_circuit = circuit


def backend_circuit() -> Optional[CircuitBreaker]:
    with _lock:
        return _backend_circuit


def _circuit_state_value() -> int:
    cb = backend_circuit()
    return 0 if cb is None else cb.state_value()


def register_sensors() -> None:
    """Materialize the Resilience.* sensor family (idempotent)."""
    reg = registry()
    reg.counter(RETRY_ATTEMPTS_SENSOR)
    reg.counter(RECONNECTS_SENSOR)
    reg.counter(TRANSPORT_ERRORS_SENSOR)
    reg.counter(SOLVER_FAILOVER_SENSOR)
    reg.counter(ADMISSION_REJECTIONS_SENSOR)
    reg.gauge(CIRCUIT_STATE_SENSOR, _circuit_state_value)


def configure(config) -> ResilienceSettings:
    """Snapshot ``resilience.*`` keys (CruiseControlConfig mapping access)
    into the process settings and register the sensor family."""
    global _settings
    _settings = ResilienceSettings(
        retry_max_attempts=int(config["resilience.retry.max.attempts"]),
        retry_base_delay_ms=int(config["resilience.retry.base.delay.ms"]),
        retry_max_delay_ms=int(config["resilience.retry.max.delay.ms"]),
        retry_deadline_ms=int(config["resilience.retry.deadline.ms"]),
        circuit_failure_threshold=int(
            config["resilience.circuit.failure.threshold"]),
        circuit_reset_timeout_ms=int(
            config["resilience.circuit.reset.timeout.ms"]),
        reconnect_enabled=bool(
            config["resilience.backend.reconnect.enabled"]),
        journal_path=str(config["resilience.journal.path"] or ""),
        journal_adoption_timeout_ms=int(
            config["resilience.journal.adoption.timeout.ms"]),
        health_retry_after_s=int(config["resilience.health.retry.after.s"]),
    )
    register_sensors()
    return _settings


__all__ = [
    "ADMISSION_REJECTIONS_SENSOR", "CIRCUIT_STATE_SENSOR",
    "BackendCircuitOpenError", "CircuitBreaker", "CircuitState",
    "ReconnectingBackend", "ResilienceSettings", "RetryBudgetExhausted",
    "RetryPolicy", "STATE_VALUE", "backend_circuit", "call_with_retry",
    "configure", "cpu_fallback", "is_device_failure", "register_sensors",
    "set_backend_circuit", "settings",
]
