"""Three-state circuit breaker (closed → open → half-open → closed).

Counts consecutive failures; at ``failure_threshold`` the circuit opens and
``allow()`` refuses calls until ``reset_timeout_s`` has elapsed, after which
a bounded number of half-open probes may pass.  One probe success re-closes
the circuit; one probe failure re-opens it and restarts the timeout.

The breaker is pure mechanism — it does not raise.  Callers (the
reconnecting backend) gate on ``allow()`` and translate a refused call into
their own error type so the executor can distinguish "backend is down,
pause" from "this one call failed, mark dead".
"""

from __future__ import annotations

import threading
import time
from enum import Enum
from typing import Callable, Dict, Optional


class CircuitState(Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


#: Gauge encoding for /metrics: higher is worse.
STATE_VALUE = {CircuitState.CLOSED: 0,
               CircuitState.HALF_OPEN: 1,
               CircuitState.OPEN: 2}


class CircuitBreaker:
    def __init__(self, name: str = "circuit", *,
                 failure_threshold: int = 5,
                 reset_timeout_s: float = 10.0,
                 half_open_max_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_max_probes = max(1, int(half_open_max_probes))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CircuitState.CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probes_granted = 0
        self.open_count = 0          # times the circuit tripped open
        self.reclose_count = 0       # times a half-open probe healed it

    # -- state ------------------------------------------------------------

    @property
    def state(self) -> CircuitState:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def state_value(self) -> int:
        return STATE_VALUE[self.state]

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            self._maybe_half_open_locked()
            return {"state": self._state.value,
                    "consecutiveFailures": self._consecutive_failures,
                    "failureThreshold": self.failure_threshold,
                    "openCount": self.open_count,
                    "recloseCount": self.reclose_count}

    def _maybe_half_open_locked(self) -> None:
        if (self._state is CircuitState.OPEN
                and self._opened_at is not None
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            self._state = CircuitState.HALF_OPEN
            self._probes_granted = 0

    # -- gate + outcome reporting -----------------------------------------

    def allow(self) -> bool:
        """May a call proceed right now?  In half-open state, grants at most
        ``half_open_max_probes`` in-flight probes until an outcome lands."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state is CircuitState.CLOSED:
                return True
            if self._state is CircuitState.HALF_OPEN:
                if self._probes_granted < self.half_open_max_probes:
                    self._probes_granted += 1
                    return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state is not CircuitState.CLOSED:
                self.reclose_count += 1
            self._state = CircuitState.CLOSED
            self._consecutive_failures = 0
            self._opened_at = None
            self._probes_granted = 0

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            trip = (self._state is CircuitState.HALF_OPEN
                    or self._consecutive_failures >= self.failure_threshold)
            if trip:
                if self._state is not CircuitState.OPEN:
                    self.open_count += 1
                self._state = CircuitState.OPEN
                self._opened_at = self._clock()
                self._probes_granted = 0
