"""Retry with jittered exponential backoff under a total deadline budget.

Reference posture: the reference Cruise Control leans on the Kafka admin
client's built-in retries; our admin protocol is a bare JSON-lines socket,
so the retry economics live here instead.  A ``RetryPolicy`` is pure data
(safe to share across threads); ``call_with_retry`` is the single execution
engine, injectable clock/sleep for deterministic tests.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type, TypeVar

from cruise_control_tpu.common.metrics import registry

LOG = logging.getLogger(__name__)

T = TypeVar("T")

RETRY_ATTEMPTS_SENSOR = "Resilience.retry-attempts"


class RetryBudgetExhausted(RuntimeError):
    """Every attempt failed (count or deadline); ``__cause__`` is the last
    underlying error."""


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff bounded by attempts AND wall-clock.

    ``deadline_s`` is a *budget across the whole retry cycle*: a sleep that
    would overrun it is not taken — the cycle fails early rather than
    blocking a caller (the executor's progress loop) past its patience.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.1
    max_delay_s: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.5            # ± fraction of the computed delay
    deadline_s: float = 30.0

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        raw = min(self.max_delay_s,
                  self.base_delay_s * (self.multiplier ** attempt))
        if self.jitter > 0.0:
            raw *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, raw)


def call_with_retry(
    fn: Callable[[], T],
    policy: Optional[RetryPolicy] = None,
    *,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    name: str = "call",
    rng: Optional[random.Random] = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
) -> T:
    """Run ``fn`` under ``policy``; raise :class:`RetryBudgetExhausted` when
    the attempt count or the deadline budget runs out.

    Exceptions not listed in ``retry_on`` propagate immediately — the
    circuit breaker's open signal rides this path so a tripped circuit is
    never retried against.
    """
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    attempts_sensor = registry().counter(RETRY_ATTEMPTS_SENSOR)
    deadline = clock() + policy.deadline_s
    last: Optional[BaseException] = None
    for attempt in range(max(1, policy.max_attempts)):
        try:
            return fn()
        except retry_on as exc:          # noqa: PERF203 — retry loop
            last = exc
            attempts_sensor.inc()
            delay = policy.delay_s(attempt, rng)
            if attempt + 1 >= policy.max_attempts:
                break
            if clock() + delay > deadline:
                LOG.debug("%s: deadline budget (%.1fs) exhausted after "
                          "attempt %d", name, policy.deadline_s, attempt + 1)
                break
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            LOG.debug("%s failed (attempt %d/%d: %s); retrying in %.3fs",
                      name, attempt + 1, policy.max_attempts, exc, delay)
            sleep(delay)
    raise RetryBudgetExhausted(
        f"{name} failed after {policy.max_attempts} attempt(s) "
        f"within {policy.deadline_s:.1f}s: {last}") from last
