"""Solver device-failover: classify device-loss errors, rerun on CPU.

A dead/hung TPU device surfaces as ``XlaRuntimeError`` (or a wrapped
``RuntimeError`` with a PJRT status message) at the dispatch seam.  Losing
the accelerator should degrade the propose path, not kill it: the facade
catches these, re-runs the solve pinned to the CPU backend, and tags the
response + trace span ``degraded=true`` so operators can see the cluster is
being balanced on the slow path.
"""

from __future__ import annotations

import logging
from contextlib import contextmanager

LOG = logging.getLogger(__name__)

SOLVER_FAILOVER_SENSOR = "Resilience.solver-cpu-failovers"

#: Exception type names that indicate the runtime/device died (matched by
#: name — jaxlib's exception classes move between modules across versions).
_FAILURE_TYPE_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "DeviceLostError",
    "PjRtError", "InternalError",
})

#: Status-message markers from PJRT/XLA for device loss and runtime death
#: (seen in practice over flaky TPU tunnels; see docs/OPERATIONS.md).
_FAILURE_MARKERS = (
    "DEVICE_LOST", "device lost", "DATA_LOSS",
    "failed to enqueue", "Unable to launch",
    "Socket closed", "Connection reset",
    "TPU initialization failed", "backend_compile_and_load",
    "ABORTED: ", "UNAVAILABLE: ",
)


def is_device_failure(exc: BaseException) -> bool:
    """True when ``exc`` (or anything in its cause chain) looks like the
    accelerator runtime died, as opposed to an application error."""
    seen = set()
    cur: BaseException | None = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if type(cur).__name__ in _FAILURE_TYPE_NAMES:
            return True
        if isinstance(cur, (RuntimeError, OSError)):
            msg = str(cur)
            if any(marker in msg for marker in _FAILURE_MARKERS):
                return True
        cur = cur.__cause__ or cur.__context__
    return False


@contextmanager
def cpu_fallback():
    """Run the body with JAX dispatch pinned to the first CPU device."""
    import jax
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        yield cpu
