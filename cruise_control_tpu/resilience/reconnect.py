"""Reconnecting admin backend: a poisoned transport is rebuilt, not fatal.

``SubprocessClusterBackend``/``SocketClusterBackend`` deliberately poison
themselves on any framing desync — correct for protocol safety, but it made
every transport hiccup terminal for the whole execution.  This wrapper owns
a *factory* (the transport constructors do not retain their connect
parameters) and rebuilds the inner backend under the retry policy whenever
a call raises :class:`BackendTransportError`.

Safety argument for retrying admin ops: every protocol op is idempotent at
the peer (reassignments are keyed by (topic, partition); re-submitting an
in-flight one is a no-op; ``is_done``/``list``/``describe`` are reads), and
after every reconnect the wrapper re-polls ``in_progress_reassignments()``
so the caller's view re-anchors on what the cluster is actually still
doing (exposed as ``last_repoll``).

When the circuit breaker trips, calls fail fast with
:class:`BackendCircuitOpenError` — a subclass of ``BackendTransportError``
so existing handlers still degrade gracefully, but distinct so the executor
can *pause* (``PAUSED_BACKEND_DOWN``) instead of letting tasks rot to the
alert timeout.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional, Set, Tuple

from cruise_control_tpu.common.metrics import registry
from cruise_control_tpu.executor.subprocess_backend import (
    BackendCircuitOpenError, BackendTransportError, SubprocessClusterBackend)
from cruise_control_tpu.resilience.circuit import CircuitBreaker, CircuitState
from cruise_control_tpu.resilience.retry import (RetryBudgetExhausted,
                                                 RetryPolicy, call_with_retry)

LOG = logging.getLogger(__name__)

RECONNECTS_SENSOR = "Resilience.backend.reconnects"
TRANSPORT_ERRORS_SENSOR = "Resilience.backend.transport-errors"


class ReconnectingBackend:
    """ClusterAdminBackend that survives transport death.

    ``factory`` must return a *connected* transport backend each call (a
    closure over host/port/auth — the transports don't store them).  The
    wrapper connects lazily: construction never touches the network, so the
    service can boot while its admin peer is down and report it via
    ``/health`` instead of crashing.
    """

    def __init__(self, factory: Callable[[], SubprocessClusterBackend], *,
                 policy: Optional[RetryPolicy] = None,
                 circuit: Optional[CircuitBreaker] = None,
                 name: str = "backend") -> None:
        self._factory = factory
        self._policy = policy or RetryPolicy()
        self.circuit = circuit or CircuitBreaker(name)
        self.name = name
        self._lock = threading.RLock()
        self._inner: Optional[SubprocessClusterBackend] = None
        self._ever_connected = False
        self.last_repoll: Optional[Set[Tuple[str, int]]] = None
        reg = registry()
        self._sensor_reconnects = reg.counter(RECONNECTS_SENSOR)
        self._sensor_transport_errors = reg.counter(TRANSPORT_ERRORS_SENSOR)

    # -- connection management --------------------------------------------

    def inner_backend(self) -> Optional[SubprocessClusterBackend]:
        """The live transport, if any (test/introspection surface)."""
        with self._lock:
            return self._inner

    def _ensure(self) -> SubprocessClusterBackend:
        with self._lock:
            if self._inner is None:
                inner = self._factory()
                # Idempotent re-anchor: what is the cluster still doing?
                self.last_repoll = set(inner.in_progress_reassignments())
                self._inner = inner
                if self._ever_connected:
                    self._sensor_reconnects.inc()
                    LOG.info("admin backend %s reconnected; %d reassignments "
                             "still in progress at the peer", self.name,
                             len(self.last_repoll))
                self._ever_connected = True
            return self._inner

    def _discard(self) -> None:
        with self._lock:
            inner, self._inner = self._inner, None
        if inner is not None:
            try:
                # _poison closes the transport without the shutdown
                # handshake close() performs (the peer outlives us).
                inner._poison("discarded by reconnecting wrapper")
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    # -- call engine -------------------------------------------------------

    def _call(self, method: str, *args, **kwargs):
        def attempt():
            if not self.circuit.allow():
                raise BackendCircuitOpenError(
                    f"admin backend '{self.name}' circuit "
                    f"{self.circuit.state.value}")
            try:
                inner = self._ensure()
            except (BackendTransportError, OSError, ConnectionError) as exc:
                self._sensor_transport_errors.inc()
                self.circuit.record_failure()
                self._discard()
                raise BackendTransportError(
                    f"reconnect to admin backend failed: {exc}") from exc
            try:
                result = getattr(inner, method)(*args, **kwargs)
            except BackendTransportError:
                self._sensor_transport_errors.inc()
                self.circuit.record_failure()
                self._discard()
                raise
            self.circuit.record_success()
            return result

        try:
            return call_with_retry(
                attempt, self._policy,
                retry_on=(BackendTransportError,),
                name=f"backend.{method}")
        except BackendCircuitOpenError:
            raise
        except RetryBudgetExhausted as exc:
            if self.circuit.state is CircuitState.OPEN:
                raise BackendCircuitOpenError(
                    f"admin backend '{self.name}' circuit open "
                    f"after retries: {exc}") from exc
            raise BackendTransportError(str(exc)) from exc

    def probe(self) -> bool:
        """One recovery attempt within the circuit's half-open budget.
        Used by the paused executor; True means the backend answered and
        the circuit re-closed."""
        if not self.circuit.allow():
            return False
        try:
            inner = self._ensure()
            self.last_repoll = set(inner.in_progress_reassignments())
        except (BackendTransportError, OSError, ConnectionError):
            self._sensor_transport_errors.inc()
            self.circuit.record_failure()
            self._discard()
            return False
        self.circuit.record_success()
        return True

    # -- ClusterAdminBackend protocol --------------------------------------

    def execute_replica_reassignments(self, tasks) -> None:
        self._call("execute_replica_reassignments", tasks)

    def execute_logdir_moves(self, tasks) -> None:
        self._call("execute_logdir_moves", tasks)

    def execute_preferred_leader_election(self, tasks) -> None:
        self._call("execute_preferred_leader_election", tasks)

    def in_progress_reassignments(self) -> Set[Tuple[str, int]]:
        return self._call("in_progress_reassignments")

    def finished(self, task) -> bool:
        # raise_transport_errors so the executor can tell "backend down"
        # (pause) apart from "not finished yet" (keep polling).
        return self._call("finished", task, raise_transport_errors=True)

    def offline_logdirs(self):
        return self._call("offline_logdirs")

    def set_throttles(self, *args, **kwargs) -> None:
        self._call("set_throttles", *args, **kwargs)

    def clear_throttles(self) -> None:
        self._call("clear_throttles")

    # -- pass-through conveniences (sim control, tests) --------------------

    def request(self, op: str, **kwargs):
        return self._call("request", op, **kwargs)

    def describe_topics(self):
        return self._call("describe_topics")

    def close(self) -> None:
        with self._lock:
            inner, self._inner = self._inner, None
        if inner is not None:
            try:
                inner.close()
            except Exception:  # noqa: BLE001 — peer may already be gone
                pass
