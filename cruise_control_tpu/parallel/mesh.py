"""Device-mesh construction and sharding rules for the solver.

SURVEY §5: the reference scales a single-JVM solver by threads; the TPU
design scales by sharding the REPLICA axis of the cluster tensors over a
``jax.sharding.Mesh`` and letting XLA insert the collectives (segment-sums
become psum-ed partial sums, top-k a sharded sort + gather) — the
"annotate shardings, let the compiler partition" recipe.  A second mesh axis
parallelizes independent what-if scenarios (the DP analog; BASELINE config
#5's remove-broker batch).

Everything here is shape-rule based: an array whose leading dimension equals
the padded replica count is sharded over ``replica``; a lane-stacked array is
sharded over ``scenario`` (and over ``replica`` in its second dimension when
it stacks per-replica tensors); everything else is replicated.  Broker-axis
aggregates stay replicated — they are O(B) and every phase reads them densely.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SCENARIO_AXIS = "scenario"
REPLICA_AXIS = "replica"


def make_solver_mesh(num_devices: Optional[int] = None,
                     scenario_parallelism: int = 1,
                     devices: Optional[Sequence] = None) -> Mesh:
    """2D mesh (scenario, replica).  ``scenario_parallelism`` devices are
    dedicated to lane-parallel what-ifs; the rest shard the replica axis.
    With the defaults the whole mesh shards replicas."""
    devs = list(devices if devices is not None else jax.devices())
    n = num_devices if num_devices is not None else len(devs)
    if len(devs) < n:
        raise RuntimeError(
            f"make_solver_mesh({n}): only {len(devs)} devices visible — "
            "if this is a virtual-CPU run, a JAX backend was initialized "
            "before utils.hermetic.force_cpu(n) could take effect (call it "
            "first, in a fresh process)")
    devs = devs[:n]
    if n % scenario_parallelism:
        raise ValueError(f"{n} devices not divisible by "
                         f"scenario_parallelism={scenario_parallelism}")
    shape = (scenario_parallelism, n // scenario_parallelism)
    return Mesh(mesh_utils.create_device_mesh(shape, devs),
                axis_names=(SCENARIO_AXIS, REPLICA_AXIS))


def _spec_for(arr, num_replicas_padded: int, lanes: Optional[int]) -> P:
    shape = getattr(arr, "shape", ())
    if lanes is not None and len(shape) >= 1 and shape[0] == lanes:
        if len(shape) >= 2 and shape[1] == num_replicas_padded:
            return P(SCENARIO_AXIS, REPLICA_AXIS)
        return P(SCENARIO_AXIS)
    if len(shape) >= 1 and shape[0] == num_replicas_padded:
        return P(REPLICA_AXIS)
    return P()


def replica_shardings(mesh: Mesh, tree, num_replicas_padded: int):
    """NamedSharding pytree: replica-leading arrays sharded, rest replicated."""
    return jax.tree_util.tree_map(
        lambda a: NamedSharding(mesh, _spec_for(a, num_replicas_padded, None)),
        tree)


def scenario_shardings(mesh: Mesh, tree, num_replicas_padded: int, lanes: int):
    """NamedSharding pytree for lane-stacked arrays (what-if batches)."""
    return jax.tree_util.tree_map(
        lambda a: NamedSharding(mesh, _spec_for(a, num_replicas_padded, lanes)),
        tree)
