"""Multi-host (multi-process) solver execution.

SURVEY §5 "Distributed communication backend": the reference's control
plane speaks Kafka/ZK and scales its solver by threads inside one JVM.
The TPU-native scale-out axis is a *global* ``jax.sharding.Mesh`` spanning
every process of a multi-host deployment: JAX's distributed runtime (gRPC
coordinator — the DCN control channel) assembles all processes' chips into
one mesh, the solver's replica-axis shardings (``parallel/mesh.py``) apply
unchanged, and XLA inserts the cross-host collectives (psum/all-gather)
that ride ICI within a slice and DCN across slices.

Deployment contract (standard SPMD):

- every process runs the same program and calls :func:`propose_multihost`
  with a snapshot of the SAME padded shapes AND the same ``meta``
  (topic/broker identities are resolved process-locally when proposals are
  assembled, so meta must be identical everywhere — it is names and ids,
  not load data, and is not broadcast);
- the COORDINATOR's tensor content wins — (state, placement) arrays are
  broadcast from process 0 before the solve, so workers may pass
  placeholder array content (zeros of the agreed size class);
- every process receives the identical :class:`OptimizerResult` (the solve
  itself is deterministic, and host-side assembly runs on process-local
  copies gathered from the global mesh).

Verified end-to-end by ``tests/test_multihost.py``, which spawns two
coordinated processes on a virtual-CPU mesh and asserts both emit
identical proposals.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from cruise_control_tpu.parallel.mesh import make_solver_mesh


def initialize(coordinator_address: str, num_processes: int,
               process_id: int,
               heartbeat_timeout_s: Optional[int] = None) -> None:
    """Join this process to the distributed runtime.  A repeat call with a
    runtime already up is a no-op (callers may share one bootstrap path);
    ``coordinator_address`` is ``host:port`` of process 0 — reachable over
    the deployment's control network (DCN).

    ``heartbeat_timeout_s`` bounds peer-failure detection: when a process
    dies mid-solve, every SURVIVOR is terminated by the coordination
    service with a fatal "tasks are unhealthy (stopped sending heartbeats)"
    diagnosis after this many seconds, instead of hanging forever in the
    orphaned collective (the SPMD analog of the reference's ZK session
    timeout, ``BrokerFailureDetector.java:64-92``).  None keeps the JAX
    default (100 s); verified by ``tests/test_multihost.py``."""
    try:
        from jax._src.distributed import global_state as _state
    except ImportError:         # private module moved: rely on the
        _state = None           # message-matched RuntimeError below
    if _state is not None and getattr(_state, "client", None) is not None:
        return
    kwargs = {}
    if heartbeat_timeout_s is not None:
        kwargs["heartbeat_timeout_seconds"] = int(heartbeat_timeout_s)
    try:
        jax.distributed.initialize(coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kwargs)
    except RuntimeError as e:
        msg = str(e).lower()
        # jax's wording varies by version: "already initialized" vs
        # "distributed.initialize should only be called once."
        if "already initialized" not in msg and "called once" not in msg:
            raise


def global_solver_mesh(scenario_parallelism: int = 1):
    """Solver mesh over EVERY process's devices (call after
    :func:`initialize`; single-process it equals the local mesh)."""
    return make_solver_mesh(scenario_parallelism=scenario_parallelism)


def broadcast_from_coordinator(tree):
    """Overwrite every process's copy of ``tree`` with process 0's content
    (shapes/dtypes must already agree — the SPMD contract above)."""
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(tree)


def propose_multihost(state, placement, meta, goal_names: Optional[Sequence[str]] = None,
                      constraint=None, scenario_parallelism: int = 1,
                      polish_passes: int = 1):
    """Run one full proposal generation on the global mesh.

    All processes must call this with same-shaped (state, placement) and an
    IDENTICAL meta (see the module contract); process 0's array content is
    broadcast, the goal stack solves sharded over the global replica axis,
    and the identical OptimizerResult is returned everywhere.
    """
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer

    state, placement = broadcast_from_coordinator((state, placement))
    mesh = global_solver_mesh(scenario_parallelism)
    opt = GoalOptimizer(constraint=constraint, goal_names=goal_names,
                        mesh=mesh, polish_passes=polish_passes)
    return opt.optimizations(state, placement, meta)


def batch_remove_scenarios_multihost(state, placement, meta, scenario_sets,
                                     goal_names: Optional[Sequence[str]] = None,
                                     constraint=None,
                                     scenario_parallelism: int = 2,
                                     num_candidates: int = 512):
    """Remove-broker what-if batch on the global mesh — the DP×MP analog
    (scenario axis data-parallel across hosts, replica axis model-parallel
    within; BASELINE config #5 at multi-host scale).

    Same SPMD contract as :func:`propose_multihost`: all processes call with
    same shapes + identical ``meta`` and ``scenario_sets``; process 0's
    tensor content is broadcast; every process returns the identical
    :class:`BatchScenarioResult`.
    """
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer

    state, placement = broadcast_from_coordinator((state, placement))
    mesh = global_solver_mesh(scenario_parallelism)
    opt = GoalOptimizer(constraint=constraint, goal_names=goal_names,
                        mesh=mesh)
    return opt.batch_remove_scenarios(state, placement, meta, scenario_sets,
                                      num_candidates=num_candidates)
