from cruise_control_tpu.parallel.mesh import (
    make_solver_mesh,
    replica_shardings,
    scenario_shardings,
)

__all__ = ["make_solver_mesh", "replica_shardings", "scenario_shardings"]
