from cruise_control_tpu.utils.hermetic import force_cpu, probe_tpu

__all__ = ["force_cpu", "probe_tpu"]
