"""Backend selection guards for tunneled-TPU environments.

The axon PJRT plugin registers itself in every interpreter (sitecustomize),
and JAX backend discovery initializes *every* registered plugin regardless of
``JAX_PLATFORMS`` — so a process that must stay CPU-only (tests, dry runs,
benchmark fallback) has to deregister the factory *and* override the already-
captured config before the first backend lookup.  One canonical copy of that
recipe lives here; ``tests/conftest.py``, ``bench.py`` and
``__graft_entry__.py`` all route through it.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional


def force_cpu(n_devices: Optional[int] = None) -> None:
    """Force JAX onto the host-CPU platform, optionally with ``n_devices``
    virtual devices.  Must run before the first backend initialization; safe
    to call again afterwards (idempotent env/config writes).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        parts = [f for f in flags.split() if
                 "xla_force_host_platform_device_count" not in f]
        parts.append(f"--xla_force_host_platform_device_count={n_devices}")
        os.environ["XLA_FLAGS"] = " ".join(parts)

    import jax
    from jax._src import xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)


def probe_tpu(timeout_s: float = 180.0) -> bool:
    """True iff a non-CPU accelerator backend initializes in a throwaway
    subprocess.  TPU-tunnel init can hang or raise (tunnel down, libtpu
    version skew); probing out-of-process with a timeout keeps the caller
    alive either way."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return False
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax, sys; d = jax.devices(); "
             "sys.exit(0 if d and d[0].platform != 'cpu' else 1)"],
            timeout=timeout_s, capture_output=True)
        return probe.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def enable_persistent_compilation_cache(path: Optional[str] = None) -> bool:
    """Persist compiled XLA executables across processes (content-addressed),
    cutting the multi-minute north-star-scale warmup to cache reads on
    repeat runs.  Safe to call before or after backend init.  The default
    path is per-user (a world-shared /tmp dir would silently no-op for the
    second user).  Returns True when the cache already holds entries
    ("warm") so callers can annotate timing artifacts."""
    import jax

    if path is None:
        # Under the user's own cache root (not a predictable /tmp name a
        # co-tenant could pre-create or poison with attacker-compiled code).
        root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
            os.path.expanduser("~"), ".cache")
        path = os.path.join(root, "cruise_control_tpu", "jax_cache")
        os.makedirs(path, exist_ok=True)
    warm = False
    try:
        warm = os.path.isdir(path) and any(os.scandir(path))
    except OSError:
        pass
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return warm
