"""Shared TLS context construction for the framework's TCP faces.

One canonical copy of the client/server SSL setup used by the metrics bus
(``reporter/transport.py``), the admin driver (``executor/
subprocess_backend.py``) and the admin listener (``executor/
broker_simulator.py``) — a hardening change (minimum version, cipher policy,
hostname rules) lands everywhere at once instead of drifting per copy.
Import-light on purpose: the broker simulator must keep starting in
milliseconds.
"""

from __future__ import annotations

from typing import Optional


def read_secret_file(path: str, what: str = "secret") -> str:
    """One canonical read-and-strip for every shared-secret file the
    framework's faces consume (metrics bus, admin driver, maintenance bus,
    simulator listener) — a missing or empty file fails with a clear error
    instead of a raw traceback at assembly time."""
    try:
        with open(path) as f:
            secret = f.read().strip()
    except OSError as e:
        raise ValueError(f"cannot read {what} file {path!r}: {e}") from e
    if not secret:
        raise ValueError(f"{what} file {path!r} is empty")
    return secret


def client_ssl_context(cafile: Optional[str] = None):
    """TLS context for a framework client connection.

    With ``cafile`` the peer's chain is verified against it (typically the
    peer's own self-signed cert — a pin).  Hostname checking is off either
    way: these private endpoints are addressed by IP:port, not by the
    cert's DNS name, so the CA pin is the trust anchor.  Without ``cafile``
    the link is encrypted but unverified — an explicit opt-in for
    demo/test topologies.
    """
    import ssl

    if cafile:
        ctx = ssl.create_default_context(cafile=cafile)
        ctx.check_hostname = False
    else:
        # Public-API equivalent of the former ssl._create_unverified_context()
        # call: encrypted-but-unverified, built from documented knobs only
        # (the private helper's behavior is not a stable contract across
        # Python releases).
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx


def server_ssl_context(certfile: str, keyfile: Optional[str] = None):
    """TLS context for a framework listener (PEM chain + key, the same
    config shape as the web server's webserver.ssl.* keys)."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile or None)
    return ctx
