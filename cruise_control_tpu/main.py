"""Application bootstrap.

Reference: ``KafkaCruiseControlMain.java:26-41`` / ``KafkaCruiseControlApp``
— parse config, wire the component stack, start the HTTP server.  The
cluster-facing seams (metadata backend, metric sampler, admin backend) are
chosen by config; ``--demo`` wires the in-process fake cluster so the full
service runs standalone (the role of the reference's embedded-broker harness).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import time
from typing import Optional

from cruise_control_tpu.common.exceptions import ConfigError
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.detector.notifier import SelfHealingNotifier
from cruise_control_tpu.executor.backend import FakeClusterBackend
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor.capacity import (
    BrokerCapacityConfigFileResolver,
    FixedBrokerCapacityResolver,
)
from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.metadata import (
    BrokerInfo,
    FakeMetadataBackend,
    MetadataClient,
    PartitionInfo,
)
from cruise_control_tpu.monitor.sample_store import FileSampleStore, NoopSampleStore
from cruise_control_tpu.monitor.sampler import SyntheticWorkloadSampler
from cruise_control_tpu.monitor.task_runner import LoadMonitorTaskRunner
from cruise_control_tpu.servlet.server import CruiseControlApp


def demo_metadata(num_brokers: int = 6, num_partitions: int = 48,
                  rf: int = 2) -> FakeMetadataBackend:
    brokers = [BrokerInfo(i, rack=str(i % 3), host=f"host{i}")
               for i in range(num_brokers)]
    parts = [PartitionInfo("demo-topic", p, leader=p % num_brokers,
                           replicas=tuple((p + i) % num_brokers for i in range(rf)),
                           in_sync=tuple((p + i) % num_brokers for i in range(rf)))
             for p in range(num_partitions)]
    return FakeMetadataBackend(brokers, parts)


def build_app(config: CruiseControlConfig,
              port: Optional[int] = None) -> CruiseControlApp:
    """Wire the full stack against the in-process demo cluster (the role of
    the reference's embedded-broker harness); real deployments substitute
    the metadata/admin/sampler seams."""
    # Install the process-wide compile service from compile.* keys before
    # anything can touch a jitted function, and point JAX's persistent
    # compilation cache at the versioned entry for this goal stack (no-op
    # unless compile.persistent.cache.enabled).
    from cruise_control_tpu.compilesvc import configure as configure_compile
    from cruise_control_tpu.compilesvc.service import goal_stack_hash
    compile_svc = configure_compile(config)
    compile_svc.cache.activate(
        goal_stack_hash=goal_stack_hash(config.goal_names("default.goals")))
    # Observability next: trace.* keys gate the span tracer / audit-log
    # bounds / profile dir before any request or daemon can create spans.
    from cruise_control_tpu.obsvc import configure as configure_obsvc
    configure_obsvc(config)
    from cruise_control_tpu import resilience
    res = resilience.configure(config)
    # Materialize the Fuzz.* counters at boot: nightly fuzz campaigns share
    # this registry, and the sensor-drift guard (scripts/check_sensors.py)
    # requires every documented sensor to exist on a live scrape.
    from cruise_control_tpu.fuzzsvc.runner import fuzz_sensors
    fuzz_sensors()
    backend = demo_metadata()
    metadata_client = MetadataClient(backend,
                                     ttl_ms=config["metadata.max.age.ms"])
    capacity_file = config.get("capacity.config.file")
    resolver_name = str(config.originals.get(
        "broker.capacity.config.resolver.class", ""))
    if "Env" in resolver_name:
        from cruise_control_tpu.monitor.capacity import BrokerEnvCapacityResolver
        resolver = BrokerEnvCapacityResolver()
    elif capacity_file:
        resolver = BrokerCapacityConfigFileResolver(capacity_file)
    else:
        resolver = None
    load_monitor = LoadMonitor(
        metadata_client,
        capacity_resolver=resolver,
        num_windows=config["num.partition.metrics.windows"],
        window_ms=config["partition.metrics.window.ms"],
        min_samples_per_window=config["min.samples.per.partition.metrics.window"],
        num_broker_windows=config["num.broker.metrics.windows"],
        broker_window_ms=config["broker.metrics.window.ms"],
    )
    store_dir = config.get("sample.store.dir")
    mode = config.get("metric.sampler.mode", "synthetic")
    # Reflective plugin overrides (AbstractConfig.getConfiguredInstance):
    # an explicit *.class key is consulted FIRST so the mode-derived default
    # (and its side effects — store directories, reporter pipelines) is
    # never built just to be discarded.  A plugin whose constructor declares
    # a ``config`` parameter receives the full config, mirroring the
    # reference's configure(configs) contract.
    def _plugin(path, **kwargs):
        from cruise_control_tpu.config.config_def import get_configured_instance
        return get_configured_instance(path, config=config, **kwargs)

    sampler_cls = str(config.originals.get("metric.sampler.class", "") or "")
    store_cls = str(config.originals.get("sample.store.class", "") or "")
    if store_cls:
        store = _plugin(store_cls)
    elif store_dir and mode == "reporter":
        # KafkaSampleStore shape: accepted samples ride the same
        # partitioned-log SPI the reporter publishes on, so a restart
        # replays them with the N-consumer reload (monitor/sample_store.py
        # LogSampleStore; reference KafkaSampleStore.java:82-504).
        import os as _os
        from cruise_control_tpu.monitor.sample_store import LogSampleStore
        from cruise_control_tpu.reporter import FileTransport
        store = LogSampleStore(
            FileTransport(_os.path.join(store_dir, "partition-samples")),
            FileTransport(_os.path.join(store_dir, "broker-samples")),
            num_loaders=config["num.metric.fetchers"])
    elif store_dir:
        store = FileSampleStore(store_dir)
    else:
        store = NoopSampleStore()
    reporters = []
    if sampler_cls:
        sampler = _plugin(sampler_cls)
    elif mode == "reporter":
        # Full ingestion edge: per-broker reporter agents → transport →
        # fan-out consuming sampler (the metrics-reporter pipeline).  With a
        # store dir the metrics bus itself is durable too.
        from cruise_control_tpu.monitor.fetcher import ConsumingMetricSampler
        from cruise_control_tpu.reporter import (
            DemoBrokerMetricsSource,
            FileTransport,
            InProcessTransport,
            MetricsReporter,
        )
        offsets_path = None
        if store_dir:
            import os as _os
            transport = FileTransport(_os.path.join(store_dir, "metrics"),
                                      num_partitions=8)
            # Durable bus needs durable consumer positions or every restart
            # re-ingests the whole historical log into the current window.
            offsets_path = _os.path.join(store_dir,
                                         "metrics-consumer-offsets.json")
        else:
            transport = InProcessTransport(num_partitions=8)
        source = DemoBrokerMetricsSource(backend)
        interval = config["metric.sampling.interval.ms"]
        reporters = [MetricsReporter(b.broker_id, source, transport,
                                     reporting_interval_ms=interval / 2)
                     for b in backend.fetch().brokers]
        sampler = ConsumingMetricSampler(
            transport, num_fetchers=config["num.metric.fetchers"],
            offsets_path=offsets_path)
    elif mode == "prometheus":
        from cruise_control_tpu.monitor.prometheus import PrometheusMetricSampler
        sampler = PrometheusMetricSampler(
            endpoint=config["prometheus.server.endpoint"])
    else:
        sampler = SyntheticWorkloadSampler()
    task_runner = LoadMonitorTaskRunner(
        load_monitor, sampler, store,
        sampling_interval_ms=config["metric.sampling.interval.ms"])
    task_runner.reporters = reporters
    bus_port = int(config["metrics.transport.listen.port"])
    if bus_port and mode == "reporter" and not sampler_cls:
        # Network face of the metrics bus: external broker agents publish to
        # this listener with reporter.SocketTransport; the in-process
        # consuming sampler reads the same underlying log.
        from cruise_control_tpu.reporter import TransportServer
        from cruise_control_tpu.utils.netsec import read_secret_file
        secret_file = config["metrics.transport.auth.secret.file"]
        bus_secret = (read_secret_file(secret_file, "metrics bus secret")
                      if secret_file else None)
        bind = config["metrics.transport.listen.address"]
        if bind not in ("127.0.0.1", "localhost", "::1") and not bus_secret:
            logging.getLogger(__name__).warning(
                "metrics bus bound to %s with NO authentication — any peer "
                "that can reach the port can forge metrics or read workload "
                "data; set metrics.transport.auth.secret.file (and TLS)",
                bind)
        bus_server = TransportServer(
            transport, host=bind, port=bus_port, auth_secret=bus_secret,
            ssl_certfile=config["metrics.transport.ssl.certfile"] or None,
            ssl_keyfile=config["metrics.transport.ssl.keyfile"] or None)
        # Started/stopped with the sampling machinery (the task runner
        # start()s and stop()s everything in its reporters list).
        task_runner.reporters = list(reporters) + [bus_server]
    elif bus_port:
        logging.getLogger(__name__).warning(
            "metrics.transport.listen.port=%d ignored: it serves the "
            "reporter-mode transport (metric.sampler.mode=reporter, no "
            "metric.sampler.class override)", bus_port)
    admin_cls = str(config.originals.get("executor.admin.backend.class", "")
                    or "")
    admin_addr = config["executor.admin.backend.address"]
    if admin_cls:
        admin_backend = _plugin(admin_cls)
    elif admin_addr:
        from cruise_control_tpu.executor.subprocess_backend import (
            SocketClusterBackend,
        )
        host, _, aport = admin_addr.rpartition(":")
        if not aport.isdigit():
            raise ConfigError(
                "executor.admin.backend.address must be host:port "
                f"(got {admin_addr!r})")
        from cruise_control_tpu.utils.netsec import read_secret_file
        admin_secret_file = config["executor.admin.backend.auth.secret.file"]
        admin_secret = (read_secret_file(admin_secret_file, "admin backend "
                                         "secret") if admin_secret_file
                        else None)
        ahost = host or "127.0.0.1"
        aport_i = int(aport)
        ssl_en = config["executor.admin.backend.ssl.enable"]
        cafile = config["executor.admin.backend.ssl.cafile"] or None

        def _admin_factory():
            return SocketClusterBackend(
                ahost, aport_i, auth_secret=admin_secret,
                ssl_enable=ssl_en, ssl_cafile=cafile)

        if res.reconnect_enabled:
            # Transport hiccups rebuild the connection under the retry
            # policy instead of poisoning the whole execution; the breaker
            # is published so /metrics and /health can read its state.
            from cruise_control_tpu.resilience import ReconnectingBackend
            circuit = res.circuit("backend")
            resilience.set_backend_circuit(circuit)
            admin_backend = ReconnectingBackend(
                _admin_factory, policy=res.retry_policy(), circuit=circuit)
        else:
            admin_backend = _admin_factory()
    else:
        admin_backend = FakeClusterBackend(backend)
    executor = Executor(admin_backend, config.executor_config())
    if res.journal_path:
        from cruise_control_tpu.executor.journal import ExecutionJournal
        executor.set_journal(ExecutionJournal(res.journal_path))
    notifier_kwargs = dict(
        self_healing_enabled=config["self.healing.enabled"],
        broker_failure_alert_threshold_ms=
            config["broker.failure.alert.threshold.ms"],
        broker_failure_self_healing_threshold_ms=
            config["broker.failure.self.healing.threshold.ms"])
    notifier_cls = str(config.originals.get("anomaly.notifier.class", "") or "")
    webhook_url = config.get("anomaly.notifier.webhook.url")
    if notifier_cls:
        notifier = _plugin(notifier_cls, **notifier_kwargs)
    elif webhook_url:
        from cruise_control_tpu.detector.notifier import WebhookSelfHealingNotifier
        notifier = WebhookSelfHealingNotifier(
            webhook_url, channel=config.get("anomaly.notifier.webhook.channel", ""),
            **notifier_kwargs)
    else:
        notifier = SelfHealingNotifier(**notifier_kwargs)
    slo_detector = None
    if bool(config.get("slo.enabled")):
        # Burn-rate SLO anomalies (obsvc/slo.py) over the sensor history
        # rings; the detector registers under the anomaly manager like every
        # other detector, so violations land in /state and the audit ring.
        from cruise_control_tpu.obsvc.slo import (
            SloViolationDetector,
            evaluator_from_config,
        )
        slo_detector = SloViolationDetector(evaluator_from_config(config))
    from cruise_control_tpu.model.resident import ResidentModelService
    resident = ResidentModelService(
        enabled=bool(config["model.resident.enabled"]),
        max_delta_slots=int(config["model.resident.max.delta.slots"]),
        max_delta_chain=int(config["model.resident.max.delta.chain"]))
    # Segment width for budgeted (anytime) solves: set the process default
    # BEFORE any GoalSolver is built so the shared default_solver() and
    # per-request custom-goal solvers all pick it up.
    from cruise_control_tpu.analyzer.solver import set_default_segment_rounds
    set_default_segment_rounds(int(config["solver.segment.rounds"]))
    # Convex-relaxation fast path (analyzer/relax.py): a process-wide switch
    # like the segment width, set before any optimizer routes a goal, and its
    # Solver.relax.* sensors materialized for the drift guard.
    from cruise_control_tpu.analyzer.relax import relax_sensors, set_relaxation
    set_relaxation(bool(config["solver.relaxation.enabled"]),
                   iterations=int(config["solver.relaxation.iterations"]),
                   candidates=int(config["solver.relaxation.candidates"]),
                   waves=int(config["solver.relaxation.waves"]),
                   tolerance=float(config["solver.relaxation.tolerance"]))
    relax_sensors()
    default_deadline = config.get("solver.default.deadline.ms")
    cc = CruiseControl(
        load_monitor, executor, task_runner=task_runner,
        resident_service=resident,
        constraint=config.balancing_constraint(),
        default_goals=config.goal_names("default.goals"),
        notifier=notifier,
        self_healing_goals=config.goal_names("anomaly.detection.goals"),
        anomaly_detection_interval_s=
            config["anomaly.detection.interval.ms"] / 1000.0,
        proposal_precompute_interval_s=
            config["proposal.expiration.ms"] / 1000.0,
        default_completeness=_default_completeness(config),
        topic_anomaly_target_rf=(
            int(config["topic.anomaly.target.replication.factor"])
            if config.originals.get("topic.anomaly.target.replication.factor")
            else None),
        slo_detector=slo_detector,
        default_deadline_ms=(float(default_deadline)
                             if default_deadline else None),
        shutdown_grace_ms=float(config["solver.shutdown.grace.ms"]),
        slo_preempt_enabled=bool(config.get("slo.preempt.enabled")))
    # The shared solver singleton may predate this build (tests build apps
    # in-process); align its segment width with the config too.
    cc.optimizer.solver.segment_rounds = int(config["solver.segment.rounds"])
    maint_addr = config["maintenance.event.transport.address"]
    maint_dir = config["maintenance.event.transport.dir"]
    if maint_addr or maint_dir:
        # Maintenance plans from the message bus (MaintenanceEventTopicReader
        # analog): a TCP TransportServer peer or a FileTransport directory
        # feeds the MaintenanceEventDetector with committed offsets.
        import os as _os

        from cruise_control_tpu.detector.anomalies import AnomalyType
        from cruise_control_tpu.detector.maintenance_reader import (
            MaintenanceEventReader,
        )
        if maint_addr:
            from cruise_control_tpu.reporter import SocketTransport
            from cruise_control_tpu.utils.netsec import read_secret_file
            m_secret_file = config[
                "maintenance.event.transport.auth.secret.file"]
            m_secret = (read_secret_file(m_secret_file, "maintenance bus "
                                         "secret") if m_secret_file else None)
            maint_transport = SocketTransport(
                maint_addr, auth_secret=m_secret,
                ssl_enable=config["maintenance.event.transport.ssl.enable"],
                ssl_cafile=config["maintenance.event.transport.ssl.cafile"]
                or None)
        else:
            from cruise_control_tpu.reporter import FileTransport
            maint_transport = FileTransport(maint_dir, num_partitions=8)
        offsets_path = config["maintenance.event.offsets.path"] or (
            _os.path.join(maint_dir, "consumer-offsets.json")
            if maint_dir else None)
        cc.maintenance_reader = MaintenanceEventReader(
            maint_transport,
            cc.anomaly_detector.detectors[AnomalyType.MAINTENANCE_EVENT],
            offsets_path=offsets_path,
            expiration_ms=config["maintenance.plan.expiration.ms"])
    ssl_on = config["webserver.ssl.enable"]
    if ssl_on and not config["webserver.ssl.certfile"]:
        hint = ""
        if any(k.startswith("webserver.ssl.keystore")
               for k in config.originals):
            hint = (" (found reference-style webserver.ssl.keystore.* keys: "
                    "this port serves TLS from PEM files — export the "
                    "keystore to PEM and set webserver.ssl.certfile/"
                    "webserver.ssl.keyfile; see docs/CONFIGURATION.md)")
        raise ConfigError(
            "webserver.ssl.enable=true requires webserver.ssl.certfile — "
            "refusing to silently serve the control plane over plain HTTP"
            + hint)
    app = CruiseControlApp(
        cc,
        host=config["webserver.http.address"],
        port=port if port is not None else config["webserver.http.port"],
        two_step_verification=config["two.step.verification.enabled"],
        max_active_user_tasks=config["max.active.user.tasks"],
        security=_security_provider(config),
        ssl_certfile=config["webserver.ssl.certfile"] if ssl_on else None,
        ssl_keyfile=config["webserver.ssl.keyfile"] or None,
        ssl_keyfile_password=config["webserver.ssl.keyfile.password"] or None,
        ui_diskpath=config["webserver.ui.diskpath"] or None,
        ui_urlprefix=config["webserver.ui.urlprefix"],
        api_urlprefix=config["webserver.api.urlprefix"],
        user_task_retention_ms=config["completed.user.task.retention.time.ms"],
        user_task_timeout_ms=(
            float(config.get("servlet.user.task.timeout.ms"))
            if config.get("servlet.user.task.timeout.ms") else None))
    return app


def _default_completeness(config):
    """min.valid.partition.ratio → the baseline completeness gate every
    goal-based operation must clear (LoadMonitor.meetCompletenessRequirements
    compares it to the valid-entity ratio)."""
    ratio = float(config["min.valid.partition.ratio"])
    if ratio <= 0.0:
        return None
    from cruise_control_tpu.monitor.load_monitor import (
        ModelCompletenessRequirements,
    )
    return ModelCompletenessRequirements(
        min_monitored_partitions_percentage=ratio)


def _security_provider(config: CruiseControlConfig):
    """webserver.security.* → provider instance (None when disabled)."""
    if not config["webserver.security.enable"]:
        return None
    from cruise_control_tpu.servlet import security as sec
    kind = config["webserver.security.provider"]
    if kind == "basic":
        return sec.BasicSecurityProvider(
            credentials_file=config["webserver.auth.credentials.file"] or None)
    if kind == "jwt":
        secret = config["webserver.auth.jwt.secret"]
        if not secret:
            raise ValueError("webserver.auth.jwt.secret required for jwt provider")
        return sec.JwtSecurityProvider(secret)
    if kind == "spnego":
        validator_path = config["webserver.auth.spnego.validator.class"]
        if not validator_path:
            raise ValueError(
                "webserver.auth.spnego.validator.class required for the "
                "spnego provider (a GSSAPI-backed ticket validator)")
        creds = config["webserver.auth.credentials.file"]
        if not creds:
            # The reference's SPNEGO provider authorizes via its user store
            # (SpnegoUserStoreAuthorizationService); without one, every
            # authenticated-but-unknown principal would need a default role,
            # and defaulting valid-ticket strangers to USER grants them read
            # access the reference denies with 403.
            raise ValueError(
                "webserver.auth.credentials.file required for the spnego "
                "provider (the user store that maps principals to roles)")
        from cruise_control_tpu.config.config_def import get_configured_instance
        validator = get_configured_instance(validator_path)
        return sec.SpnegoSecurityProvider(
            validator, credentials_file=creds, default_role=None)
    if kind == "trusted_proxy":
        ips = [s.strip() for s in
               config["webserver.auth.trusted.proxy.ips"].split(",") if s.strip()]
        if not ips:
            raise ValueError("webserver.auth.trusted.proxy.ips required for "
                             "the trusted_proxy provider")
        return sec.TrustedProxySecurityProvider(
            ips, user_header=config["webserver.auth.trusted.proxy.user.header"])
    raise ValueError(f"unknown webserver.security.provider {kind!r}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="cruise-control-tpu")
    parser.add_argument("--config", help="properties file", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--demo", action="store_true",
                        help="run against the in-process fake cluster")
    parser.add_argument("--platform", choices=("auto", "tpu", "cpu"),
                        default="auto",
                        help="JAX backend: auto probes the TPU tunnel with a "
                             "timeout and falls back to CPU (a wedged tunnel "
                             "would otherwise hang the first solve); cpu "
                             "forces the host platform; tpu uses the default "
                             "backend unconditionally")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    import os
    if args.platform == "cpu":
        from cruise_control_tpu.utils.hermetic import force_cpu
        force_cpu()
    elif args.platform == "auto":
        from cruise_control_tpu.utils.hermetic import force_cpu, probe_tpu
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # Env-pinned CPU is a deliberate choice, not a TPU outage —
            # no warning, but still deregister the tunnel plugin.
            force_cpu()
        elif not probe_tpu():
            logging.getLogger(__name__).warning(
                "TPU backend unavailable; falling back to CPU")
            force_cpu()
    if not args.demo:
        # The in-process fake cluster is the only bundled cluster backend;
        # real-cluster deployments implement the MetadataBackend /
        # AdminBackend / MetricSampler seams (monitor/metadata.py,
        # executor/backend.py, monitor/sampler.py) and wire them in their
        # own bootstrap.  Refuse to silently serve the demo cluster.
        parser.error("only --demo mode ships a cluster backend; for a real "
                     "cluster, wire your MetadataBackend/AdminBackend/"
                     "MetricSampler implementations via the seams in "
                     "monitor/metadata.py, executor/backend.py and "
                     "monitor/sampler.py")
    config = (CruiseControlConfig.from_properties_file(args.config)
              if args.config else CruiseControlConfig())
    app = build_app(config, port=args.port)
    app.cc.start_up()
    app.start()
    scheme = "https" if app.ssl_enabled else "http"
    print(f"cruise-control-tpu listening on "
          f"{scheme}://{config['webserver.http.address']}:{app.port}"
          " (demo cluster)", flush=True)
    stop = [False]
    signal.signal(signal.SIGTERM, lambda *a: stop.__setitem__(0, True))
    signal.signal(signal.SIGINT, lambda *a: stop.__setitem__(0, True))
    try:
        while not stop[0]:
            time.sleep(0.5)
    finally:
        app.stop()
        app.cc.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
