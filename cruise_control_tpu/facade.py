"""Orchestration façade.

Reference: ``KafkaCruiseControl.java:73-856`` — the single object wiring
LoadMonitor + GoalOptimizer + Executor + AnomalyDetectorManager and exposing
every operation the API layer serves: cluster model queries, proposals,
rebalance, add/remove/demote brokers, fix offline replicas, topic RF change,
pause/resume sampling, self-healing toggles, stop execution.  Operations
follow the GoalBasedOperationRunnable template
(``servlet/handler/async/runnable/GoalBasedOperationRunnable.java:100-211``):
sanity checks → reserve execution → compute on a fresh snapshot → optionally
execute.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu import resilience as _resilience
from cruise_control_tpu.analyzer import (
    BalancingConstraint,
    GoalOptimizer,
    OptimizationOptions,
    OptimizerResult,
)
from cruise_control_tpu.analyzer.budget import SolveBudget
from cruise_control_tpu.common.metrics import registry as _metric_registry
from cruise_control_tpu.analyzer.goals.registry import DEFAULT_GOALS
from cruise_control_tpu.common.exceptions import OngoingExecutionError, UserRequestError
from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyType,
    BrokerFailures,
    DiskFailures,
    GoalViolations,
    MaintenanceEvent,
    MetricAnomaly,
    SloViolationAnomaly,
    TopicAnomaly,
)
from cruise_control_tpu.detector.detectors import (
    BrokerFailureDetector,
    DiskFailureDetector,
    GoalViolationDetector,
    MaintenanceEventDetector,
    MetricAnomalyDetector,
    TopicAnomalyDetector,
)
from cruise_control_tpu.detector.manager import AnomalyDetectorManager
from cruise_control_tpu.detector.notifier import NoopNotifier, SelfHealingNotifier
from cruise_control_tpu.executor.executor import (Executor, ExecutorConfig,
                                                  ExecutorState)
from cruise_control_tpu.model.builder import ClusterModel
from cruise_control_tpu.model.resident import ResidentModelService
from cruise_control_tpu.model.stats import compute_stats
from cruise_control_tpu.monitor.load_monitor import (
    LoadMonitor,
    ModelCompletenessRequirements,
)
from cruise_control_tpu.monitor.task_runner import LoadMonitorTaskRunner
from cruise_control_tpu.obsvc import convergence as _convergence
from cruise_control_tpu.obsvc import oplog as _oplog
from cruise_control_tpu.obsvc.audit import audit_log
from cruise_control_tpu.obsvc.tracer import tracer as _obsvc_tracer

LOG = logging.getLogger(__name__)

# Legacy snapshot padding size-class floors; the compile service's shape-
# bucket policy (compilesvc.buckets.ShapeBucketPolicy) keeps them as its
# smallest buckets, so pre-bucketing shapes stay canonical.
PAD_R, PAD_B = 64, 8


class _SloPreemptDetector:
    """Wraps the SLO burn-rate detector when ``slo.preempt.enabled`` is on:
    solve-time violations come out *fixable* so the notifier routes them to
    the facade's fixer (which preempts the offending solve) instead of
    IGNOREing them as audit-only."""

    def __init__(self, inner):
        self.inner = inner

    def detect(self):
        anomalies = self.inner.detect()
        for a in anomalies:
            if getattr(a, "objective", "") == "solve-time":
                a.fixable = True
        return anomalies

    def __getattr__(self, name):
        return getattr(self.inner, name)


@dataclass
class OperationResult:
    """What every operation returns to the API layer."""

    optimizer_result: Optional[OptimizerResult]
    dryrun: bool
    executed: bool
    info: str = ""
    # True when the solve fell back to the CPU backend after a device
    # failure — the answer is correct but slower-path; operators alert on it.
    degraded: bool = False
    # True when the solve was preempted (deadline / cancel / shutdown / SLO)
    # and returned the best placement found so far instead of converging.
    partial: bool = False
    # Advisory (never blocks the request): the model fingerprint violated a
    # configured anomaly.model.* staleness threshold at solve time.
    model_stale: bool = False

    def to_dict(self, explain: bool = False) -> Dict:
        d = {"dryrun": self.dryrun, "executed": self.executed, "info": self.info}
        if self.degraded:
            d["degraded"] = True
        if self.partial:
            d["partial"] = True
        if self.model_stale:
            d["modelStale"] = True
        if self.optimizer_result is not None:
            d["result"] = self.optimizer_result.to_dict(explain=explain)
        return d


class CruiseControl:
    """The façade. All cross-component calls route through here."""

    def __init__(
        self,
        load_monitor: LoadMonitor,
        executor: Executor,
        task_runner: Optional[LoadMonitorTaskRunner] = None,
        constraint: Optional[BalancingConstraint] = None,
        default_goals: Optional[Sequence[str]] = None,
        notifier=None,
        self_healing_goals: Optional[Sequence[str]] = None,
        anomaly_detection_interval_s: float = 300.0,
        proposal_precompute_interval_s: float = 0.0,
        default_completeness: Optional[ModelCompletenessRequirements] = None,
        topic_anomaly_target_rf: Optional[int] = None,
        resident_service: Optional[ResidentModelService] = None,
        slo_detector=None,
        default_deadline_ms: Optional[float] = None,
        shutdown_grace_ms: float = 5000.0,
        slo_preempt_enabled: bool = False,
    ):
        self.load_monitor = load_monitor
        self.executor = executor
        # Device-resident cluster model: frozen tensors stay on-device across
        # requests and the monitor's changes arrive as scatter-applied deltas
        # instead of full re-freezes (perf_opt: resident model).
        self.resident = resident_service or ResidentModelService()
        # Offline-logdir key of the last resident build: a flip means disk
        # deaths changed, which the delta journal does not express — rebuild.
        self._offline_key: Optional[tuple] = None
        # (model_generation, Placement) of the last default-goal full solve;
        # seeds what-if lanes so they polish a near-balanced placement
        # instead of re-deriving it from scratch.
        self._base_solution: Optional[tuple] = None
        self.task_runner = task_runner
        # Baseline completeness gate for every goal-based operation
        # (min.valid.partition.ratio; requests may pass stricter ones).
        self.default_completeness = default_completeness
        self.constraint = constraint or BalancingConstraint()
        self.default_goals = list(default_goals or DEFAULT_GOALS)
        self.optimizer = GoalOptimizer(constraint=self.constraint,
                                       goal_names=self.default_goals)
        self.notifier = notifier or SelfHealingNotifier()
        self._lock = threading.RLock()
        if task_runner is not None:
            executor.set_sampling_hooks(
                lambda: task_runner.pause_sampling("executor"),
                lambda: task_runner.resume_sampling("executor"))
        self.topic_anomaly_target_rf = topic_anomaly_target_rf
        # Deadline/cancellation plumbing (SolveBudget): every operation may
        # carry a budget; the registry lets /cancel_user_task, the SLO
        # escalation and shutdown's grace-drain reach in-flight solves.
        self.default_deadline_ms = default_deadline_ms
        self.shutdown_grace_ms = shutdown_grace_ms
        self.slo_preempt_enabled = slo_preempt_enabled
        self._active_budgets: Set[SolveBudget] = set()
        self._budget_lock = threading.Lock()
        # Optional SLO burn-rate detector (obsvc/slo.py), assembled by the
        # bootstrap from slo.* keys; rides the same manager as the rest.
        self.slo_detector = slo_detector
        self.anomaly_detector = self._build_anomaly_detector(
            self_healing_goals, anomaly_detection_interval_s)
        # Background proposal precompute (GoalOptimizer.java:137-188): a
        # daemon refreshing the generation-keyed proposal cache whenever the
        # model generation moves, so GET /proposals is a cache hit instead of
        # paying cold-solve latency.  0 disables (tests/offline use).
        self._precompute_interval_s = proposal_precompute_interval_s
        self._precompute_stop = threading.Event()
        self._precompute_thread: Optional[threading.Thread] = None
        self._precomputed_generation = None
        # Optional bus consumer feeding the MaintenanceEventDetector
        # (MaintenanceEventTopicReader analog) — assembled by the bootstrap
        # when maintenance.event.transport.* is configured; owned here so its
        # lifecycle rides start_up/shutdown like the reference's reader rides
        # the AnomalyDetectorManager's.
        self.maintenance_reader = None
        # Background compile warmup (compilesvc): AOT-compiles the configured
        # goal stack's bucket set right after start_up so the first operator
        # request never pays cold-compile latency.  Built lazily in start_up
        # only when the compile service has warmup enabled.
        self.warmup_daemon = None
        # Wall-clock of the last solve that needed the CPU fallback; cleared
        # by the next clean solve.  Feeds the /health device probe.
        self._solver_degraded_at: Optional[float] = None
        self._journal_recovery_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start_up(self) -> None:
        """KafkaCruiseControl.startUp :201-232."""
        if self.task_runner is not None:
            self.task_runner.start()
        self.anomaly_detector.start_detection()
        if self.maintenance_reader is not None:
            self.maintenance_reader.start()
        if self._precompute_interval_s > 0:
            # Non-daemon: a daemon thread killed inside native XLA code at
            # interpreter exit aborts the process; a non-daemon thread makes
            # exit wait for the in-flight solve (bounded), then stop cleanly.
            # The loop also watches main-thread liveness (atexit is no help:
            # CPython joins non-daemon threads BEFORE atexit callbacks run),
            # so exit paths that never call shutdown() cannot hang the
            # interpreter for more than ~a second past the in-flight solve.
            self._precompute_thread = threading.Thread(
                target=self._precompute_loop, name="proposal-precompute",
                daemon=False)
            self._precompute_thread.start()
        from cruise_control_tpu.compilesvc import compile_service
        if compile_service().warmup_enabled:
            self.warmup_daemon = self._build_warmup_daemon()
            self.warmup_daemon.start()
        if getattr(self.executor, "journal", None) is not None:
            # Reconcile the crash journal off the startup path: the admin
            # peer may itself be down, and /health reports the in-progress
            # recovery as degraded until it lands.
            timeout_s = (_resilience.settings().journal_adoption_timeout_ms
                         / 1000.0)
            self._journal_recovery_thread = threading.Thread(
                target=self._recover_journal, args=(timeout_s,),
                name="journal-recovery", daemon=True)
            self._journal_recovery_thread.start()

    def _recover_journal(self, timeout_s: float) -> None:
        try:
            self.executor.recover_from_journal(adoption_timeout_s=timeout_s)
        except Exception:  # noqa: BLE001 — recovery must never kill startup
            LOG.exception("journal recovery failed")

    def shutdown(self) -> None:
        # Grace-drain first: cancel every in-flight solve and give it one
        # grace window to unwind through its next segment boundary, so the
        # teardown below never yanks components out from under a dispatch.
        self._drain_solves(self.shutdown_grace_ms)
        if self.warmup_daemon is not None:
            self.warmup_daemon.stop()
        if self.maintenance_reader is not None:
            self.maintenance_reader.stop()
        self._precompute_stop.set()
        if self._precompute_thread is not None:
            self._precompute_thread.join(timeout=5.0)
            if self._precompute_thread.is_alive():
                LOG.warning("proposal precompute still solving; it will stop "
                            "after the in-flight solve completes")
        self.anomaly_detector.shutdown()
        if self.task_runner is not None:
            self.task_runner.shutdown()
        # A self-healing fix may still be executing (the detector tick that
        # started it is fire-and-forget); stop it, or its paused-backend
        # probe loop outlives the app and keeps failing against a peer that
        # is being torn down with us.
        self.executor.user_triggered_stop_execution(user=False)
        # Network-facing admin drivers (SocketClusterBackend) hold a live
        # connection; close it so embedders cycling apps don't leak sockets.
        close = getattr(self.executor.backend, "close", None)
        if close is not None:
            close()
        # Un-publish this app's breaker: the process-global circuit outlives
        # the app, and health() in a later-built app (tests rebuild apps
        # in-process) would otherwise read a dead backend's OPEN state and
        # shed its proposal traffic.
        circuit = getattr(self.executor.backend, "circuit", None)
        if circuit is not None and circuit is _resilience.backend_circuit():
            _resilience.set_backend_circuit(None)

    def _interruptible_wait(self) -> bool:
        """True = stop.  Waits the precompute interval in <=1 s slices,
        stopping early when the stop event fires or the main thread is gone
        (interpreter finalization joins non-daemon threads before atexit, so
        liveness polling is the only reliable unattended-exit signal)."""
        remaining = self._precompute_interval_s
        while remaining > 0:
            slice_s = min(1.0, remaining)
            if self._precompute_stop.wait(slice_s):
                return True
            if not threading.main_thread().is_alive():
                return True
            remaining -= slice_s
        return False

    def _precompute_loop(self) -> None:
        """ProposalCandidateComputer analog (GoalOptimizer.java:545-592): on
        each tick, if the model generation advanced and completeness holds,
        run the default-goal dryrun solve so the cache is warm for readers."""
        while not self._interruptible_wait():
            try:
                generation = self.load_monitor.model_generation
                if generation == self._precomputed_generation:
                    continue
                if not self.load_monitor.meet_completeness_requirements(
                        self.default_completeness
                        or ModelCompletenessRequirements()):
                    # Too early for the proposal solve, but not for the model:
                    # fold the monitor's pending journal into the resident
                    # entry now, on the daemon's clock, so the first request
                    # after the window completes starts from current device
                    # tensors instead of paying the accumulated delta (or an
                    # overflow-forced full freeze).
                    self._pre_apply_resident_deltas(generation)
                    continue
                # Root span: the daemon thread has no request context, so
                # each precompute tick is its own trace in the ring.
                with _obsvc_tracer().span("precompute", generation=generation):
                    self.proposals()
                self._precomputed_generation = generation
            except Exception as e:          # noqa: BLE001 — keep the daemon up
                LOG.warning("proposal precompute failed: %s", e)

    def _pre_apply_resident_deltas(self, generation) -> None:
        """Resident-model follow-on (docs/RESIDENT.md): a precompute tick
        that cannot run the full solve yet still advances the device model.
        The snapshot path applies whatever delta the journal holds (or
        no-ops when nothing is pending); the pin is released immediately —
        nothing solves here, the point is moving the scatter off the first
        request's critical path."""
        if not self.resident.enabled:
            return
        try:
            with _obsvc_tracer().span("precompute.delta_preapply",
                                      generation=generation):
                self._resident_snapshot()
        except Exception as e:   # noqa: BLE001 — monitor may still be booting
            LOG.debug("resident delta pre-apply skipped: %s", e)
        else:
            self.resident.release()

    # ------------------------------------------------------- compile warmup

    def _freeze_bucketed(self, builder):
        """Freeze a model builder at the compile service's canonical shape
        buckets (geometric over the PAD_R/PAD_B floors), so every snapshot
        of a similar-sized cluster lands on an already-compiled shape."""
        from cruise_control_tpu.compilesvc import compile_service
        n_replicas, n_brokers = builder.counts()
        pad_r, pad_b = compile_service().pad_targets(n_replicas, n_brokers)
        return builder.freeze(pad_replicas_to=pad_r, pad_brokers_to=pad_b)

    def _resident_snapshot(self, requirements=None):
        """Device tensors for the monitor's current model via the resident
        service: the monitor diffs its long-lived builder, the service turns
        the journal into a scatter-applied delta, and only bucket changes /
        inexpressible edits pay a full freeze.  Returned tensors are PINNED —
        callers must :meth:`ResidentModelService.release` after the solve."""
        from cruise_control_tpu.compilesvc import compile_service

        def build() -> ClusterModel:
            # Runs under the resident service lock, so the monitor diff and
            # the delta collection cannot interleave with another request.
            try:
                offline = self._offline_logdirs() or {}
            except Exception as e:   # noqa: BLE001 — network seam
                LOG.warning("offline-logdir query failed (%s); building the "
                            "model without dead-disk enrichment", e)
                offline = {}
            key = tuple(sorted((int(b), tuple(sorted(int(d) for d in ds)))
                               for b, ds in offline.items()))
            if key != self._offline_key:
                # A recovered disk has no mark_disk_alive analog, so any
                # flip in the offline set forces a rebuild + full freeze
                # rather than trying to express it as a delta.
                self.load_monitor.reset_resident_builder()
                self.resident.invalidate("offline-logdirs-changed")
                self._offline_key = key
            builder, fresh = self.load_monitor.resident_model_builder(
                requirements=requirements)
            if fresh:
                for b_id, disks in offline.items():
                    for d in disks:
                        try:
                            builder.mark_disk_dead(int(b_id), int(d))
                        except (KeyError, IndexError):
                            pass
            return builder

        return self.resident.snapshot(build, compile_service().pad_targets,
                                      pin=True)

    def _build_warmup_daemon(self):
        """Warm tasks run REAL solves at the bucket shapes: AOT
        ``lower().compile()`` would skip jit's in-process dispatch cache, so
        the first operator request would retrace anyway.  Task keys make
        re-warming idempotent; failures (e.g. load monitor not yet complete)
        are logged by the daemon and never fatal."""
        from cruise_control_tpu.compilesvc import WarmupDaemon, compile_service

        svc = compile_service()
        daemon = WarmupDaemon()

        def wait_model_ready(timeout_s: float = 600.0) -> None:
            # start_up launches the warmer before the monitor has completed
            # its first aggregation window; a warm task solving immediately
            # would fail on "0 completed windows".  Poll completeness (and
            # the daemon's abort probe, so shutdown is never blocked) until
            # a model can actually be built.
            req = (self.default_completeness
                   or ModelCompletenessRequirements())
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if daemon.should_abort():
                    raise RuntimeError("warmup aborted before model ready")
                try:
                    if self.load_monitor.meet_completeness_requirements(req):
                        return
                except Exception:   # noqa: BLE001 — monitor still booting
                    pass
                time.sleep(0.25)
            raise TimeoutError(
                f"load monitor produced no complete window in {timeout_s:.0f}s")

        def _warm_snapshot():
            """Bucketed tensors for warm tasks — through the resident service
            when enabled, so the warmup ALSO seeds the resident entry and the
            first operator request starts on the delta path."""
            if self.resident.enabled:
                return self._resident_snapshot(), True
            builder = self.load_monitor.cluster_model_builder()
            return self._freeze_bucketed(builder), False

        def warm_proposals():
            wait_model_ready()
            self.proposals()

        def _warm_whatif(width: int):
            wait_model_ready()
            (state, placement, meta), pinned = _warm_snapshot()
            try:
                first = [int(meta.broker_ids[0])]
                self.optimizer.batch_remove_scenarios(
                    state, placement, meta,
                    [list(first) for _ in range(width)])
            finally:
                if pinned:
                    self.resident.release()

        def warm_delta():
            # Compile the delta-apply scatter executables at the model's
            # bucket so the first steady-state delta never pays a trace.
            wait_model_ready()
            if not self.resident.enabled:
                return
            (state, placement, meta), pinned = _warm_snapshot()
            try:
                self.resident.warm_scatter(
                    int(state.leader_load.shape[0]),
                    int(state.capacity.shape[0]),
                    int(state.disk_capacity.shape[1]))
            finally:
                if pinned:
                    self.resident.release()

        def warm_relax():
            # Convex-relaxation fast path: compile the fractional+rounding
            # executable per eligible goal at the bucket shape, with the same
            # priority-ordered priors chain the optimizer will use.  No-op
            # (and no relax cache keys) when the fast path is off.
            from cruise_control_tpu.analyzer import relax as _relax
            if not _relax.relaxation_enabled():
                return
            import jax.numpy as jnp

            from cruise_control_tpu.analyzer.context import build_context
            from cruise_control_tpu.analyzer.goals.registry import (
                get_goals_by_priority,
            )
            wait_model_ready()
            (state, placement, meta), pinned = _warm_snapshot()
            try:
                solver = self.optimizer.solver
                gctx = build_context(state, placement, meta,
                                     self.optimizer.constraint,
                                     OptimizationOptions())
                gctx, placement = solver.shard_inputs(gctx, placement)
                agg = solver.aggregates(gctx, placement)
                iters, k_cfg, waves, _tol = _relax.relaxation_params()
                k = min(k_cfg, state.num_replicas_padded)
                priors = []
                for goal in get_goals_by_priority(self.default_goals):
                    if daemon.should_abort():
                        return
                    if getattr(goal, "relax_eligible", False):
                        fn = _relax._relax_fn(solver, goal, tuple(priors),
                                              state.num_replicas_padded, k,
                                              waves)
                        out = fn(gctx, placement, agg, jnp.int32(iters))
                        out[0].broker.block_until_ready()
                    priors.append(goal)
            finally:
                if pinned:
                    self.resident.release()

        daemon.add_task(("proposals", tuple(self.default_goals)),
                        warm_proposals)
        # The lane ladder is a LIST: each width warms its own vmapped
        # executable, so chunked wide batches find every block width hot.
        for width in svc.warmup_lane_ladder:
            w = max(1, int(width))
            daemon.add_task(("whatif", tuple(self.default_goals), w),
                            lambda w=w: _warm_whatif(w))
        daemon.add_task(("warm_delta", tuple(self.default_goals)), warm_delta)
        daemon.add_task(("relax", tuple(self.default_goals)), warm_relax)
        return daemon

    def _offline_logdirs(self):
        """Disk-failure source: the executor's cluster backend answers the
        describeLogDirs-shaped query (DiskFailureDetector.java:1-118);
        backends without the query report no failures rather than breaking
        detection wholesale."""
        backend = getattr(self.executor, "backend", None)
        query = getattr(backend, "offline_logdirs", None)
        if query is None:
            return {}
        return query()

    def _build_anomaly_detector(self, self_healing_goals,
                                interval_s) -> AnomalyDetectorManager:
        detectors = {
            AnomalyType.GOAL_VIOLATION: GoalViolationDetector(
                self.load_monitor, goal_names=self_healing_goals),
            AnomalyType.BROKER_FAILURE: BrokerFailureDetector(
                self.load_monitor.metadata_client),
            AnomalyType.DISK_FAILURE: DiskFailureDetector(
                self._offline_logdirs),
            AnomalyType.METRIC_ANOMALY: MetricAnomalyDetector(
                self.load_monitor.broker_aggregator),
            AnomalyType.TOPIC_ANOMALY: TopicAnomalyDetector(
                self.load_monitor.metadata_client,
                target_replication_factor=self.topic_anomaly_target_rf),
            AnomalyType.MAINTENANCE_EVENT: MaintenanceEventDetector(),
        }
        if self.slo_detector is not None:
            slo = self.slo_detector
            if self.slo_preempt_enabled:
                # Escalation: a burning solve-time SLO becomes FIXABLE, and
                # the fix is "preempt the offending solve" (the notifier
                # IGNOREs unfixable anomalies before the fixer ever runs).
                slo = _SloPreemptDetector(slo)
            detectors[AnomalyType.SLO_VIOLATION] = slo
        return AnomalyDetectorManager(
            detectors, notifier=self.notifier, fixer=self._fix_anomaly,
            detection_interval_s=interval_s)

    # ---------------------------------------------------------- model views

    def cluster_model_snapshot(self, allow_capacity_estimation: bool = True):
        from cruise_control_tpu.compilesvc import compile_service
        return self.load_monitor.cluster_model(
            allow_capacity_estimation=allow_capacity_estimation,
            pad_fn=compile_service().pad_targets)

    def broker_stats(self) -> Dict:
        """GET /load (KafkaCruiseControl.clusterModel + brokerStats)."""
        state, placement, meta = self.cluster_model_snapshot()
        stats = compute_stats(state, placement, self.constraint.balance_threshold)
        return stats.to_dict()

    def partition_load(self, max_entries: int = 100) -> List[Dict]:
        """GET /partition_load: per-partition loads sorted by utilization."""
        import numpy as np

        from cruise_control_tpu.model import ops
        state, placement, meta = self.cluster_model_snapshot()
        load = np.asarray(ops.effective_load(state, placement))[:meta.num_replicas]
        lead = np.asarray(placement.is_leader)[:meta.num_replicas]
        part = np.asarray(state.partition)[:meta.num_replicas]
        out = []
        leaders = np.nonzero(lead)[0]
        order = leaders[np.argsort(-load[leaders].sum(axis=1))]
        for r in order[:max_entries]:
            t_idx, p_num = meta.partitions[part[r]]
            out.append({
                "topic": meta.topics[t_idx], "partition": int(p_num),
                "cpu": float(load[r][0]), "networkInbound": float(load[r][1]),
                "networkOutbound": float(load[r][2]), "disk": float(load[r][3]),
            })
        return out

    # ------------------------------------------------------ solve budgets

    def _make_budget(self, deadline_ms, cancel_event) -> Optional[SolveBudget]:
        """Build the operation's :class:`SolveBudget`, or ``None`` when no
        deadline (request param or ``solver.default.deadline.ms``) and no
        cancellation token apply — the ``None`` path is byte-identical to
        the pre-budget solver."""
        deadline = (deadline_ms if deadline_ms is not None
                    else self.default_deadline_ms)
        if (deadline is None or deadline <= 0) and cancel_event is None:
            return None
        return SolveBudget(deadline, cancel_event=cancel_event)

    def _register_budget(self, budget: Optional[SolveBudget]) -> None:
        if budget is None:
            return
        with self._budget_lock:
            self._active_budgets.add(budget)

    def _unregister_budget(self, budget: Optional[SolveBudget]) -> None:
        if budget is None:
            return
        with self._budget_lock:
            self._active_budgets.discard(budget)

    def active_solves(self) -> int:
        """Number of budget-carrying solves currently in flight."""
        with self._budget_lock:
            return len(self._active_budgets)

    def cancel_active_solves(self, reason: str = "cancelled") -> int:
        """Cancel every in-flight budget-carrying solve; returns how many
        tokens were signalled.  Each solve stops at its next segment / goal
        boundary and returns its current placement tagged partial."""
        with self._budget_lock:
            budgets = list(self._active_budgets)
        for b in budgets:
            b.cancel(reason)
        if budgets:
            LOG.info("cancelled %d in-flight solve(s): %s",
                     len(budgets), reason)
        return len(budgets)

    def _drain_solves(self, grace_ms: float) -> bool:
        """Grace-drain: cancel in-flight solves and wait (bounded) for them
        to unwind through their segment boundaries.  True = drained."""
        if not self.cancel_active_solves("shutdown"):
            return True
        deadline = time.monotonic() + max(0.0, grace_ms) / 1000.0
        while time.monotonic() < deadline:
            with self._budget_lock:
                if not self._active_budgets:
                    return True
            time.sleep(0.05)
        with self._budget_lock:
            leftover = len(self._active_budgets)
        if leftover:
            LOG.warning("%d solve(s) still draining past the %.0fms grace "
                        "budget", leftover, grace_ms)
        return leftover == 0

    # ------------------------------------------------------------ operations

    def _run_operation(
        self,
        goals: Optional[Sequence[str]],
        options: OptimizationOptions,
        dryrun: bool,
        model_mutator=None,
        requirements: Optional[ModelCompletenessRequirements] = None,
        use_cached: bool = False,
        deadline_ms: Optional[float] = None,
        cancel_event: Optional[threading.Event] = None,
    ) -> OperationResult:
        tr = _obsvc_tracer()
        if not tr.enabled:
            return self._run_operation_impl(goals, options, dryrun,
                                            model_mutator, requirements,
                                            use_cached, deadline_ms,
                                            cancel_event)
        with tr.span("operation", dryrun=dryrun,
                     num_goals=len(goals or self.default_goals)):
            return self._run_operation_impl(goals, options, dryrun,
                                            model_mutator, requirements,
                                            use_cached, deadline_ms,
                                            cancel_event)

    def _run_operation_impl(
        self,
        goals: Optional[Sequence[str]],
        options: OptimizationOptions,
        dryrun: bool,
        model_mutator=None,
        requirements: Optional[ModelCompletenessRequirements] = None,
        use_cached: bool = False,
        deadline_ms: Optional[float] = None,
        cancel_event: Optional[threading.Event] = None,
    ) -> OperationResult:
        goals = list(goals or self.default_goals)
        if self.default_completeness is not None:
            # The operator's min.valid.partition.ratio is a FLOOR: explicit
            # per-request requirements may only strengthen it.
            requirements = (self.default_completeness if requirements is None
                            else requirements.stronger(
                                self.default_completeness))
        budget = self._make_budget(deadline_ms, cancel_event)
        self._register_budget(budget)
        if not dryrun:
            self.executor.set_generating_proposals_for_execution(True)
        pinned = False
        try:
            # Mutator-free operations ride the resident model: on-device
            # tensors updated by scatter-applied monitor deltas.  Mutators
            # (add/remove/demote, RF change) edit a THROWAWAY builder, so
            # they keep the classic build-enrich-freeze path.
            if model_mutator is None and self.resident.enabled:
                state, placement, meta = self._resident_snapshot(requirements)
                pinned = True
            else:
                state, placement, meta = self._freeze_bucketed(
                    self._build_enriched(requirements, model_mutator))

            def refreeze():
                # The tensors (and the resident entry's buffers) may live on
                # the failed device; drop everything device-side and rebuild
                # from the monitor inside the CPU fallback context.
                self.resident.invalidate("device-failover")
                self.load_monitor.reset_resident_builder()
                return self._freeze_bucketed(
                    self._build_enriched(requirements, model_mutator))

            optimizer = (self.optimizer if goals == self.default_goals
                         else GoalOptimizer(constraint=self.constraint,
                                            goal_names=goals))
            generation = (self.load_monitor.model_generation
                          if use_cached and model_mutator is None else None)
            result, degraded = self._solve_with_failover(
                optimizer, state, placement, meta, options, generation,
                refreeze=refreeze, budget=budget)
            if (model_mutator is None and not degraded and not result.partial
                    and goals == self.default_goals
                    and result.final_placement is not None):
                # Remember the balanced answer: what-if lanes warm-start
                # from it while the generation (and thus the shape) holds.
                # A partial answer never seeds warm starts — it would bake
                # an unconverged placement into every later lane.
                self._base_solution = (self.load_monitor.model_generation,
                                       result.final_placement)
            executed = False
            # A deadline-preempted answer is anytime-safe (every round's
            # placement is feasible and hard-goal-safe), so it executes.
            # Cancellation (user / SLO preempt / shutdown) means "stop",
            # not "act on what you have" — those never execute.
            may_execute = (not result.partial
                           or result.preempt_reason == "deadline")
            if not dryrun and result.proposals and may_execute:
                self.executor.execute_proposals(result.proposals, wait=False)
                executed = True
            elif not dryrun:
                self.executor.set_generating_proposals_for_execution(False)
            # Advisory staleness tag: the verdict gates self-healing, but
            # user-requested proposal traffic still serves — flagged so the
            # caller knows the data quality behind the answer.
            from cruise_control_tpu.obsvc.fidelity import fidelity as _fidelity
            stale = _fidelity().staleness_reason() is not None
            return OperationResult(result, dryrun=dryrun, executed=executed,
                                   degraded=degraded,
                                   partial=bool(result.partial),
                                   model_stale=stale)
        except Exception:
            if not dryrun:
                try:
                    self.executor.set_generating_proposals_for_execution(False)
                except OngoingExecutionError:
                    pass
            raise
        finally:
            self._unregister_budget(budget)
            if pinned:
                self.resident.release()

    def _build_enriched(self, requirements=None, model_mutator=None
                        ) -> ClusterModel:
        """Fresh builder + dead-logdir enrichment + optional mutator.

        Dead logdirs are the ADMIN backend's knowledge (AdminClient
        describeLogDirs in the reference), not the metadata sampler's:
        fold them into the model so their replicas solve as offline —
        without this, fix_offline_replicas would "fix" a healthy model
        and never evacuate the failed disk.  Logdir ids map to the
        broker's disk indices (the JBOD contract the capacity resolver
        uses).  A transient admin-socket failure must not take down
        every optimization operation (the query is an enrichment, and
        the anomaly cycle retries) — log it and build without."""
        builder = self.load_monitor.cluster_model_builder(
            requirements=requirements)
        try:
            offline = self._offline_logdirs() or {}
        except Exception as e:   # noqa: BLE001 — network seam
            LOG.warning("offline-logdir query failed (%s); building the "
                        "model without dead-disk enrichment", e)
            offline = {}
        for b_id, disks in offline.items():
            for d in disks:
                try:
                    builder.mark_disk_dead(int(b_id), int(d))
                except (KeyError, IndexError):
                    # Broker/disk absent from current metadata (e.g.
                    # already decommissioned) — nothing to mark.
                    pass
        if model_mutator is not None:
            model_mutator(builder)
        return builder

    def _solve_with_failover(self, optimizer, state, placement, meta,
                             options, generation, *, refreeze=None,
                             budget=None):
        """Dispatch the solve; on device loss, fail over to the CPU backend.

        The accelerator can die mid-flight (preemption, driver crash, XLA
        runtime abort).  A rebalance answer computed on CPU is identical —
        just slower — so catch device-loss-shaped errors at this one seam,
        re-run under ``jax.default_device(cpu)``, and tag the response +
        trace span ``degraded`` so operators see the path taken.  The cache
        generation is dropped for the retry: the cached entry may itself be
        poisoned by the dead device.

        ``refreeze`` (when given) rebuilds (state, placement, meta) inside
        the fallback context: the originals — and the resident model cache
        they may have come from — live on the failed device, so the retry
        must not read them.  The callable is responsible for invalidating
        the resident entry so later requests full-freeze on a live backend.
        """
        try:
            result = optimizer.optimizations(
                state, placement, meta, options=options,
                model_generation=generation, budget=budget)
            self._solver_degraded_at = None
            return result, False
        except Exception as exc:  # noqa: BLE001 — classified below
            if not _resilience.is_device_failure(exc):
                raise
            _metric_registry().counter(
                _resilience.SOLVER_FAILOVER_SENSOR).inc()
            LOG.error("accelerator failure during solve (%s: %s); "
                      "retrying on CPU backend", type(exc).__name__, exc)
        span = _obsvc_tracer().current()
        if span is not None:
            span.set("degraded", True)
        with _resilience.cpu_fallback():
            if refreeze is not None:
                state, placement, meta = refreeze()
            result = optimizer.optimizations(
                state, placement, meta, options=options,
                model_generation=None, budget=budget)
        self._solver_degraded_at = time.time()
        return result, True

    def proposals(self, goals: Optional[Sequence[str]] = None,
                  options: Optional[OptimizationOptions] = None,
                  deadline_ms: Optional[float] = None,
                  cancel_event: Optional[threading.Event] = None
                  ) -> OperationResult:
        """GET /proposals — always dryrun, uses the proposal cache."""
        return self._run_operation(goals, options or OptimizationOptions(),
                                   dryrun=True, use_cached=True,
                                   deadline_ms=deadline_ms,
                                   cancel_event=cancel_event)

    def rebalance(self, goals: Optional[Sequence[str]] = None,
                  dryrun: bool = True,
                  options: Optional[OptimizationOptions] = None,
                  deadline_ms: Optional[float] = None,
                  cancel_event: Optional[threading.Event] = None
                  ) -> OperationResult:
        """POST /rebalance (RebalanceRunnable)."""
        return self._run_operation(goals, options or OptimizationOptions(),
                                   dryrun=dryrun, deadline_ms=deadline_ms,
                                   cancel_event=cancel_event)

    def add_brokers(self, broker_ids: Sequence[int],
                    goals: Optional[Sequence[str]] = None,
                    dryrun: bool = True,
                    deadline_ms: Optional[float] = None,
                    cancel_event: Optional[threading.Event] = None
                    ) -> OperationResult:
        """POST /add_broker (AddBrokersRunnable): mark brokers as new and let
        distribution goals pull load onto them."""
        ids = set(broker_ids)

        def mutate(cm: ClusterModel):
            for b in cm.brokers():
                if b.broker_id in ids:
                    b.new_broker = True

        return self._run_operation(goals, OptimizationOptions(), dryrun,
                                   model_mutator=mutate,
                                   deadline_ms=deadline_ms,
                                   cancel_event=cancel_event)

    def remove_brokers(self, broker_ids: Sequence[int],
                       goals: Optional[Sequence[str]] = None,
                       dryrun: bool = True,
                       deadline_ms: Optional[float] = None,
                       cancel_event: Optional[threading.Event] = None
                       ) -> OperationResult:
        """POST /remove_broker (RemoveBrokersRunnable): decommission — mark
        dead so every goal must evacuate them, and exclude them as
        destinations."""
        ids = set(broker_ids)

        def mutate(cm: ClusterModel):
            for b in ids:
                cm.set_broker_state(b, alive=False)

        options = OptimizationOptions(
            excluded_brokers_for_replica_move=frozenset(ids),
            excluded_brokers_for_leadership=frozenset(ids))
        return self._run_operation(goals, options, dryrun, model_mutator=mutate,
                                   deadline_ms=deadline_ms,
                                   cancel_event=cancel_event)

    def remove_brokers_batch(self, removal_sets: Sequence[Sequence[int]],
                             goals: Optional[Sequence[str]] = None,
                             num_candidates: int = 512,
                             deadline_ms: Optional[float] = None,
                             cancel_event: Optional[threading.Event] = None):
        """Batch decommission study: solve every removal set as a vmap lane of
        one compiled program (BASELINE config #5).  The reference would run
        ``RemoveBrokersRunnable`` once per set; this shares the model build
        and the per-goal compilation across all scenarios."""
        budget = self._make_budget(deadline_ms, cancel_event)
        self._register_budget(budget)
        pinned = False
        if self.resident.enabled:
            state, placement, meta = self._resident_snapshot()
            pinned = True
        else:
            builder = self.load_monitor.cluster_model_builder()
            state, placement, meta = self._freeze_bucketed(builder)
        try:
            goal_names = list(goals or self.default_goals)
            optimizer = (self.optimizer if goal_names == self.default_goals
                         else GoalOptimizer(constraint=self.constraint,
                                            goal_names=goal_names))
            # Warm start: when the base cluster was already solved this
            # generation, lanes begin from that balanced placement instead of
            # the raw snapshot — each lane only repairs its own removal's
            # damage, and the batched while_loop's per-lane progress guard
            # exits those lanes in a handful of rounds.
            warm = None
            base = self._base_solution
            if (base is not None
                    and base[0] == self.load_monitor.model_generation
                    and base[1].broker.shape == placement.broker.shape):
                warm = base[1]
            return optimizer.batch_remove_scenarios(
                state, placement, meta, removal_sets,
                num_candidates=num_candidates, warm_start=warm,
                budget=budget)
        finally:
            self._unregister_budget(budget)
            if pinned:
                self.resident.release()

    def demote_brokers(self, broker_ids: Sequence[int],
                       dryrun: bool = True,
                       deadline_ms: Optional[float] = None,
                       cancel_event: Optional[threading.Event] = None
                       ) -> OperationResult:
        """POST /demote_broker (DemoteBrokerRunnable): move leadership off
        the brokers via preferred-leader election with them excluded."""
        options = OptimizationOptions(
            excluded_brokers_for_leadership=frozenset(broker_ids))
        return self._run_operation(["PreferredLeaderElectionGoal"], options,
                                   dryrun, deadline_ms=deadline_ms,
                                   cancel_event=cancel_event)

    def fix_offline_replicas(self, goals: Optional[Sequence[str]] = None,
                             dryrun: bool = True,
                             deadline_ms: Optional[float] = None,
                             cancel_event: Optional[threading.Event] = None
                             ) -> OperationResult:
        """POST /fix_offline_replicas (FixOfflineReplicasRunnable)."""
        return self._run_operation(goals, OptimizationOptions(), dryrun,
                                   deadline_ms=deadline_ms,
                                   cancel_event=cancel_event)

    def change_topic_replication_factor(self, topic: str, target_rf: int,
                                        goals: Optional[Sequence[str]] = None,
                                        dryrun: bool = True,
                                        deadline_ms: Optional[float] = None,
                                        cancel_event: Optional[threading.Event] = None
                                        ) -> OperationResult:
        """POST /topic_configuration (TopicConfigurationRunnable →
        ClusterModel.createOrDeleteReplicas :962-1027)."""

        def mutate(cm: ClusterModel):
            cm.create_or_delete_replicas(topic, target_rf)

        return self._run_operation(goals, OptimizationOptions(), dryrun,
                                   model_mutator=mutate,
                                   deadline_ms=deadline_ms,
                                   cancel_event=cancel_event)

    def stop_execution(self) -> None:
        self.executor.user_triggered_stop_execution()

    # ------------------------------------------------------- sampling admin

    def pause_sampling(self, reason: str = "user requested") -> None:
        if self.task_runner is None:
            raise UserRequestError("no sampling task runner configured")
        self.task_runner.pause_sampling(reason)

    def resume_sampling(self, reason: str = "user requested") -> None:
        if self.task_runner is None:
            raise UserRequestError("no sampling task runner configured")
        self.task_runner.resume_sampling(reason)

    # ----------------------------------------------------------- self-healing

    def set_self_healing(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        return self.notifier.set_self_healing_for(anomaly_type, enabled)

    def _fix_anomaly(self, anomaly: Anomaly) -> bool:
        """Self-healing dispatch (§3.5): every fix is a normal operation."""
        # Stage 2 of the self-healing audit: annotate the detector's entry
        # with the concrete operation chosen for this anomaly; the chosen
        # action also lands in the operation audit log (nobody asked for a
        # self-healing fix, so its trail matters most).
        def note(action: str) -> None:
            audit_log().set_action(anomaly.anomaly_type.name, action)
            _oplog.record("start", endpoint=f"self-healing:{action}",
                          principal="self-healing",
                          anomaly=anomaly.anomaly_type.name)

        # Staleness gate (anomaly.model.* thresholds): never self-heal on a
        # model the fidelity observatory says is stale or heavily invalid —
        # a fix computed from bad data can move replicas the wrong way.
        # SLO-violation anomalies are exempt: preempting a runaway solve
        # depends on no model data.  User-requested proposal traffic is
        # unaffected (it serves with an advisory modelStale=true tag).
        if not isinstance(anomaly, SloViolationAnomaly):
            from cruise_control_tpu.obsvc.fidelity import fidelity as _fidelity
            stale = _fidelity().staleness_reason()
            if stale is not None:
                _fidelity().record_stale_gate()
                fp = _fidelity().current_fingerprint()
                audit_log().record(
                    anomaly.anomaly_type.name,
                    {"reason": "stale_model", "detail": stale,
                     "fingerprint": fp},
                    "IGNORED")
                _oplog.record("abort",
                              endpoint=f"self-healing:"
                                       f"{anomaly.anomaly_type.name}",
                              principal="self-healing", reason="stale_model",
                              generation=(fp or {}).get("generation"))
                LOG.warning("self-healing fix for %s IGNORED: %s",
                            anomaly.anomaly_type.name, stale)
                return False

        try:
            if isinstance(anomaly, BrokerFailures):
                note("remove_broker")
                r = self.remove_brokers(sorted(anomaly.failed_brokers), dryrun=False)
            elif isinstance(anomaly, DiskFailures):
                note("fix_offline_replicas")
                r = self.fix_offline_replicas(dryrun=False)
            elif isinstance(anomaly, GoalViolations):
                note("rebalance")
                r = self.rebalance(anomaly.fixable_violated_goals or None,
                                   dryrun=False)
            elif isinstance(anomaly, MetricAnomaly):
                if anomaly.suggested_action == "remove":
                    note("remove_broker")
                    r = self.remove_brokers([anomaly.broker_id], dryrun=False)
                elif anomaly.suggested_action == "demote":
                    note("demote_broker")
                    r = self.demote_brokers([anomaly.broker_id], dryrun=False)
                else:
                    return False
            elif isinstance(anomaly, TopicAnomaly):
                if anomaly.target_replication_factor is None:
                    return False
                note("topic_replication_factor")
                r = self.change_topic_replication_factor(
                    anomaly.topic, anomaly.target_replication_factor, dryrun=False)
            elif isinstance(anomaly, SloViolationAnomaly):
                # Escalated solve-time SLO burn: actively preempt the
                # offending in-flight solve(s) via their cancellation
                # tokens.  No proposals to execute — success is "the solve
                # was told to stop"; each preempted operation returns its
                # anytime-safe partial placement to its own caller.
                if not (self.slo_preempt_enabled
                        and anomaly.objective == "solve-time"):
                    return False
                note("preempt_solve")
                preempted = self.cancel_active_solves("slo-preempt")
                _oplog.record("preempted" if preempted else "finish",
                              endpoint="self-healing:preempt_solve",
                              principal="self-healing", solves=preempted)
                return preempted > 0
            elif isinstance(anomaly, MaintenanceEvent):
                note(f"maintenance:{anomaly.plan}")
                r = self._run_maintenance(anomaly)
            else:
                return False
            ok = r.executed or bool(r.optimizer_result
                                    and not r.optimizer_result.proposals)
            _oplog.record("finish" if ok else "abort",
                          endpoint=f"self-healing:{anomaly.anomaly_type.name}",
                          principal="self-healing", executed=r.executed,
                          partial=r.partial or None)
            return ok
        except OngoingExecutionError:
            LOG.info("fix deferred: execution already in progress")
            _oplog.record("abort",
                          endpoint=f"self-healing:{anomaly.anomaly_type.name}",
                          principal="self-healing",
                          reason="ongoing-execution")
            return False

    def _run_maintenance(self, event: MaintenanceEvent) -> OperationResult:
        if event.plan == "add_broker":
            return self.add_brokers(event.broker_ids, dryrun=False)
        if event.plan == "remove_broker":
            return self.remove_brokers(event.broker_ids, dryrun=False)
        if event.plan == "demote_broker":
            return self.demote_brokers(event.broker_ids, dryrun=False)
        if event.plan == "fix_offline_replicas":
            return self.fix_offline_replicas(dryrun=False)
        if event.plan == "topic_replication_factor":
            return self.change_topic_replication_factor(
                event.topic, event.replication_factor, dryrun=False)
        return self.rebalance(dryrun=False)

    # ---------------------------------------------------------------- state

    def state(self) -> Dict:
        """GET /state aggregation (CruiseControlState.java)."""
        runner_state = (self.task_runner.state.value
                        if self.task_runner is not None else "NOT_STARTED")
        from cruise_control_tpu.obsvc.execution import execution as _execution
        from cruise_control_tpu.obsvc.fidelity import fidelity as _fidelity
        from cruise_control_tpu.obsvc.memory import memory_ledger
        return {
            "MonitorState": {
                **self.load_monitor.state(runner_state).to_dict(),
                "modelQualityState": _fidelity().state_summary(),
            },
            "ExecutorState": {
                **self.executor.state_summary(),
                "executionState": _execution().state_summary(),
            },
            "AnomalyDetectorState": self.anomaly_detector.state_summary(),
            "AnalyzerState": {
                "isProposalReady": True,
                "goalReadiness": [
                    {"name": g, "status": "ready"} for g in self.default_goals],
                "residentModel": self.resident.stats(),
                "convergence": _convergence().state_summary(),
                "activeSolves": self.active_solves(),
                "memoryState": memory_ledger().state_summary(),
            },
        }

    def health(self) -> Dict:
        """GET /health — per-component probes with a ready/degraded/unhealthy
        rollup.  Cheap by construction (no solve, no model build): a load
        balancer polls this every few seconds.

        Probe semantics:
          * ``model``     — completeness floor met → ready; else degraded
            (goal operations would be rejected, reads still serve).
          * ``backend``   — admin circuit CLOSED → ready, HALF_OPEN →
            degraded, OPEN or executor in PAUSED_BACKEND_DOWN → unhealthy.
          * ``device``    — last solve needed the CPU fallback → degraded.
          * ``journal``   — startup reconciliation running or un-reconciled
            orphans on disk → degraded.
        """
        probes: Dict[str, Dict] = {}

        # -- model freshness
        model_status, detail = "ready", {}
        try:
            if self.default_completeness is not None:
                if not self.load_monitor.meet_completeness_requirements(
                        self.default_completeness):
                    model_status = "degraded"
                    detail["reason"] = "completeness requirements not met"
        except Exception as e:  # noqa: BLE001 — a probe never raises
            model_status, detail = "degraded", {"reason": str(e)}
        probes["model"] = {"status": model_status, **detail}

        # -- admin backend circuit
        circuit = (getattr(self.executor.backend, "circuit", None)
                   or _resilience.backend_circuit())
        backend_status, detail = "ready", {}
        if circuit is not None:
            snap = circuit.snapshot()
            detail = {"circuit": snap}
            if snap["state"] == "open":
                backend_status = "unhealthy"
            elif snap["state"] == "half_open":
                backend_status = "degraded"
        if self.executor.state is ExecutorState.PAUSED_BACKEND_DOWN:
            backend_status = "unhealthy"
            detail["reason"] = "executor paused: backend down"
        probes["backend"] = {"status": backend_status, **detail}

        # -- accelerator liveness (observed, not probed: poking the device
        # from the health path could itself wedge on a dead accelerator)
        if self._solver_degraded_at is not None:
            probes["device"] = {
                "status": "degraded",
                "reason": "solver on CPU fallback",
                "sinceMs": int(self._solver_degraded_at * 1000)}
        else:
            probes["device"] = {"status": "ready"}

        # -- crash journal
        journal_status, detail = "ready", {}
        if self.executor.recovering:
            journal_status = "degraded"
            detail["reason"] = "journal reconciliation in progress"
        else:
            journal = getattr(self.executor, "journal", None)
            if journal is not None:
                try:
                    lag = journal.lag()
                except OSError as e:
                    lag, detail = 0, {"reason": str(e)}
                if lag:
                    journal_status = "degraded"
                    detail = {"reason": "un-reconciled journaled tasks",
                              "lag": lag}
        probes["journal"] = {"status": journal_status, **detail}

        order = {"ready": 0, "degraded": 1, "unhealthy": 2}
        worst = max((p["status"] for p in probes.values()),
                    key=lambda s: order[s])
        return {"status": worst, "probes": probes}
