"""Typed configuration system.

Reference: core ``common/config/ConfigDef.java`` / ``AbstractConfig.java``
(Kafka-style typed definitions with defaults and validators, reflective
plugin loading) and ``config/KafkaCruiseControlConfig.java`` +
``config/constants/*`` (~270 keys split per subsystem).
"""

from cruise_control_tpu.config.config_def import ConfigDef, ConfigType, range_validator, in_validator
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig

__all__ = ["ConfigDef", "ConfigType", "CruiseControlConfig",
           "range_validator", "in_validator"]
