"""The framework's config surface.

Reference: ``config/KafkaCruiseControlConfig.java`` over the per-subsystem
constants classes — ``AnalyzerConfig`` (611), ``MonitorConfig`` (559),
``ExecutorConfig`` (614), ``AnomalyDetectorConfig`` (417),
``WebServerConfig`` (495).  Key names match the reference property names so a
reference ``cruisecontrol.properties`` file parses directly; goal lists
accept fully-qualified Java class names (the registry strips packages) —
the ``goals``/``default.goals`` switch-in point BASELINE.json requires.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals.registry import (
    DEFAULT_ANOMALY_DETECTION_GOALS,
    DEFAULT_GOALS,
    DEFAULT_HARD_GOALS,
    DEFAULT_INTRA_BROKER_GOALS,
    SUPPORTED_GOALS,
)
from cruise_control_tpu.common.exceptions import ConfigError
from cruise_control_tpu.config.config_def import (
    ConfigDef,
    ConfigType,
    in_validator,
    load_properties,
    range_validator,
)
from cruise_control_tpu.executor.executor import ExecutorConfig


def _analyzer_def() -> ConfigDef:
    d = ConfigDef()
    d.define("default.goals", ConfigType.LIST, ",".join(DEFAULT_GOALS),
             doc="goal priority list used when a request names no goals")
    d.define("goals", ConfigType.LIST, ",".join(SUPPORTED_GOALS),
             doc="all goals the instance supports")
    d.define("hard.goals", ConfigType.LIST, ",".join(DEFAULT_HARD_GOALS))
    d.define("intra.broker.goals", ConfigType.LIST,
             ",".join(DEFAULT_INTRA_BROKER_GOALS))
    d.define("cpu.balance.threshold", ConfigType.DOUBLE, 1.1,
             range_validator(1.0))
    d.define("network.inbound.balance.threshold", ConfigType.DOUBLE, 1.1,
             range_validator(1.0))
    d.define("network.outbound.balance.threshold", ConfigType.DOUBLE, 1.1,
             range_validator(1.0))
    d.define("disk.balance.threshold", ConfigType.DOUBLE, 1.1, range_validator(1.0))
    d.define("cpu.capacity.threshold", ConfigType.DOUBLE, 0.7,
             range_validator(0.0, 1.0))
    d.define("network.inbound.capacity.threshold", ConfigType.DOUBLE, 0.8,
             range_validator(0.0, 1.0))
    d.define("network.outbound.capacity.threshold", ConfigType.DOUBLE, 0.8,
             range_validator(0.0, 1.0))
    d.define("disk.capacity.threshold", ConfigType.DOUBLE, 0.8,
             range_validator(0.0, 1.0))
    d.define("cpu.low.utilization.threshold", ConfigType.DOUBLE, 0.0)
    d.define("network.inbound.low.utilization.threshold", ConfigType.DOUBLE, 0.0)
    d.define("network.outbound.low.utilization.threshold", ConfigType.DOUBLE, 0.0)
    d.define("disk.low.utilization.threshold", ConfigType.DOUBLE, 0.0)
    d.define("max.replicas.per.broker", ConfigType.LONG, 10_000,
             range_validator(1))
    d.define("replica.count.balance.threshold", ConfigType.DOUBLE, 1.1)
    d.define("leader.replica.count.balance.threshold", ConfigType.DOUBLE, 1.1)
    d.define("topic.replica.count.balance.threshold", ConfigType.DOUBLE, 3.0)
    d.define("topic.names.with.min.leaders.per.broker", ConfigType.LIST, "")
    d.define("min.topic.leaders.per.broker", ConfigType.INT, 1)
    # Also the background-precompute cadence (GoalOptimizer.java:107-135):
    # the facade's precompute daemon refreshes the generation-keyed proposal
    # cache at this interval.
    d.define("proposal.expiration.ms", ConfigType.LONG, 60_000)
    d.define("goal.violation.distribution.threshold.multiplier",
             ConfigType.DOUBLE, 1.0)
    d.define("num.proposal.precompute.threads", ConfigType.INT, 1,
             doc="accepted for reference compatibility; the batched solver "
                 "precomputes with one daemon (a solve is one device "
                 "dispatch, so a thread pool adds nothing)")
    return d


def _monitor_def() -> ConfigDef:
    d = ConfigDef()
    d.define("num.partition.metrics.windows", ConfigType.INT, 5)
    d.define("partition.metrics.window.ms", ConfigType.LONG, 300_000)
    d.define("num.broker.metrics.windows", ConfigType.INT, 20)
    d.define("broker.metrics.window.ms", ConfigType.LONG, 300_000)
    d.define("min.samples.per.partition.metrics.window", ConfigType.INT, 1)
    d.define("metric.sampling.interval.ms", ConfigType.LONG, 120_000)
    d.define("monitor.state.update.interval.ms", ConfigType.LONG, 30_000,
             doc="accepted for reference compatibility; monitor state here "
                 "is computed on read (with a short-lived cache), not on a "
                 "refresh timer")
    d.define("broker.capacity.config.resolver.class", ConfigType.CLASS, "")
    d.define("capacity.config.file", ConfigType.STRING, "")
    d.define("sample.store.class", ConfigType.CLASS, "")
    d.define("sample.store.dir", ConfigType.STRING, "")
    d.define("metric.sampler.class", ConfigType.CLASS, "")
    # "synthetic" (default) | "reporter" (metrics-reporter pipeline through
    # the transport) | "prometheus" — demo-mode sampler selection.
    d.define("metric.sampler.mode", ConfigType.STRING, "synthetic")
    # Network face of the metrics bus (the role the Kafka listener plays for
    # __CruiseControlMetrics): 0 disables; any other port serves the
    # reporter-mode transport over TCP so external broker agents can publish
    # with reporter.SocketTransport.
    d.define("metrics.transport.listen.port", ConfigType.INT, 0,
             doc="TCP port serving the metrics-bus transport; 0 = in-process "
                 "only.  Requires metric.sampler.mode=reporter (and no "
                 "metric.sampler.class override) — otherwise the port is "
                 "ignored with a warning")
    d.define("metrics.transport.listen.address", ConfigType.STRING, "127.0.0.1",
             doc="bind address for the metrics-bus listener.  Binding beyond "
                 "loopback (0.0.0.0 for remote broker agents) should set "
                 "metrics.transport.auth.secret.file (and ideally TLS) — a "
                 "plaintext unauthenticated bus lets anyone who can reach "
                 "the port forge metrics or read workload data")
    d.define("metrics.transport.auth.secret.file", ConfigType.STRING, "",
             doc="file holding the shared secret every bus peer must present "
                 "as its first frame ({'op':'auth','token':...}); empty = "
                 "unauthenticated (loopback/demo only).  Reporter agents "
                 "pass the same secret to reporter.SocketTransport")
    d.define("metrics.transport.ssl.certfile", ConfigType.STRING, "",
             doc="PEM cert chain enabling TLS on the metrics-bus listener "
                 "(same config shape as webserver.ssl.*); empty = plaintext")
    d.define("metrics.transport.ssl.keyfile", ConfigType.STRING, "",
             doc="PEM private key for metrics.transport.ssl.certfile "
                 "(empty when the cert file bundles the key)")
    d.define("num.metric.fetchers", ConfigType.INT, 4)
    d.define("prometheus.server.endpoint", ConfigType.STRING, "")
    d.define("min.valid.partition.ratio", ConfigType.DOUBLE, 0.95,
             range_validator(0.0, 1.0))
    d.define("metadata.max.age.ms", ConfigType.LONG, 5_000)
    return d


def _executor_def() -> ConfigDef:
    d = ConfigDef()
    d.define("num.concurrent.partition.movements.per.broker", ConfigType.INT, 5)
    d.define("num.concurrent.intra.broker.partition.movements", ConfigType.INT, 2)
    d.define("num.concurrent.leader.movements", ConfigType.INT, 1000)
    d.define("max.num.cluster.movements", ConfigType.INT, 1250)
    d.define("execution.progress.check.interval.ms", ConfigType.LONG, 10_000)
    d.define("default.replication.throttle", ConfigType.LONG, None)
    d.define("task.execution.alerting.threshold.ms", ConfigType.LONG, 90_000)
    d.define("auto.adjust.concurrency", ConfigType.BOOLEAN, False)
    # Cluster-facing admin driver selection (the reference's executor always
    # speaks ZK/AdminClient; here the seam is the ClusterAdminBackend
    # protocol): a class override, or a host:port of a peer speaking the
    # admin protocol (broker_simulator --listen, or any real driver shim).
    d.define("executor.admin.backend.class", ConfigType.CLASS, "",
             doc="ClusterAdminBackend implementation; beats the address key")
    d.define("executor.admin.backend.address", ConfigType.STRING, "",
             doc="host:port of an admin-protocol peer (SocketClusterBackend);"
                 " empty = in-process fake (demo)")
    d.define("executor.admin.backend.auth.secret.file", ConfigType.STRING, "",
             doc="file holding the shared secret presented to the admin peer "
                 "as the connection's first frame (broker_simulator "
                 "--auth-token-file); empty = unauthenticated (demo only)")
    d.define("executor.admin.backend.ssl.enable", ConfigType.BOOLEAN, False,
             doc="wrap the admin connection in TLS; pair with the cafile key "
                 "to verify the peer (alone it encrypts without verifying)")
    d.define("executor.admin.backend.ssl.cafile", ConfigType.STRING, "",
             doc="PEM CA (typically the peer's self-signed cert) pinning the "
                 "admin peer's identity; implies ssl.enable")
    return d


def _anomaly_def() -> ConfigDef:
    d = ConfigDef()
    d.define("anomaly.detection.goals", ConfigType.LIST,
             ",".join(DEFAULT_ANOMALY_DETECTION_GOALS))
    d.define("anomaly.detection.interval.ms", ConfigType.LONG, 300_000)
    d.define("self.healing.enabled", ConfigType.BOOLEAN, False)
    d.define("broker.failure.alert.threshold.ms", ConfigType.LONG, 900_000)
    d.define("broker.failure.self.healing.threshold.ms", ConfigType.LONG, 1_800_000)
    d.define("anomaly.notifier.class", ConfigType.CLASS, "")
    # Webhook alerting (SlackSelfHealingNotifier analog): set a URL to route
    # anomaly alerts to a JSON webhook (Slack/Teams/generic receiver).
    d.define("anomaly.notifier.webhook.url", ConfigType.STRING, "")
    d.define("anomaly.notifier.webhook.channel", ConfigType.STRING, "")
    d.define("topic.anomaly.target.replication.factor", ConfigType.INT, None)
    # Maintenance-plan stream (MaintenanceEventTopicReader analog): plans
    # arrive over a partitioned-log Transport instead of in-process submit().
    # Exactly one of address (TCP TransportServer peer) or dir (FileTransport
    # directory) enables the reader.
    d.define("maintenance.event.transport.address", ConfigType.STRING, "",
             doc="host:port of a TransportServer carrying maintenance plans "
                 "(reporter.SocketTransport consumer); empty = disabled")
    d.define("maintenance.event.transport.dir", ConfigType.STRING, "",
             doc="FileTransport directory carrying maintenance plans; "
                 "empty = disabled.  Ignored when the address key is set")
    d.define("maintenance.event.transport.auth.secret.file", ConfigType.STRING,
             "",
             doc="file holding the shared secret presented to the maintenance "
                 "bus (required when the TransportServer it points at is "
                 "secured); empty = unauthenticated")
    d.define("maintenance.event.transport.ssl.enable", ConfigType.BOOLEAN,
             False,
             doc="wrap the maintenance bus connection in TLS; pair with the "
                 "cafile key to verify the peer")
    d.define("maintenance.event.transport.ssl.cafile", ConfigType.STRING, "",
             doc="PEM CA pinning the maintenance bus peer's identity; "
                 "implies ssl.enable")
    d.define("maintenance.plan.expiration.ms", ConfigType.LONG, 900_000,
             doc="validity period of a maintenance plan; older plans read "
                 "from the stream are discarded "
                 "(MaintenanceEventTopicReader.java expiration semantics)")
    d.define("maintenance.event.offsets.path", ConfigType.STRING, "",
             doc="JSON file persisting the reader's committed offsets "
                 "(restart resumes instead of replaying); empty = "
                 "<transport.dir>/consumer-offsets.json when dir mode, else "
                 "uncommitted")
    d.define("anomaly.model.min.valid.partition.ratio", ConfigType.DOUBLE,
             0.0, range_validator(0.0, 1.0),
             doc="staleness gate: self-healing fixes are IGNORED (audit "
                 "reason stale_model) while the current model fingerprint's "
                 "valid-partition ratio is below this; 0.0 disables the "
                 "ratio check")
    d.define("anomaly.model.max.age.ms", ConfigType.LONG, 0,
             range_validator(0),
             doc="staleness gate: self-healing fixes are IGNORED (audit "
                 "reason stale_model) while the current model fingerprint's "
                 "newest valid window is older than this; 0 disables the "
                 "age check")
    return d


def _compile_def() -> ConfigDef:
    """compilesvc keys (no reference analog — the reference JVM has no XLA
    executables to manage)."""
    d = ConfigDef()
    d.define("compile.warmup.enabled", ConfigType.BOOLEAN, True,
             doc="start the background warmup daemon on facade start_up; it "
                 "runs real dryrun solves at the canonical shape buckets so "
                 "the first operator request never pays cold-compile latency")
    d.define("compile.warmup.lanes", ConfigType.LIST, "4",
             doc="what-if lane-width LADDER the warmup daemon pre-compiles "
                 "(comma list, e.g. \"4,16\"); every width gets its own warm "
                 "task so chunked wide batches find each block width hot")
    d.define("compile.lane.chunking.enabled", ConfigType.BOOLEAN, True,
             doc="route wide what-if batches through already-compiled lane "
                 "executables (e.g. 64 lanes as 4x16) instead of compiling "
                 "a fresh full-width program")
    d.define("compile.max.lane.bucket", ConfigType.INT, 16, range_validator(1),
             doc="largest lane executable compiled fresh; wider batches are "
                 "chunked through this width (must be on the lane ladder)")
    d.define("compile.replica.pad.floor", ConfigType.INT, 64,
             range_validator(1),
             doc="smallest replica-axis shape bucket (geometric growth above)")
    d.define("compile.broker.pad.floor", ConfigType.INT, 8, range_validator(1),
             doc="smallest broker-axis shape bucket")
    d.define("compile.bucket.growth", ConfigType.DOUBLE, 2.0,
             range_validator(1.001),
             doc="geometric growth factor between consecutive shape buckets")
    d.define("compile.persistent.cache.enabled", ConfigType.BOOLEAN, False,
             doc="persist XLA executables across restarts under versioned "
                 "keys (jaxlib version, machine fingerprint, goal stack, "
                 "bucket).  Default off: XLA:CPU executables from a machine-"
                 "feature-skewed producer can SIGILL the consumer, so CPU "
                 "deployments must opt in knowingly")
    d.define("compile.persistent.cache.path", ConfigType.STRING, "",
             doc="cache root; empty = ~/.cache/cruise_control_tpu/"
                 "compile_cache")
    d.define("compile.persistent.cache.max.bytes", ConfigType.LONG,
             4 * 1024 * 1024 * 1024, range_validator(1),
             doc="per-entry-directory size cap; oldest executables evicted "
                 "first")
    d.define("compile.persistent.cache.cpu.probe", ConfigType.BOOLEAN, True,
             doc="gate CPU cache activation on a two-subprocess write-then-"
                 "load probe of the XLA:CPU loader (memoized per jaxlib + "
                 "machine fingerprint); false restores blind-trust "
                 "activation for hosts validated out of band")
    return d


def _model_def() -> ConfigDef:
    """Resident-model keys (no reference analog — the reference JVM rebuilds
    its ClusterModel object graph per request; this port keeps the frozen
    tensors on-device and scatter-applies monitor deltas)."""
    d = ConfigDef()
    d.define("model.resident.enabled", ConfigType.BOOLEAN, True,
             doc="keep the frozen (state, placement) tensors device-resident "
                 "across requests and apply LoadMonitor changes as sparse "
                 "scatter deltas; disable to re-freeze the full model every "
                 "request (the pre-resident behavior)")
    d.define("model.resident.max.delta.slots", ConfigType.INT, 8192,
             range_validator(1),
             doc="largest touched-row count a delta may carry; bigger edits "
                 "fall back to a full freeze (slots are padded to a "
                 "geometric bucket so the scatter executable's shape is "
                 "stable)")
    d.define("model.resident.max.delta.chain", ConfigType.INT, 512,
             range_validator(1),
             doc="consecutive delta applies allowed since the last full "
                 "freeze; the next snapshot past this re-freezes (bounds "
                 "numeric drift and caps replay length)")
    return d


def _trace_def() -> ConfigDef:
    """obsvc keys (no reference analog — the reference JVM leans on flat
    Dropwizard sensors; span tracing is this port's solve-time instrument)."""
    d = ConfigDef()
    d.define("trace.enabled", ConfigType.BOOLEAN, False,
             doc="propagate a span tree through every HTTP request, "
                 "precompute tick and executor batch (GET /trace); adds "
                 "block_until_ready fences around solver dispatches, so "
                 "leave off unless attributing time")
    d.define("trace.ring.size", ConfigType.INT, 32, range_validator(1),
             doc="how many recent root traces GET /trace retains")
    d.define("trace.audit.log.size", ConfigType.INT, 256, range_validator(1),
             doc="bounded length of the self-healing audit log surfaced in "
                 "the AnomalyDetectorState substate of GET /state")
    d.define("trace.profile.dir", ConfigType.STRING, "",
             doc="root directory for POST /profile TensorBoard trace dirs; "
                 "empty = <tmpdir>/cruise_control_tpu_profiles")
    d.define("trace.solver.rounds", ConfigType.BOOLEAN, False,
             doc="record per-round solver convergence curves (applied moves, "
                 "violated/stranded counts, goal metric, resync/stall flags) "
                 "in an on-device stats buffer threaded through the solve "
                 "loop's carry, surfaced via GET /solver_stats.  The flag "
                 "joins the solver's jit-cache key and compilesvc bucket "
                 "label, so the default-off executables are byte-identical "
                 "to a build without the recorder")
    d.define("trace.solver.ring.size", ConfigType.INT, 64, range_validator(1),
             doc="bounded flight-recorder ring of recent per-solve "
                 "convergence records kept for GET /solver_stats")
    d.define("obs.history.enabled", ConfigType.BOOLEAN, True,
             doc="run the sensor-history sampler thread: periodic "
                 "MetricRegistry snapshots into bounded per-sensor "
                 "time-series rings (GET /metrics/history)")
    d.define("obs.history.interval.ms", ConfigType.LONG, 10_000,
             range_validator(100),
             doc="sampling cadence of the sensor-history recorder")
    d.define("obs.history.ring.size", ConfigType.INT, 360, range_validator(1),
             doc="samples retained per sensor (360 x 10 s default = 1 h)")
    d.define("slo.enabled", ConfigType.BOOLEAN, False,
             doc="evaluate the latency/solve objectives below over the "
                 "sensor-history rings and emit SloViolationAnomaly through "
                 "the detector -> notifier -> audit path")
    d.define("slo.endpoint.latency.p99.ms", ConfigType.DOUBLE, 5_000.0,
             range_validator(0.001),
             doc="per-endpoint objective: p99 of each servlet endpoint's "
                 "successful-request-execution-timer must stay below this")
    d.define("slo.solve.rounds.max", ConfigType.INT, 96, range_validator(1),
             doc="per-solve objective: a goal's convergence rounds must stay "
                 "below this (hitting the solver's own round cap means the "
                 "loop never converged)")
    d.define("slo.solve.time.ms", ConfigType.DOUBLE, 30_000.0,
             range_validator(0.001),
             doc="per-solve objective: p99 of the proposal-computation timer")
    d.define("slo.error.budget", ConfigType.DOUBLE, 0.1,
             range_validator(0.0001, 1.0),
             doc="fraction of history samples allowed to breach an objective "
                 "before the burn rate reads 1.0")
    d.define("slo.burn.window.short.s", ConfigType.DOUBLE, 300.0,
             range_validator(1.0),
             doc="short burn-rate window (both windows must burn to alert)")
    d.define("slo.burn.window.long.s", ConfigType.DOUBLE, 3_600.0,
             range_validator(1.0), doc="long burn-rate window")
    d.define("slo.burn.rate.threshold", ConfigType.DOUBLE, 1.0,
             range_validator(0.0001),
             doc="burn rate (violating fraction / error budget) at or above "
                 "which a window counts as burning")
    d.define("slo.memory.utilization.max", ConfigType.DOUBLE, 0.9,
             range_validator(0.0001, 1.0),
             doc="memory-headroom objective: the device-buffer ledger's "
                 "tracked utilization (Memory.device-utilization, live bytes "
                 "/ device budget) must stay below this fraction")
    d.define("slo.execution.seconds.per.move.max", ConfigType.DOUBLE, 60.0,
             range_validator(0.001),
             doc="execution-throughput objective: the executor flight "
                 "recorder's EWMA seconds-per-move "
                 "(Executor.seconds-per-move) must stay below this; the "
                 "gauge reads 0.0 between batches so idle never burns")
    d.define("execution.observatory.enabled", ConfigType.BOOLEAN, True,
             doc="run the execution flight recorder: move provenance "
                 "threaded from the optimizer into executor tasks and the "
                 "journal, per-broker inflight accounting, EWMA "
                 "move-completion throughput and batch ETA "
                 "(GET /execution_progress, Executor.* throughput sensors). "
                 "Host-side only: solver executables and jit cache keys are "
                 "byte-identical with the observatory off")
    d.define("execution.history.ring.size", ConfigType.INT, 64,
             range_validator(1),
             doc="bounded ring of recent execution-batch summaries the "
                 "flight recorder retains for /execution_progress")
    d.define("execution.throughput.ewma.alpha", ConfigType.DOUBLE, 0.3,
             range_validator(0.0001, 1.0),
             doc="EWMA smoothing factor for the seconds-per-move estimator "
                 "(higher = reacts faster to the latest completion)")
    d.define("monitor.fidelity.enabled", ConfigType.BOOLEAN, True,
             doc="run the model-fidelity observatory: a ModelFingerprint "
                 "(generation, window age, valid-partition ratio, "
                 "extrapolated fraction by kind, dead brokers) recorded at "
                 "every model freeze / resident delta-apply and stamped "
                 "onto optimizer results and proposals, plus the ingest "
                 "telemetry ring behind GET /model_quality.  Host-side "
                 "only: solver executables and jit cache keys are "
                 "byte-identical with the observatory off")
    d.define("monitor.fidelity.ring.size", ConfigType.INT, 64,
             range_validator(1),
             doc="bounded rings of recent fingerprints, window-close "
                 "quality entries and liveness flaps the fidelity recorder "
                 "retains for /model_quality")
    d.define("slo.model.age.max.ms", ConfigType.DOUBLE, 1_800_000.0,
             range_validator(0.001),
             doc="model-freshness objective: the current fingerprint's age "
                 "(Monitor.fingerprint-age-ms, now minus its newest valid "
                 "window's end) must stay below this; the gauge reads 0.0 "
                 "before the first fingerprint so cold boot never burns")
    d.define("slo.model.valid.partition.ratio.min", ConfigType.DOUBLE, 0.8,
             range_validator(0.0001, 1.0),
             doc="model-validity objective: the fingerprint's valid-"
                 "partition ratio must stay at or above this (evaluated on "
                 "the inverted Monitor.invalid-partition-ratio gauge, which "
                 "reads 0.0 before the first fingerprint, so 'bad' is "
                 "above threshold and idle never burns)")
    return d


def _memory_def() -> ConfigDef:
    """Device-memory observatory keys (no reference analog — the reference
    JVM delegates memory pressure to the garbage collector; on an
    accelerator, HBM occupancy is a first-class scheduling input)."""
    d = ConfigDef()
    d.define("memory.enabled", ConfigType.BOOLEAN, True,
             doc="run the device-buffer ledger (per-subsystem live-bytes "
                 "accounting, GET /memory, Memory.* sensors) and the "
                 "per-executable compile-cost ledger.  Host-side only: no "
                 "traced code changes, every jit cache key and executable "
                 "is byte-identical with the ledger off")
    d.define("memory.headroom.fraction", ConfigType.DOUBLE, 0.9,
             range_validator(0.0001, 1.0),
             doc="lane-dispatch guard ceiling: a what-if batch whose "
                 "projected peak bytes exceed this fraction of the device "
                 "budget is re-chunked onto narrower lane widths, or refused "
                 "(degraded-style tagging) when no ladder width fits")
    d.define("memory.device.budget.bytes", ConfigType.LONG, 0,
             range_validator(0),
             doc="device memory budget the headroom guard divides by; "
                 "0 = take the backend-reported bytes_limit from "
                 "device.memory_stats() (XLA:CPU reports none, leaving the "
                 "guard inert unless this override is set)")
    d.define("memory.analysis.mode", ConfigType.STRING, "lowered",
             in_validator("off", "lowered", "full"),
             doc="per-executable cost analysis depth on each fresh XLA "
                 "compile: 'off' disables rows; 'lowered' (default) re-lowers "
                 "on abstract avals for flops/bytes-accessed plus arg/out "
                 "sizes (~ms, once per bucket label); 'full' additionally "
                 "AOT-compiles for temp/generated-code bytes and true peak "
                 "(a second XLA compile per family — bench/profile opt-in)")
    return d


def _fuzz_def() -> ConfigDef:
    """fuzzsvc keys (no reference analog — the reference's randomized
    OptimizationVerifier corpora live in its JUnit parameters; here the
    fuzz campaign is an operable service entrypoint)."""
    d = ConfigDef()
    d.define("fuzz.num.scenarios", ConfigType.INT, 8, range_validator(1),
             doc="scenarios per campaign (seeds fuzz.seed.base..+N-1)")
    d.define("fuzz.seed.base", ConfigType.INT, 100,
             doc="first scenario seed; every failure replays from "
                 "(seed, kind) alone")
    d.define("fuzz.scenario.budget.s", ConfigType.DOUBLE, 120.0,
             range_validator(0.001),
             doc="per-scenario soft wall-clock budget; overruns are "
                 "reported, not killed (a stuck solve IS a finding)")
    d.define("fuzz.corpus.dir", ConfigType.STRING, ".fuzz-corpus",
             doc="failing scenarios (and their shrunk .min forms) are "
                 "saved here as replayable JSON")
    d.define("fuzz.storm.cycles", ConfigType.INT, 1, range_validator(0),
             doc="chaos-storm inject→detect→heal cycles per scenario; "
                 "0 disables the storm")
    d.define("fuzz.shrink.max.steps", ConfigType.INT, 8, range_validator(0),
             doc="greedy-shrinker descent bound on a failing scenario")
    return d


def _resilience_def() -> ConfigDef:
    """resilience keys (retry budgets, admin-backend circuit breaker, crash
    journal, /health).  No single reference analog — the reference leans on
    the JVM AdminClient's internal retries; here the transport is ours, so
    the failure policy is operator-visible config."""
    d = ConfigDef()
    d.define("resilience.retry.max.attempts", ConfigType.INT, 4,
             range_validator(1),
             doc="attempts per admin-backend call before the retry budget "
                 "is exhausted")
    d.define("resilience.retry.base.delay.ms", ConfigType.LONG, 100,
             range_validator(1),
             doc="first-retry backoff; later retries multiply by 2 with "
                 "±50% jitter")
    d.define("resilience.retry.max.delay.ms", ConfigType.LONG, 5_000,
             range_validator(1), doc="backoff ceiling per sleep")
    d.define("resilience.retry.deadline.ms", ConfigType.LONG, 30_000,
             range_validator(1),
             doc="wall-clock budget across all attempts of one logical call")
    d.define("resilience.circuit.failure.threshold", ConfigType.INT, 5,
             range_validator(1),
             doc="consecutive backend failures that open the circuit")
    d.define("resilience.circuit.reset.timeout.ms", ConfigType.LONG, 10_000,
             range_validator(1),
             doc="open-circuit hold before a half-open probe is allowed")
    d.define("resilience.backend.reconnect.enabled", ConfigType.BOOLEAN, True,
             doc="wrap the socket admin backend in the reconnecting/"
                 "circuit-breaking transport")
    d.define("resilience.journal.path", ConfigType.STRING, "",
             doc="crash-safe execution journal file; empty disables "
                 "journaling (and startup reconciliation)")
    d.define("resilience.journal.adoption.timeout.ms", ConfigType.LONG,
             30_000, range_validator(1),
             doc="startup budget for waiting on re-adopted in-flight "
                 "reassignments before declaring them still-in-flight")
    d.define("resilience.health.retry.after.s", ConfigType.INT, 30,
             range_validator(1),
             doc="Retry-After header value on 503s while unhealthy")
    return d


def _solver_def() -> ConfigDef:
    """Deadline / preemption keys (no reference analog — the reference JVM
    can interrupt its proposal thread; here the solve is a device dispatch,
    so preemption is a first-class budget threaded through the solver's
    segmented executables)."""
    d = ConfigDef()
    d.define("solver.default.deadline.ms", ConfigType.LONG, None,
             doc="default wall-clock budget for every goal-based operation's "
                 "solve; on expiry the solve stops at its next segment "
                 "boundary and returns the best placement found so far, "
                 "tagged partial.  A request's ?deadline_ms= overrides it; "
                 "empty/0 = unbudgeted (byte-identical executables and "
                 "results to a build without deadlines)")
    d.define("solver.segment.rounds", ConfigType.INT, 8, range_validator(1),
             doc="convergence rounds per segmented-solve dispatch when a "
                 "deadline is set; smaller = tighter deadline adherence, "
                 "more host-device round-trips.  Never affects budget-less "
                 "solves (they run the fused single-dispatch loop)")
    d.define("solver.shutdown.grace.ms", ConfigType.LONG, 5_000,
             range_validator(0),
             doc="facade.shutdown grace-drain: cancel in-flight solves and "
                 "wait up to this long for them to unwind through their "
                 "next segment boundary before tearing components down")
    d.define("slo.preempt.enabled", ConfigType.BOOLEAN, False,
             doc="escalate the solve-time SLO objective from emit-anomaly "
                 "to actively preempting the offending in-flight solve "
                 "(the anomaly becomes fixable and the fix cancels every "
                 "active solve budget with reason slo-preempt).  Requires "
                 "slo.enabled and self-healing for SLO_VIOLATION")
    d.define("solver.relaxation.enabled", ConfigType.BOOLEAN, False,
             doc="convex-relaxation fast path for relax-eligible "
                 "distribution goals (analyzer/relax.py): fractional "
                 "mirror-descent solve + transport-style rounding, with the "
                 "greedy kernel demoted to a warm-started integer repair "
                 "pass.  Ineligible goals — and everything when off — take "
                 "the greedy path bit-for-bit (identical executables, cache "
                 "keys, and results).  Budgeted/deadline solves always stay "
                 "on the greedy path")
    d.define("solver.relaxation.iterations", ConfigType.INT, 48,
             range_validator(1),
             doc="mirror-descent iterations for the fractional solve; a "
                 "traced loop bound, so changing it never recompiles")
    d.define("solver.relaxation.candidates", ConfigType.INT, 4096,
             range_validator(1),
             doc="top-K movable replicas given fractional mass per goal "
                 "(clamped to the replica pad; compile-time tile width, "
                 "same role as the greedy candidate width)")
    d.define("solver.relaxation.waves", ConfigType.INT, 4, range_validator(1),
             doc="rounding waves: each wave commits at most one accepted "
                 "move per partition/src/dst/host group, vetoed "
                 "destinations retry their runner-up next wave")
    d.define("solver.relaxation.tolerance", ConfigType.DOUBLE, 0.05,
             range_validator(0),
             doc="relative soft-goal balancedness slack the relax+repair "
                 "result may trail pure greedy by before the fuzz "
                 "relaxation_sound invariant flags it")
    return d


def _webserver_def() -> ConfigDef:
    d = ConfigDef()
    d.define("webserver.http.port", ConfigType.INT, 9090)
    d.define("webserver.http.address", ConfigType.STRING, "127.0.0.1")
    d.define("webserver.api.urlprefix", ConfigType.STRING, "/kafkacruisecontrol/*")
    # Static frontend (reference WebServerConfig:81-90 + setupWebUi): empty
    # diskpath disables serving (the frontend bundle ships separately).
    d.define("webserver.ui.diskpath", ConfigType.STRING, "",
             doc="directory with the built web frontend; empty = no UI")
    d.define("webserver.ui.urlprefix", ConfigType.STRING, "/*",
             doc="URL path the frontend is served from")
    d.define("webserver.request.maxBlockTimeMs", ConfigType.LONG, 10_000,
             doc="accepted for reference compatibility; every mutating "
                 "request is async-202 from the start, so there is no "
                 "sync-to-async conversion timer")
    d.define("webserver.session.maxExpiryTimeMs", ConfigType.LONG, 21_600_000,
             doc="accepted for reference compatibility; task affinity rides "
                 "the User-Task-ID header, not servlet sessions")
    # Security (reference WebServerConfig.WEBSERVER_SECURITY_*):
    d.define("webserver.security.enable", ConfigType.BOOLEAN, False)
    # "basic" | "jwt" | "trusted_proxy"
    d.define("webserver.security.provider", ConfigType.STRING, "basic")
    d.define("webserver.auth.credentials.file", ConfigType.STRING, "")
    d.define("webserver.auth.jwt.secret", ConfigType.STRING, "")
    d.define("webserver.auth.trusted.proxy.ips", ConfigType.STRING, "")
    d.define("webserver.auth.trusted.proxy.user.header", ConfigType.STRING,
             "X-Forwarded-User")
    # SPNEGO (reference servlet/security/spnego/*): the GSS ticket validator
    # is a plugin — Kerberos libraries are deployment-specific.
    d.define("webserver.auth.spnego.validator.class", ConfigType.CLASS, None,
             doc="callable/class returning the authenticated principal for a "
                 "GSS token; replaces the reference's JAAS+keytab wiring "
                 "(spnego.keytab.file / spnego.principal)")
    # TLS listener (reference WebServerConfig WEBSERVER_SSL_* +
    # KafkaCruiseControlApp.java:100-120).  INTENTIONAL DEVIATION: the
    # reference configures a JKS/PKCS12 keystore (webserver.ssl.keystore.
    # location/.password/.type, webserver.ssl.key.password); Python's ssl
    # module loads PEM, so the keys here name a PEM chain + key instead.
    # main.py points reference-keystore users at the rename.
    d.define("webserver.ssl.enable", ConfigType.BOOLEAN, False)
    d.define("webserver.ssl.certfile", ConfigType.STRING, "",
             doc="PEM cert chain; replaces the reference's "
                 "`webserver.ssl.keystore.location` (JKS/PKCS12 keystores "
                 "are JVM-specific — export to PEM)")
    d.define("webserver.ssl.keyfile", ConfigType.STRING, "",
             doc="PEM private key (reference: inside the keystore)")
    d.define("webserver.ssl.keyfile.password", ConfigType.STRING, "",
             doc="replaces the reference's `webserver.ssl.key.password`")
    d.define("max.active.user.tasks", ConfigType.INT, 25)
    d.define("completed.user.task.retention.time.ms", ConfigType.LONG, 86_400_000)
    d.define("two.step.verification.enabled", ConfigType.BOOLEAN, False)
    d.define("servlet.user.task.timeout.ms", ConfigType.LONG, None,
             doc="wall-clock cap on async 202 user tasks: past it the "
                 "task's cancellation token fires (reason timeout), the "
                 "solve stops at its next budget checkpoint, and the task "
                 "lands in the TIMED_OUT terminal state in /user_tasks; "
                 "empty/0 = unbounded (pre-cap behavior)")
    return d


class CruiseControlConfig:
    """Parsed config over the merged per-subsystem definitions."""

    def __init__(self, props: Optional[Dict[str, Any]] = None):
        self.definition = (_analyzer_def().merge(_monitor_def())
                           .merge(_executor_def()).merge(_anomaly_def())
                           .merge(_compile_def()).merge(_model_def())
                           .merge(_trace_def()).merge(_memory_def())
                           .merge(_fuzz_def()).merge(_resilience_def())
                           .merge(_solver_def()).merge(_webserver_def()))
        props = dict(props or {})
        known = self.definition.keys()
        self.originals = props
        self.values = self.definition.parse(
            {k: v for k, v in props.items() if k in known})
        self._validate_goal_names()

    @classmethod
    def from_properties_file(cls, path: str) -> "CruiseControlConfig":
        return cls(load_properties(path))

    def _validate_goal_names(self) -> None:
        from cruise_control_tpu.analyzer.goals.registry import goal_by_name
        for key in ("default.goals", "goals", "hard.goals",
                    "anomaly.detection.goals", "intra.broker.goals"):
            for name in self.values.get(key) or []:
                try:
                    goal_by_name(name)
                except ValueError as e:
                    raise ConfigError(f"{key}: {e}") from None

    def __getitem__(self, key: str) -> Any:
        return self.values[key]

    def get(self, key: str, default=None) -> Any:
        return self.values.get(key, default)

    # ----------------------------------------------------- derived objects

    def goal_names(self, key: str = "default.goals") -> List[str]:
        return [g.rsplit(".", 1)[-1] for g in self.values[key]]

    def balancing_constraint(self) -> BalancingConstraint:
        v = self.values
        return BalancingConstraint(
            balance_threshold=np.array(
                [v["cpu.balance.threshold"],
                 v["network.inbound.balance.threshold"],
                 v["network.outbound.balance.threshold"],
                 v["disk.balance.threshold"]], dtype=np.float32),
            capacity_threshold=np.array(
                [v["cpu.capacity.threshold"],
                 v["network.inbound.capacity.threshold"],
                 v["network.outbound.capacity.threshold"],
                 v["disk.capacity.threshold"]], dtype=np.float32),
            low_utilization_threshold=np.array(
                [v["cpu.low.utilization.threshold"],
                 v["network.inbound.low.utilization.threshold"],
                 v["network.outbound.low.utilization.threshold"],
                 v["disk.low.utilization.threshold"]], dtype=np.float32),
            max_replicas_per_broker=int(v["max.replicas.per.broker"]),
            replica_balance_threshold=v["replica.count.balance.threshold"],
            leader_replica_balance_threshold=
                v["leader.replica.count.balance.threshold"],
            topic_replica_balance_threshold=
                v["topic.replica.count.balance.threshold"],
            min_topic_leaders_per_broker=v["min.topic.leaders.per.broker"],
            min_leader_topic_names=tuple(
                v["topic.names.with.min.leaders.per.broker"] or ()),
            goal_violation_distribution_threshold_multiplier=
                v["goal.violation.distribution.threshold.multiplier"],
        )

    def executor_config(self) -> ExecutorConfig:
        v = self.values
        return ExecutorConfig(
            concurrent_partition_movements_per_broker=
                v["num.concurrent.partition.movements.per.broker"],
            concurrent_intra_broker_partition_movements=
                v["num.concurrent.intra.broker.partition.movements"],
            concurrent_leader_movements=v["num.concurrent.leader.movements"],
            max_num_cluster_movements=v["max.num.cluster.movements"],
            progress_check_interval_s=
                v["execution.progress.check.interval.ms"] / 1000.0,
            replication_throttle_bytes_per_s=v["default.replication.throttle"],
            task_execution_alert_timeout_s=
                v["task.execution.alerting.threshold.ms"] / 1000.0,
            auto_adjust_concurrency=v["auto.adjust.concurrency"],
        )
