"""ConfigDef: typed config definitions with defaults and validators.

Reference: core ``common/config/ConfigDef.java`` — ``define(name, type,
default, validator, importance, doc)``, type coercion (STRING/INT/LONG/
DOUBLE/BOOLEAN/LIST/CLASS), unknown-key tolerance, and ``AbstractConfig``'s
``getConfiguredInstance`` reflective plugin loading (here: dotted-path or
registry-name resolution).
"""

from __future__ import annotations

import enum
import importlib
import os
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from cruise_control_tpu.common.exceptions import ConfigError

# ``${env:VAR}`` value indirection: secrets (TLS keystore passwords, webhook
# tokens) stay out of properties files and are pulled from the process
# environment when the config is loaded.
_ENV_REF = re.compile(r"\$\{env:([A-Za-z_][A-Za-z0-9_]*)\}")


def resolve_env_refs(raw: Any) -> Any:
    """Substitute every ``${env:VAR}`` occurrence in a string value with the
    environment variable's current value.  Non-strings and strings without a
    reference pass through untouched; referencing an unset variable is a
    ConfigError (a silently-empty secret is worse than a startup failure)."""
    if not isinstance(raw, str) or "${env:" not in raw:
        return raw

    def _sub(m: "re.Match[str]") -> str:
        var = m.group(1)
        if var not in os.environ:
            raise ConfigError(
                f"config value references ${{env:{var}}} but {var} is not "
                "set in the environment")
        return os.environ[var]

    return _ENV_REF.sub(_sub, raw)


class ConfigType(enum.Enum):
    STRING = "string"
    INT = "int"
    LONG = "long"
    DOUBLE = "double"
    BOOLEAN = "boolean"
    LIST = "list"          # comma-separated string → List[str]
    CLASS = "class"        # dotted path resolved at get time


_NO_DEFAULT = object()


def range_validator(lo=None, hi=None) -> Callable[[str, Any], None]:
    def check(name, value):
        if lo is not None and value < lo:
            raise ConfigError(f"{name}={value} below minimum {lo}")
        if hi is not None and value > hi:
            raise ConfigError(f"{name}={value} above maximum {hi}")
    return check


def in_validator(*allowed) -> Callable[[str, Any], None]:
    def check(name, value):
        if value not in allowed:
            raise ConfigError(f"{name}={value!r} not one of {allowed}")
    return check


@dataclass
class ConfigKey:
    name: str
    config_type: ConfigType
    default: Any = _NO_DEFAULT
    validator: Optional[Callable[[str, Any], None]] = None
    doc: str = ""

    @property
    def has_default(self) -> bool:
        return self.default is not _NO_DEFAULT


class ConfigDef:
    def __init__(self):
        self._keys: Dict[str, ConfigKey] = {}

    def define(self, name: str, config_type: ConfigType, default: Any = _NO_DEFAULT,
               validator: Optional[Callable[[str, Any], None]] = None,
               doc: str = "") -> "ConfigDef":
        if name in self._keys:
            raise ConfigError(f"duplicate config key {name}")
        self._keys[name] = ConfigKey(name, config_type, default, validator, doc)
        return self

    def keys(self) -> Dict[str, ConfigKey]:
        return dict(self._keys)

    def merge(self, other: "ConfigDef") -> "ConfigDef":
        for k in other._keys.values():
            if k.name not in self._keys:
                self._keys[k.name] = k
        return self

    # --------------------------------------------------------------- parse

    def parse(self, props: Dict[str, Any]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, key in self._keys.items():
            if name in props:
                value = self._coerce(key, props[name])
            elif key.has_default:
                # Defaults go through the same coercion so a LIST default
                # given as a comma string becomes a list.
                value = (None if key.default is None
                         else self._coerce(key, key.default))
            else:
                raise ConfigError(f"missing required config {name}")
            if key.validator is not None and value is not None:
                key.validator(name, value)
            out[name] = value
        return out

    @staticmethod
    def _coerce(key: ConfigKey, raw: Any) -> Any:
        t = key.config_type
        try:
            if raw is None:
                return None
            # Programmatic overrides get the same ${env:VAR} indirection as
            # properties files (load_properties already resolved those).
            raw = resolve_env_refs(raw)
            if t is ConfigType.STRING or t is ConfigType.CLASS:
                return str(raw)
            if t in (ConfigType.INT, ConfigType.LONG):
                return int(raw)
            if t is ConfigType.DOUBLE:
                return float(raw)
            if t is ConfigType.BOOLEAN:
                if isinstance(raw, bool):
                    return raw
                return str(raw).strip().lower() in ("true", "1", "yes")
            if t is ConfigType.LIST:
                if isinstance(raw, (list, tuple)):
                    return [str(x) for x in raw]
                return [s.strip() for s in str(raw).split(",") if s.strip()]
        except (TypeError, ValueError) as e:
            raise ConfigError(f"bad value for {key.name}: {raw!r} ({e})") from None
        raise ConfigError(f"unknown config type {t}")


def load_properties(path: str) -> Dict[str, str]:
    """Java-style ``key=value`` properties file (# comments, blank lines)."""
    props: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or line.startswith("!"):
                continue
            if "=" in line:
                k, _, v = line.partition("=")
                props[k.strip()] = resolve_env_refs(v.strip())
    return props


def get_configured_instance(dotted_or_name: str, registry: Optional[Dict] = None,
                            config=None, **kwargs):
    """Reflective plugin loading (AbstractConfig.getConfiguredInstance).

    When ``config`` is given, it is passed to the plugin iff its constructor
    can receive it — a declared ``config`` parameter or a ``**kwargs``
    catch-all (the Kafka-style ``def __init__(self, **configs)`` shape) —
    mirroring the reference's configure(configs) contract without breaking
    plugins that take no configuration."""
    if registry and dotted_or_name in registry:
        cls = registry[dotted_or_name]
    else:
        bare = dotted_or_name.rsplit(".", 1)
        if len(bare) != 2:
            raise ConfigError(f"unknown plugin {dotted_or_name}")
        mod, name = bare
        try:
            cls = getattr(importlib.import_module(mod), name)
        except (ImportError, AttributeError) as e:
            raise ConfigError(
                f"cannot instantiate {dotted_or_name}: {e}") from None
    if config is not None and cls.__init__ is not object.__init__:
        # (object.__init__'s signature advertises *args/**kwargs but a
        # class without its own __init__ takes no arguments at all.)
        import inspect
        try:
            params = inspect.signature(cls.__init__).parameters.values()
        except (TypeError, ValueError):
            params = ()
        if any(p.kind is p.VAR_KEYWORD or p.name == "config"
               for p in params):
            kwargs["config"] = config
    return cls(**kwargs)
