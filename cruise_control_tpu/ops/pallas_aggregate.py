"""Pallas TPU kernel for the aggregate-recompute hot path.

``analyzer/context.compute_aggregates`` reduces every replica's load vector
onto its broker — eight independent channels (4 resources, replica/leader
counts, potential NW-out, leader bytes-in) summed by broker id over the
[R]-long replica axis, at every round boundary and aggregate resync.  XLA
lowers ``jax.ops.segment_sum`` on TPU to a sort-based scatter over HBM;
this kernel instead streams replica chunks through VMEM once and builds the
whole [channels, B] result with one-hot MXU matmuls into a VMEM-resident
accumulator:

- grid over replica chunks (TPU grid steps run sequentially, so the output
  block — revisited by every step — accumulates without atomics);
- per chunk: ``onehot[c, b] = (broker[c] == b)`` via ``broadcasted_iota``
  compare, then ``channels.T @ onehot`` on the MXU ([K, CHUNK] @
  [CHUNK, B]);
- the broker axis rides the lane dimension (padded to 128) so the [K, B]
  accumulator tiles cleanly; K=8 channels sit on sublanes.

Traffic: the replica data crosses HBM exactly once (4 + 4 bytes per
replica per channel-group) and the accumulator never leaves VMEM —
~2600 × 128 × 4 B ≈ 1.3 MB at north-star scale.

The same function runs everywhere: off-TPU it falls back to
``segment_sum`` with identical semantics — chosen by an explicit backend
check at trace time, because under an outer jit a Mosaic lowering error
surfaces at COMPILE time where no try/except here could catch it — and
tests drive the kernel in interpret mode against that fallback.  NOTE: the
kernel has only ever executed in interpret mode in this environment (the
TPU tunnel was down for the whole round) — the lowering is written to the
TPU tiling rules but is gated OFF by default until a real-chip run
validates it (`CC_PALLAS_AGG=1` opts in; see pallas_aggregates_enabled).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

#: Replicas per grid step.  512×(B padded to 128-multiples) one-hot tiles:
#: 512 × 2688 × 4 B ≈ 5.5 MB VMEM at north-star scale — inside the ~16 MB
#: budget with the accumulator and channel blocks.
CHUNK = 512


def pallas_aggregates_enabled() -> bool:
    """Kernel gate: CC_PALLAS_AGG=1 forces on, =0 forces off; default OFF
    (the kernel is untested on real TPU hardware in this environment — flip
    the default after a validated on-chip run)."""
    flag = os.environ.get("CC_PALLAS_AGG", "")
    if flag == "1":
        return True
    return False


def _kernel_impl(pl, ch_ref, broker_ref, out_ref):
    """One replica chunk: out[K, B] += channels[K, CHUNK] @ onehot[CHUNK, B]."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    chunk = broker_ref.shape[1]
    b = out_ref.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, (chunk, b), 1)
    onehot = (broker_ref[0, :, None] == cols).astype(jnp.float32)
    out_ref[:] += jnp.dot(ch_ref[:], onehot,
                          preferred_element_type=jnp.float32)


def _pallas_sums(channels_t: jnp.ndarray, broker2d: jnp.ndarray,
                 b_pad: int, interpret: bool) -> jnp.ndarray:
    from jax.experimental import pallas as pl

    k, r = channels_t.shape
    grid = r // CHUNK
    return pl.pallas_call(
        partial(_kernel_impl, pl),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((k, CHUNK), lambda i: (0, i)),
            pl.BlockSpec((1, CHUNK), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((k, b_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k, b_pad), jnp.float32),
        interpret=interpret,
    )(channels_t, broker2d)


def broker_channel_sums(channels: jnp.ndarray, broker: jnp.ndarray,
                        num_segments: int, *,
                        prefer_pallas: bool | None = None,
                        interpret: bool = False) -> jnp.ndarray:
    """f32[num_segments, K]: per-segment sums of ``channels`` ([R, K]) by
    ``broker`` ([R] int32, values in [0, num_segments)).

    Semantics are exactly ``jax.ops.segment_sum(channels, broker,
    num_segments)``; the Pallas path additionally requires padded/invalid
    rows to carry ZERO channels (the solver's ``state.valid`` masking
    already guarantees this — padded rows point at broker 0 with no load).
    ``prefer_pallas=None`` consults :func:`pallas_aggregates_enabled`; any
    trace-time Pallas failure (unsupported transform, non-TPU lowering)
    falls back to the XLA path.
    """
    if prefer_pallas is None:
        prefer_pallas = pallas_aggregates_enabled()
    if not interpret:
        # Backend eligibility is decided HERE, at trace time, with a plain
        # Python check — NOT by catching lowering errors: under an outer jit
        # (every production solve) pallas_call binds fine at trace and the
        # Mosaic lowering failure would only surface during the outer jit's
        # COMPILE, far outside any try block in this function.
        if not prefer_pallas or jax.default_backend() != "tpu":
            if prefer_pallas:
                _warn_fallback_once(
                    f"backend {jax.default_backend()!r} is not tpu")
            return jax.ops.segment_sum(channels, broker,
                                       num_segments=num_segments)
    r, k = channels.shape
    r_pad = -(-r // CHUNK) * CHUNK
    b_pad = -(-max(num_segments, 1) // 128) * 128
    ch = channels.astype(jnp.float32)
    br = broker.astype(jnp.int32)
    if r_pad != r:
        ch = jnp.pad(ch, ((0, r_pad - r), (0, 0)))
        # Padded rows: broker -1 matches no one-hot column.
        br = jnp.pad(br, (0, r_pad - r), constant_values=-1)
    try:
        out = _pallas_sums(ch.T, br.reshape(1, r_pad), b_pad,
                           interpret=interpret)
    except Exception as e:   # noqa: BLE001 — trace-time batching/API gaps
        # Trace-time failures only (e.g. an unsupported transform): compile-
        # time Mosaic errors cannot reach this handler — see above.
        _warn_fallback_once(f"{type(e).__name__}: {e}")
        return jax.ops.segment_sum(channels, broker,
                                   num_segments=num_segments)
    return out[:, :num_segments].T.astype(channels.dtype)


_warned = False


def _warn_fallback_once(why: str) -> None:
    """A silently-ignored CC_PALLAS_AGG=1 would make 'kernel on' benchmarks
    quietly measure the fallback; say so once."""
    global _warned
    if not _warned:
        _warned = True
        import logging
        logging.getLogger(__name__).warning(
            "pallas aggregate kernel requested but falling back to "
            "segment_sum: %s", why)
