"""Low-level TPU kernels (Pallas) with XLA fallbacks.

The compute path of the framework is plain JAX/XLA almost everywhere —
XLA's fusion is the right tool for the solver's tiles.  This package holds
the few ops where a hand-written TPU kernel beats what XLA emits, each with
a same-signature XLA fallback selected automatically off-TPU (and usable
for differential testing via interpret mode).
"""

from cruise_control_tpu.ops.pallas_aggregate import (
    broker_channel_sums,
    pallas_aggregates_enabled,
)

__all__ = ["broker_channel_sums", "pallas_aggregates_enabled"]
