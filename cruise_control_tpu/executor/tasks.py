"""Execution tasks and state tracking.

Reference: ``executor/ExecutionTask.java`` (state machine PENDING →
IN_PROGRESS → {COMPLETED, ABORTING → ABORTED, DEAD}), and
``executor/ExecutionTaskTracker.java`` (per-type per-state counters).
"""

from __future__ import annotations

import enum
import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from cruise_control_tpu.common.actions import ExecutionProposal


class TaskType(enum.Enum):
    INTER_BROKER_REPLICA_ACTION = "inter_broker_replica"
    INTRA_BROKER_REPLICA_ACTION = "intra_broker_replica"
    LEADER_ACTION = "leadership"


class ExecutionTaskState(enum.Enum):
    PENDING = "pending"
    IN_PROGRESS = "in_progress"
    ABORTING = "aborting"
    ABORTED = "aborted"
    DEAD = "dead"
    COMPLETED = "completed"


_VALID_TRANSITIONS = {
    ExecutionTaskState.PENDING: {ExecutionTaskState.IN_PROGRESS},
    ExecutionTaskState.IN_PROGRESS: {ExecutionTaskState.ABORTING,
                                     ExecutionTaskState.DEAD,
                                     ExecutionTaskState.COMPLETED},
    ExecutionTaskState.ABORTING: {ExecutionTaskState.ABORTED,
                                  ExecutionTaskState.DEAD},
}

_ids = itertools.count()


@dataclass
class ExecutionTask:
    proposal: ExecutionProposal
    task_type: TaskType
    execution_id: int = field(default_factory=lambda: next(_ids))
    state: ExecutionTaskState = ExecutionTaskState.PENDING
    start_time_ms: float = 0.0
    end_time_ms: float = 0.0
    alert_time_ms: float = 0.0

    def transition(self, to: ExecutionTaskState, now_ms: float = 0.0) -> None:
        allowed = _VALID_TRANSITIONS.get(self.state, set())
        if to not in allowed:
            raise ValueError(f"illegal transition {self.state} -> {to}")
        self.state = to
        if to is ExecutionTaskState.IN_PROGRESS:
            self.start_time_ms = now_ms
        elif to in (ExecutionTaskState.COMPLETED, ExecutionTaskState.ABORTED,
                    ExecutionTaskState.DEAD):
            self.end_time_ms = now_ms

    @property
    def done(self) -> bool:
        return self.state in (ExecutionTaskState.COMPLETED,
                              ExecutionTaskState.ABORTED, ExecutionTaskState.DEAD)

    @property
    def brokers_involved(self) -> List[int]:
        p = self.proposal
        out = {r.broker_id for r in p.old_replicas} | {r.broker_id for r in p.new_replicas}
        return sorted(out)


class ExecutionTaskTracker:
    """Per-type, per-state counters + data-movement progress
    (ExecutionTaskTracker.java:1-390)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: Dict[TaskType, Dict[ExecutionTaskState, List[ExecutionTask]]] = {
            t: {s: [] for s in ExecutionTaskState} for t in TaskType}
        self.finished_data_movement_mb: float = 0.0

    def add(self, task: ExecutionTask) -> None:
        with self._lock:
            self._tasks[task.task_type][task.state].append(task)

    def transition(self, task: ExecutionTask, to: ExecutionTaskState,
                   now_ms: float = 0.0) -> None:
        with self._lock:
            self._tasks[task.task_type][task.state].remove(task)
            task.transition(to, now_ms)
            self._tasks[task.task_type][task.state].append(task)
            if (to is ExecutionTaskState.COMPLETED
                    and task.task_type is TaskType.INTER_BROKER_REPLICA_ACTION):
                self.finished_data_movement_mb += (
                    task.proposal.inter_broker_data_to_move / 1e6)

    def count(self, task_type: TaskType, state: ExecutionTaskState) -> int:
        with self._lock:
            return len(self._tasks[task_type][state])

    def tasks_in(self, task_type: TaskType, state: ExecutionTaskState
                 ) -> List[ExecutionTask]:
        with self._lock:
            return list(self._tasks[task_type][state])

    def summary(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {t.value: {s.value: len(lst) for s, lst in by_state.items() if lst}
                    for t, by_state in self._tasks.items()}

    @property
    def all_done(self) -> bool:
        with self._lock:
            for by_state in self._tasks.values():
                for s in (ExecutionTaskState.PENDING, ExecutionTaskState.IN_PROGRESS,
                          ExecutionTaskState.ABORTING):
                    if by_state[s]:
                        return False
            return True
