"""Executor: applies optimization proposals to the live cluster.

Reference: ``executor/Executor.java:73-1545`` and its task-management
satellites.  All host-side control logic (no TPU involvement — this layer
throttles the managed cluster, not compute); the cluster-facing operations go
through a pluggable admin backend (fake in tests, a Kafka driver in
deployments) the way the reference splits Executor from
ExecutorUtils.scala/ExecutorAdminUtils.
"""

from cruise_control_tpu.executor.tasks import ExecutionTask, ExecutionTaskState, ExecutionTaskTracker
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.executor import Executor, ExecutorState

__all__ = [
    "ExecutionTask",
    "ExecutionTaskState",
    "ExecutionTaskTracker",
    "ExecutionTaskPlanner",
    "Executor",
    "ExecutorState",
]
