"""The executor state machine.

Reference: ``executor/Executor.java:73-1545`` — states NO_TASK →
STARTING_EXECUTION → INTER_BROKER_REPLICA_MOVEMENT → INTRA_BROKER_REPLICA_
MOVEMENT → LEADER_MOVEMENT → STOPPING; batched movements under per-broker
caps with progress polling (:1163-1330), task-dead/abort handling
(:1457-1540), user-triggered stop (:782), AIMD concurrency auto-tuning
(ConcurrencyAdjuster :313-375), and replication throttling around an
execution (ReplicationThrottleHelper.java:29-321).
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set

from cruise_control_tpu.common.actions import ExecutionProposal
from cruise_control_tpu.common.exceptions import OngoingExecutionError
from cruise_control_tpu.executor.backend import ClusterAdminBackend
from cruise_control_tpu.executor.journal import ExecutionJournal
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.subprocess_backend import (
    BackendCircuitOpenError,
    BackendTransportError,
)
from cruise_control_tpu.executor.strategies import AbstractReplicaMovementStrategy
from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskState,
    ExecutionTaskTracker,
    TaskType,
)
from cruise_control_tpu.obsvc import oplog as _oplog
from cruise_control_tpu.obsvc.audit import audit_log
from cruise_control_tpu.obsvc.execution import execution as _execution
from cruise_control_tpu.obsvc.tracer import tracer as _obsvc_tracer

LOG = logging.getLogger(__name__)
# Dedicated operation audit log (reference OPERATION_LOGGER,
# KafkaCruiseControlUtils / Executor.java:945): execution lifecycle events on
# their own logger name so deployments can route them to an audit sink.
OPERATION_LOG = logging.getLogger("cruisecontrol.operation")

# Floor for poll loops that spin against an UNAVAILABLE backend (paused
# circuit, journal adoption): storms tune progress_check_interval_s down to
# sub-millisecond for throughput, but a dead-peer probe at that cadence is a
# busy-wait.  The movement hot loops deliberately poll unfloored.
_POLL_FLOOR_S = 0.01


class ExecutorState(enum.Enum):
    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = "INTER_BROKER_REPLICA_MOVEMENT"
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = "INTRA_BROKER_REPLICA_MOVEMENT"
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT"
    # Admin-backend circuit open: in-flight work is held (not rotted to the
    # alert timeout) while the reconnecting backend probes for recovery.
    PAUSED_BACKEND_DOWN = "PAUSED_BACKEND_DOWN"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


@dataclass
class ConcurrencyAdjuster:
    """AIMD per-broker concurrency tuning (Executor.java:313-375): additive
    increase while the cluster looks healthy, multiplicative decrease on
    distress signals."""

    min_concurrency: int = 1
    max_concurrency: int = 12
    current: int = 5
    increase_step: int = 1
    decrease_factor: float = 2.0

    def on_healthy(self) -> int:
        self.current = min(self.max_concurrency, self.current + self.increase_step)
        return self.current

    def on_distress(self) -> int:
        self.current = max(self.min_concurrency,
                           int(self.current / self.decrease_factor))
        return self.current


@dataclass
class ExecutorConfig:
    concurrent_partition_movements_per_broker: int = 5
    concurrent_intra_broker_partition_movements: int = 2
    concurrent_leader_movements: int = 1000
    max_num_cluster_movements: int = 1250
    progress_check_interval_s: float = 0.01
    replication_throttle_bytes_per_s: Optional[int] = None
    task_execution_alert_timeout_s: float = 90.0
    auto_adjust_concurrency: bool = False


class Executor:
    """Applies proposal batches via the admin backend."""

    def __init__(self, backend: ClusterAdminBackend,
                 config: Optional[ExecutorConfig] = None,
                 strategy: Optional[AbstractReplicaMovementStrategy] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.backend = backend
        self.config = config or ExecutorConfig()
        self._strategy = strategy
        self._clock = clock
        self._state = ExecutorState.NO_TASK_IN_PROGRESS
        self._lock = threading.RLock()
        self._stop_requested = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tracker = ExecutionTaskTracker()
        self._planner: Optional[ExecutionTaskPlanner] = None
        self.adjuster = ConcurrencyAdjuster(
            max_concurrency=self.config.concurrent_partition_movements_per_broker * 2,
            current=self.config.concurrent_partition_movements_per_broker)
        self._on_finish: List[Callable[[], None]] = []
        self._pause_sampling: Optional[Callable[[], None]] = None
        self._resume_sampling: Optional[Callable[[], None]] = None
        self._generating_proposals_for_execution = False
        self.journal: Optional[ExecutionJournal] = None
        self.recovering = False
        self.last_journal_recovery: Optional[Dict] = None
        self._batch_meta: Dict = {"principal": None, "requestId": None}
        self._register_sensors()

    def _register_sensors(self) -> None:
        """Executor sensors (Sensors.md; Executor.java:259-275 caps)."""
        from cruise_control_tpu.common.metrics import registry
        from cruise_control_tpu.executor.tasks import (
            ExecutionTaskState as S,
            TaskType as T,
        )
        reg = registry()

        def task_count(task_type, state):
            def read():
                # Stale-gauge guard: the tracker is lifetime-cumulative, so a
                # finished batch's terminal states (aborted/dead) would stick
                # forever — the action gauges report the live batch only.
                if not self.has_ongoing_execution:
                    return 0
                return self.tracker.summary().get(task_type.value, {}).get(
                    state.value, 0)
            return read

        for kind, t in (("replica", T.INTER_BROKER_REPLICA_ACTION),
                        ("leadership", T.LEADER_ACTION)):
            for sname, s in (("in-progress", S.IN_PROGRESS),
                             ("pending", S.PENDING),
                             ("aborting", S.ABORTING),
                             ("aborted", S.ABORTED),
                             ("dead", S.DEAD)):
                reg.gauge(f"Executor.{kind}-action-{sname}", task_count(t, s))
        reg.gauge("Executor.ongoing-execution",
                  lambda: int(self.has_ongoing_execution))
        reg.gauge("Executor.inter-broker-partition-movements-per-broker-cap",
                  lambda: self.adjuster.current)
        reg.gauge("Executor.intra-broker-partition-movements-per-broker-cap",
                  lambda: self.config.concurrent_intra_broker_partition_movements)
        reg.gauge("Executor.leadership-movements-global-cap",
                  lambda: self.config.concurrent_leader_movements)
        self._sensor_started = reg.counter("Executor.execution-started")
        self._sensor_stopped = reg.counter("Executor.execution-stopped")
        self._sensor_stopped_by_user = reg.counter(
            "Executor.execution-stopped-by-user")
        # Materialized backend-failure visibility: every backend exception
        # the executor absorbs lands here, long before the alert timeout
        # would have made the damage visible as DEAD tasks.
        self._sensor_backend_errors = reg.counter("Executor.backend-errors")

    # ------------------------------------------------------------- wiring

    def set_sampling_hooks(self, pause: Callable[[], None],
                           resume: Callable[[], None]) -> None:
        """LoadMonitor pause/resume around executions (Executor :959-975)."""
        self._pause_sampling = pause
        self._resume_sampling = resume

    def add_finish_listener(self, fn: Callable[[], None]) -> None:
        self._on_finish.append(fn)

    # -------------------------------------------------------------- state

    @property
    def state(self) -> ExecutorState:
        with self._lock:
            return self._state

    @property
    def has_ongoing_execution(self) -> bool:
        return self.state is not ExecutorState.NO_TASK_IN_PROGRESS

    def set_generating_proposals_for_execution(self, flag: bool = True) -> None:
        """Reference Executor.setGeneratingProposalsForExecution :737 — blocks
        competing executions while proposals are being computed."""
        with self._lock:
            if flag and (self.has_ongoing_execution
                         or self._generating_proposals_for_execution):
                raise OngoingExecutionError("an execution is already in progress")
            self._generating_proposals_for_execution = flag

    def state_summary(self) -> Dict:
        out = {
            "state": self.state.value,
            "tasks": self.tracker.summary(),
            "finishedDataMovementMB": round(self.tracker.finished_data_movement_mb, 3),
            "concurrency": self.adjuster.current,
        }
        if self.recovering:
            out["journalRecovery"] = {"status": "recovering"}
        elif self.last_journal_recovery is not None:
            out["journalRecovery"] = self.last_journal_recovery
        return out

    # ------------------------------------------------------------ execute

    def execute_proposals(self, proposals: Sequence[ExecutionProposal],
                          wait: bool = True) -> None:
        """Start executing proposals (Executor.executeProposals :500)."""
        with self._lock:
            if self.has_ongoing_execution:
                raise OngoingExecutionError("an execution is already in progress")
            external = self.backend.in_progress_reassignments()
            if external:
                raise OngoingExecutionError(
                    f"{len(external)} reassignments already in progress "
                    "(externally initiated?)")
            self._generating_proposals_for_execution = False
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested.clear()
            self._planner = ExecutionTaskPlanner(self._strategy)
            total = min(len(proposals), self.config.max_num_cluster_movements)
            accepted = list(self._planner.add_proposals(list(proposals)[:total]))
            for t in accepted:
                self.tracker.add(t)
            # Per-tenant attribution: the requesting principal / correlation
            # id ride the request contextvars into this call (the servlet's
            # UserTaskManager copies the request context), and from here
            # into the journal batch_start line, the executor.batch span,
            # and the flight recorder's batch record.
            # Model lineage: the fingerprint the accepted proposals were
            # solved from (first stamped proposal wins — one batch, one
            # solve, one model generation).  Rides the journal batch_start
            # line and the oplog so a crash-recovered batch still knows
            # what data quality it was decided on.
            fp = next((f for f in (getattr(t.proposal, "fingerprint", None)
                                   for t in accepted) if f is not None), None)
            self._batch_meta = {"principal": _oplog.current_principal(),
                                "requestId": _oplog.current_request_id(),
                                "modelGeneration":
                                    fp.get("generation") if fp else None}
            if self.journal is not None:
                try:
                    self.journal.begin_batch(accepted, meta=self._batch_meta)
                except OSError:
                    LOG.exception("journal begin_batch failed; executing "
                                  "without crash protection")
            # Audit-log deltas are against this execution's start (the
            # tracker itself is lifetime-cumulative).
            self._exec_baseline = (
                {st: sum(self.tracker.count(t, st) for t in TaskType)
                 for st in (ExecutionTaskState.COMPLETED,
                            ExecutionTaskState.DEAD,
                            ExecutionTaskState.ABORTED)},
                self.tracker.finished_data_movement_mb)
        _execution().begin_batch(
            accepted, principal=self._batch_meta["principal"],
            request_id=self._batch_meta["requestId"])
        self._sensor_started.inc()
        OPERATION_LOG.info(
            "execution started: %d tasks (%d proposals requested, cap %d)",
            total, len(proposals), self.config.max_num_cluster_movements)
        _oplog.record("start", endpoint="executor.batch",
                      tasks=total, proposals=len(proposals),
                      request_id=self._batch_meta["requestId"],
                      generation=self._batch_meta.get("modelGeneration"))
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="proposal-execution")
        self._thread.start()
        if wait:
            self._thread.join()

    def user_triggered_stop_execution(self, user: bool = True) -> None:
        """Executor.userTriggeredStopExecution :782 (``user=False`` for
        service-initiated stops, e.g. self-healing preemption — the
        execution-stopped / execution-stopped-by-user sensors diverge)."""
        with self._lock:
            if self.has_ongoing_execution:
                self._state = ExecutorState.STOPPING_EXECUTION
                self._stop_requested.set()
                self._sensor_stopped.inc()
                OPERATION_LOG.info("execution stop requested (user=%s)", user)
                if user:
                    self._sensor_stopped_by_user.inc()

    # ---------------------------------------------------------- internals

    def _set_state(self, s: ExecutorState) -> None:
        with self._lock:
            if self._state is not ExecutorState.STOPPING_EXECUTION:
                self._state = s

    def _transition(self, task: ExecutionTask, to: ExecutionTaskState) -> None:
        """Tracker transition + write-ahead journal record (when enabled).
        The flight recorder observes BEFORE the tracker mutates task.state,
        so it sees both ends of the transition."""
        _execution().on_transition(task, to, self._now_ms())
        self.tracker.transition(task, to, self._now_ms())
        if self.journal is not None:
            try:
                self.journal.record_transition(task, to)
            except OSError:
                LOG.exception("journal transition write failed")

    def _backend_error(self, seam: str, exc: BaseException) -> None:
        """Materialize an absorbed backend failure (Executor.backend-errors)
        so peers dying is visible on /metrics before any alert timeout."""
        self._sensor_backend_errors.inc()
        LOG.debug("backend error at %s: %s", seam, exc, exc_info=exc)

    def _paused_wait(self, resume_state: ExecutorState) -> bool:
        """Hold the execution in PAUSED_BACKEND_DOWN while the reconnecting
        backend's circuit is open, probing for recovery.  True: backend is
        back, state restored to ``resume_state``.  False: a stop was
        requested while paused."""
        probe = getattr(self.backend, "probe", None)
        self._set_state(ExecutorState.PAUSED_BACKEND_DOWN)
        OPERATION_LOG.info("execution paused: admin backend circuit open")
        while not self._stop_requested.is_set():
            if probe is None or probe():
                self._set_state(resume_state)
                OPERATION_LOG.info(
                    "execution resumed: admin backend recovered")
                return True
            self._poll_sleep(floored=True)
        return False

    # ----------------------------------------------------- journal recovery

    def set_journal(self, journal: Optional[ExecutionJournal]) -> None:
        self.journal = journal

    def recover_from_journal(self, adoption_timeout_s: float = 30.0
                             ) -> Optional[Dict]:
        """Replay the write-ahead journal against the live backend: tasks
        the crashed process left non-terminal are re-adopted (still moving
        on the cluster — watch them drain), completed (no longer in
        progress: they finished while we were down), or rolled back (never
        submitted).  The summary is surfaced in /state as
        ``journalRecovery``; the journal file is retired unless the backend
        was unreachable (then it is kept for the next restart)."""
        if self.journal is None:
            return None
        replay = self.journal.replay()
        if replay is None:
            return None
        self.recovering = True
        summary: Dict = {"batchId": replay.batch_id,
                         "journaledTasks": len(replay.tasks),
                         "reAdopted": 0, "completed": 0, "rolledBack": 0,
                         "stillInFlight": 0}
        try:
            orphans = replay.orphans()
            if replay.complete or not orphans:
                summary["status"] = "clean"
                return summary
            try:
                in_prog = set(self.backend.in_progress_reassignments())
            except Exception as exc:  # noqa: BLE001 — backend down at boot
                self._backend_error("journal-recovery", exc)
                summary["status"] = "backend-unavailable"
                LOG.warning("journal recovery: backend unavailable; keeping "
                            "the journal for the next restart")
                return summary
            adopted = [t for t in orphans
                       if t.last_state == ExecutionTaskState.IN_PROGRESS.value
                       and t.topic_partition in in_prog]
            for t in orphans:
                if t in adopted:
                    continue
                if t.last_state == ExecutionTaskState.PENDING.value:
                    summary["rolledBack"] += 1
                else:
                    # Submitted but no longer on the cluster: it finished
                    # while we were down.
                    summary["completed"] += 1
            # Rebuild live tasks for the adopted set: real transports only
            # advance a reassignment when it is polled with finished(), so
            # the adoption loop must actively drive them, not just watch
            # in_progress_reassignments shrink.
            live = {t.execution_id: t.to_execution_task() for t in adopted}
            deadline = self._clock() + adoption_timeout_s
            while (adopted and self._clock() < deadline
                   and not self._stop_requested.is_set()):
                self._poll_sleep(floored=True)
                try:
                    for t in adopted:
                        self.backend.finished(live[t.execution_id])
                    in_prog = set(self.backend.in_progress_reassignments())
                except Exception as exc:  # noqa: BLE001 — peer flapping
                    self._backend_error("journal-recovery", exc)
                    break
                drained = [t for t in adopted
                           if t.topic_partition not in in_prog]
                summary["reAdopted"] += len(drained)
                adopted = [t for t in adopted if t.topic_partition in in_prog]
            summary["stillInFlight"] = len(adopted)
            summary["status"] = "reconciled"
            OPERATION_LOG.info(
                "journal recovery: batch %d — reAdopted=%d completed=%d "
                "rolledBack=%d stillInFlight=%d", replay.batch_id,
                summary["reAdopted"], summary["completed"],
                summary["rolledBack"], summary["stillInFlight"])
            return summary
        finally:
            self.recovering = False
            self.last_journal_recovery = summary
            if summary.get("status") != "backend-unavailable":
                try:
                    self.journal.mark_recovered()
                except OSError:
                    LOG.exception("failed to retire the recovered journal")

    def _run(self) -> None:
        # Root span: the execution thread has no request context, so each
        # batch is its own trace (phases + outcome counts as attrs); the
        # requesting principal / correlation id captured at accept time are
        # re-attached here for cross-referencing with the http.* span.
        attrs = {k: v for k, v in (("principal", self._batch_meta["principal"]),
                                   ("request_id", self._batch_meta["requestId"]))
                 if v is not None}
        with _obsvc_tracer().span("executor.batch", **attrs):
            self._run_impl()

    def _run_impl(self) -> None:
        try:
            if self._pause_sampling:
                self._pause_sampling()
            inter = self._planner.remaining_inter_broker_tasks
            throttled = [
                (t.proposal.topic_partition.topic, t.proposal.topic_partition.partition)
                for t in inter]
            if self.config.replication_throttle_bytes_per_s and throttled:
                throttled_brokers = sorted(
                    {b for t in inter for b in t.brokers_involved})
                try:
                    self.backend.set_throttles(
                        self.config.replication_throttle_bytes_per_s, throttled,
                        throttled_brokers,
                        proposals=[t.proposal for t in inter])
                except Exception as exc:  # noqa: BLE001 — same dead-peer
                    # policy as the movement submits: abort the execution
                    # with the planned tasks marked DEAD, not a dead thread
                    # with every task stuck PENDING.
                    self._backend_error("set-throttles", exc)
                    LOG.exception("throttle setup failed; aborting execution")
                    for t in self._planner.clear():
                        if t.state is ExecutionTaskState.PENDING:
                            self._transition(t, ExecutionTaskState.IN_PROGRESS)
                            self._transition(t, ExecutionTaskState.DEAD)
                    return
            tr = _obsvc_tracer()
            self._set_state(
                ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS)
            with tr.span("executor.inter-broker"):
                self._move_replicas(
                    TaskType.INTER_BROKER_REPLICA_ACTION,
                    self._planner.inter_broker_tasks,
                    self.backend.execute_replica_reassignments,
                    self.config.concurrent_partition_movements_per_broker)
            self._set_state(
                ExecutorState.INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS)
            with tr.span("executor.intra-broker"):
                self._move_replicas(
                    TaskType.INTRA_BROKER_REPLICA_ACTION,
                    self._planner.intra_broker_tasks,
                    self.backend.execute_logdir_moves,
                    self.config.concurrent_intra_broker_partition_movements)
            self._set_state(ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS)
            with tr.span("executor.leadership"):
                self._move_leaderships()
        finally:
            if self._stop_requested.is_set() and self._planner is not None:
                for t in self._planner.clear():
                    if t.state is ExecutionTaskState.PENDING:
                        self._transition(t, ExecutionTaskState.IN_PROGRESS)
                        self._transition(t, ExecutionTaskState.DEAD)
            if self.config.replication_throttle_bytes_per_s:
                try:
                    self.backend.clear_throttles()
                except Exception as exc:  # noqa: BLE001 — finally must finish
                    self._backend_error("clear-throttles", exc)
                    LOG.exception("failed to clear replication throttles")
            if self._resume_sampling:
                self._resume_sampling()
            with self._lock:
                self._state = ExecutorState.NO_TASK_IN_PROGRESS
            base_counts, base_mb = self._exec_baseline
            counts = {st: sum(self.tracker.count(t, st) for t in TaskType)
                      - base_counts[st]
                      for st in (ExecutionTaskState.COMPLETED,
                                 ExecutionTaskState.DEAD,
                                 ExecutionTaskState.ABORTED)}
            moved_mb = self.tracker.finished_data_movement_mb - base_mb
            # Close the flight recorder's batch: throughput summary + the
            # provenance-path histogram roll into the oplog line, the batch
            # span, and the self-healing audit entry below.
            exec_summary = _execution().end_batch(
                completed=counts[ExecutionTaskState.COMPLETED],
                dead=counts[ExecutionTaskState.DEAD],
                aborted=counts[ExecutionTaskState.ABORTED],
                moved_mb=moved_mb) or {}
            paths = exec_summary.get("pathHistogram") or {}
            OPERATION_LOG.info(
                "execution finished: completed=%d dead=%d aborted=%d "
                "moved=%.1fMB",
                counts[ExecutionTaskState.COMPLETED],
                counts[ExecutionTaskState.DEAD],
                counts[ExecutionTaskState.ABORTED],
                moved_mb)
            _oplog.record(
                "abort" if self._stop_requested.is_set() else "finish",
                endpoint="executor.batch",
                completed=counts[ExecutionTaskState.COMPLETED],
                dead=counts[ExecutionTaskState.DEAD],
                aborted=counts[ExecutionTaskState.ABORTED],
                moved_mb=round(moved_mb, 1),
                moves=exec_summary.get("moves"),
                request_id=self._batch_meta["requestId"],
                **paths)
            span = _obsvc_tracer().current()
            if span is not None:
                span.set("completed", counts[ExecutionTaskState.COMPLETED])
                span.set("dead", counts[ExecutionTaskState.DEAD])
                span.set("aborted", counts[ExecutionTaskState.ABORTED])
                span.set("moved_mb", round(moved_mb, 1))
                if paths:
                    span.set("provenance_paths", dict(paths))
            # Stage 3 of the self-healing audit: attach this batch's outcome
            # to the entry whose fix started it (no-op for user-triggered
            # executions with no pending entry).
            audit_log().attach_execution_outcome(
                completed=counts[ExecutionTaskState.COMPLETED],
                dead=counts[ExecutionTaskState.DEAD],
                aborted=counts[ExecutionTaskState.ABORTED],
                moved_mb=moved_mb,
                provenance_paths=paths or None)
            if self.journal is not None:
                try:
                    self.journal.end_batch(
                        {"completed": counts[ExecutionTaskState.COMPLETED],
                         "dead": counts[ExecutionTaskState.DEAD],
                         "aborted": counts[ExecutionTaskState.ABORTED]})
                except OSError:
                    LOG.exception("journal end_batch failed")
            for fn in self._on_finish:
                try:
                    fn()
                except Exception:       # noqa: BLE001 — listeners must not kill us
                    LOG.exception("execution finish listener failed")

    def _now_ms(self) -> float:
        return self._clock() * 1000.0

    def _poll_sleep(self, floored: bool = False) -> None:
        """One progress-poll interval; ``floored`` clamps to
        :data:`_POLL_FLOOR_S` for loops probing an unavailable backend."""
        interval = self.config.progress_check_interval_s
        time.sleep(max(interval, _POLL_FLOOR_S) if floored else interval)

    def _concurrency(self) -> int:
        return (self.adjuster.current if self.config.auto_adjust_concurrency
                else self.config.concurrent_partition_movements_per_broker)

    def _submit_batch(self, batch: List[ExecutionTask], submit_fn,
                      resume_state: ExecutorState) -> bool:
        """Submit one movement batch.  An open backend circuit pauses the
        execution and retries the same batch after recovery; any other
        failure marks the batch DEAD (the reference's task-dead handling,
        Executor.java:1457-1540).  False: the batch did not go out."""
        while not self._stop_requested.is_set():
            try:
                submit_fn(batch)
                return True
            except BackendCircuitOpenError as exc:
                self._backend_error("submit", exc)
                if not self._paused_wait(resume_state):
                    break              # stop requested while paused
            except Exception as exc:  # noqa: BLE001 — backend/peer failure
                self._backend_error("submit", exc)
                LOG.exception("movement submission failed; marking %d "
                              "tasks dead", len(batch))
                for t in batch:
                    self._transition(t, ExecutionTaskState.IN_PROGRESS)
                    self._transition(t, ExecutionTaskState.DEAD)
                if self.config.auto_adjust_concurrency:
                    _execution().record_tuner(
                        "decrease", "submit-failure",
                        self.adjuster.on_distress())
                return False
        # Stop requested before the batch went out: it is no longer in the
        # planner (batch_fn popped it), so account for it here.
        for t in batch:
            if t.state is ExecutionTaskState.PENDING:
                self._transition(t, ExecutionTaskState.IN_PROGRESS)
                self._transition(t, ExecutionTaskState.DEAD)
        return False

    def _extend_alert_windows(self, tasks: Sequence[ExecutionTask]) -> None:
        """A backend outage must not count against in-flight tasks' alert
        timeout — restart their clocks at resume."""
        now = self._now_ms()
        for t in tasks:
            if t.state is ExecutionTaskState.IN_PROGRESS:
                t.start_time_ms = now

    def _move_replicas(self, task_type: TaskType, batch_fn, submit_fn,
                       per_broker_cap: int) -> None:
        """Batched movement loop (interBrokerMoveReplicas :1163-1225)."""
        in_flight: Dict[int, int] = {}
        active: List[ExecutionTask] = []
        resume_state = self.state
        while not self._stop_requested.is_set():
            cap = (self._concurrency()
                   if task_type is TaskType.INTER_BROKER_REPLICA_ACTION
                   else per_broker_cap)
            ready = {b: cap for t in self._all_brokers(task_type) for b in [t]}
            batch = batch_fn(ready, in_flight)
            if batch:
                if not self._submit_batch(batch, submit_fn, resume_state):
                    continue
                for t in batch:
                    self._transition(t, ExecutionTaskState.IN_PROGRESS)
                    for b in t.brokers_involved:
                        in_flight[b] = in_flight.get(b, 0) + 1
                active.extend(batch)
            if not active:
                if not batch and self._planner_queue_empty(task_type):
                    break
                continue
            self._poll_sleep()
            still_active: List[ExecutionTask] = []
            paused = False
            for idx, t in enumerate(active):
                try:
                    fin = self.backend.finished(t)
                except BackendCircuitOpenError as exc:
                    self._backend_error("progress-poll", exc)
                    if self._paused_wait(resume_state):
                        self._extend_alert_windows(active)
                    # This task and everything unprocessed stay active; the
                    # outer loop re-polls (or aborts on stop).
                    still_active.extend(active[idx:])
                    paused = True
                    break
                except BackendTransportError as exc:
                    self._backend_error("progress-poll", exc)
                    fin = False
                if fin:
                    self._transition(t, ExecutionTaskState.COMPLETED)
                    for b in t.brokers_involved:
                        in_flight[b] = max(in_flight.get(b, 0) - 1, 0)
                elif (self._now_ms() - t.start_time_ms
                      > self.config.task_execution_alert_timeout_s * 1000):
                    self._transition(t, ExecutionTaskState.DEAD)
                    for b in t.brokers_involved:
                        in_flight[b] = max(in_flight.get(b, 0) - 1, 0)
                    if self.config.auto_adjust_concurrency:
                        _execution().record_tuner(
                            "decrease", "task-dead",
                            self.adjuster.on_distress())
                else:
                    still_active.append(t)
            if (not paused and self.config.auto_adjust_concurrency
                    and not still_active):
                _execution().record_tuner("increase", "batch-drained",
                                          self.adjuster.on_healthy())
            active = still_active
        # Stop requested: abort whatever is in flight.
        for t in active:
            self._transition(t, ExecutionTaskState.ABORTING)
            self._transition(t, ExecutionTaskState.ABORTED)

    def _planner_queue_empty(self, task_type: TaskType) -> bool:
        if task_type is TaskType.INTER_BROKER_REPLICA_ACTION:
            return not self._planner.remaining_inter_broker_tasks
        return not self._planner.remaining_intra_broker_tasks

    def _all_brokers(self, task_type: TaskType) -> Set[int]:
        tasks = (self._planner.remaining_inter_broker_tasks
                 if task_type is TaskType.INTER_BROKER_REPLICA_ACTION
                 else self._planner.remaining_intra_broker_tasks)
        out: Set[int] = set()
        for t in tasks:
            out.update(t.brokers_involved)
        return out

    def _move_leaderships(self) -> None:
        """Leadership batches (moveLeaderships :1281-1330)."""
        while not self._stop_requested.is_set():
            batch = self._planner.leadership_tasks(
                self.config.concurrent_leader_movements)
            if not batch:
                break
            if not self._submit_batch(
                    batch, self.backend.execute_preferred_leader_election,
                    ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS):
                continue
            for t in batch:
                self._transition(t, ExecutionTaskState.IN_PROGRESS)
            pending = list(batch)
            while pending and not self._stop_requested.is_set():
                self._poll_sleep()
                still = []
                for idx, t in enumerate(pending):
                    try:
                        if self._maybe_complete(t):
                            continue
                    except BackendCircuitOpenError as exc:
                        self._backend_error("progress-poll", exc)
                        if self._paused_wait(
                                ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS):
                            self._extend_alert_windows(pending)
                        still.extend(pending[idx:])
                        break
                    except BackendTransportError as exc:
                        self._backend_error("progress-poll", exc)
                    # Same dead-task timeout as the replica loops: a peer
                    # that dies after a successful election submit reads as
                    # finished()=False forever, and without this branch the
                    # executor would stay in LEADER_MOVEMENT for good.
                    if (self._now_ms() - t.start_time_ms
                            > self.config.task_execution_alert_timeout_s * 1000):
                        self._transition(t, ExecutionTaskState.DEAD)
                    else:
                        still.append(t)
                pending = still

    def _maybe_complete(self, t: ExecutionTask) -> bool:
        if self.backend.finished(t):
            self._transition(t, ExecutionTaskState.COMPLETED)
            return True
        return False
