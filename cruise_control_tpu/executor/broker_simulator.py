"""Scripted broker simulator — the out-of-process stand-in cluster.

The analog of the reference's embedded-broker integration harness
(``cruise-control-metrics-reporter/src/test/.../CCKafkaIntegrationTestHarness
.java``): a separate PROCESS that speaks an admin protocol, so the executor's
cluster driver (``subprocess_backend.SubprocessClusterBackend``) is exercised
over a real process boundary — serialization, request/response framing, dead
-peer behavior — not an in-process object graph.

Protocol: one JSON object per line on stdin, one JSON reply per line on
stdout (``{"id": n, "op": ...}`` → ``{"id": n, "ok": true, ...}``).  The op
surface mirrors the slices of the Kafka admin API the reference's executor
drives: partition reassignments (``ExecutorUtils.scala:31-93``), logdir moves
(``ExecutorAdminUtils.java:33-124``), preferred-leader election
(``ExecutorUtils.scala:94-114``), and incremental config changes for
replication throttles (``ReplicationThrottleHelper.java:29-321`` — the same
``*.replication.throttled.rate``/``.replicas`` keys).

Replication progress is poll-driven and deterministic: each ``is_done`` query
for a movement decrements its countdown (``polls_to_finish`` ticks), and
movements touching a failed broker never progress — which is how tests
exercise the executor's dead-task timeout path.

Run standalone: ``python -m cruise_control_tpu.executor.broker_simulator``.
No jax anywhere on this import path — the process must start in
milliseconds.
"""

from __future__ import annotations

import json
import random
import sys
import time
from typing import Dict, List, Optional, Tuple

TP = Tuple[str, int]

# ReplicationThrottleHelper.java:38-45 — the exact dynamic-config keys.
LEADER_THROTTLED_RATE = "leader.replication.throttled.rate"
FOLLOWER_THROTTLED_RATE = "follower.replication.throttled.rate"
LEADER_THROTTLED_REPLICAS = "leader.replication.throttled.replicas"
FOLLOWER_THROTTLED_REPLICAS = "follower.replication.throttled.replicas"


class BrokerSimulator:
    """In-memory cluster state + admin op handlers (usable in-process by unit
    tests; the __main__ loop wraps it in stdio framing)."""

    def __init__(self, polls_to_finish: int = 2):
        self.polls_to_finish = polls_to_finish
        # (topic, partition) -> {"replicas": [b...], "leader": b,
        #                        "logdirs": {b: dir}}
        self.partitions: Dict[TP, Dict] = {}
        # In-flight movements: key -> {"ticks": n, "apply": {...}}
        self._reassign: Dict[TP, Dict] = {}
        self._logdir: Dict[Tuple[str, int, int], Dict] = {}
        self._election: Dict[TP, Dict] = {}
        self.failed_brokers: set = set()
        self.offline_logdirs: Dict[int, set] = {}
        self.broker_configs: Dict[int, Dict[str, str]] = {}
        self.topic_configs: Dict[str, Dict[str, str]] = {}
        # Audit trail for test assertions.
        self.config_log: List[Dict] = []
        self.max_inflight = 0
        self.max_inflight_per_broker: Dict[int, int] = {}
        # Transport-level fault injection (op_chaos / --chaos-* flags):
        # per-request probabilities of added latency, a swallowed reply
        # (client read times out), or a connection reset.  Seeded so chaos
        # runs replay.
        self.chaos: Dict[str, float] = {"delay_p": 0.0, "delay_ms": 0.0,
                                        "drop_p": 0.0, "reset_p": 0.0}
        self._chaos_rng = random.Random(0)

    # ------------------------------------------------------------- handlers

    def handle(self, req: Dict) -> Dict:
        op = req.get("op")
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            return {"ok": False, "error": f"unknown op {op!r}"}
        try:
            out = fn(req) or {}
        except Exception as e:  # noqa: BLE001 — report, don't die
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        out.setdefault("ok", True)
        return out

    def op_bootstrap(self, req):
        for p in req["partitions"]:
            key = (p["topic"], int(p["partition"]))
            self.partitions[key] = {
                "replicas": [int(b) for b in p["replicas"]],
                "leader": int(p.get("leader", p["replicas"][0])),
                "logdirs": {int(b): int(d) for b, d in
                            (p.get("logdirs") or {}).items()},
            }

    def op_describe_topics(self, req):
        return {"partitions": [
            {"topic": t, "partition": p, "replicas": st["replicas"],
             "leader": st["leader"],
             "logdirs": {str(b): d for b, d in st["logdirs"].items()}}
            for (t, p), st in sorted(self.partitions.items())]}

    # -- movements

    def _track_inflight(self) -> None:
        per_broker: Dict[int, int] = {}
        for key, mv in self._reassign.items():
            for b in mv["brokers"]:
                per_broker[b] = per_broker.get(b, 0) + 1
        self.max_inflight = max(self.max_inflight,
                                len(self._reassign) + len(self._logdir))
        for b, n in per_broker.items():
            self.max_inflight_per_broker[b] = max(
                self.max_inflight_per_broker.get(b, 0), n)

    def op_alter_partition_reassignments(self, req):
        for r in req["reassignments"]:
            key = (r["topic"], int(r["partition"]))
            if key not in self.partitions:
                raise KeyError(f"unknown partition {key}")
            target = [int(b) for b in r["replicas"]]
            cur = self.partitions[key]
            stuck = bool(self.failed_brokers.intersection(
                set(target) | set(cur["replicas"])))
            self._reassign[key] = {
                "ticks": -1 if stuck else self.polls_to_finish,
                "target": target,
                "logdirs": {int(b): int(d) for b, d in
                            (r.get("logdirs") or {}).items()},
                "brokers": sorted(set(target) | set(cur["replicas"])),
            }
        self._track_inflight()

    def op_alter_replica_log_dirs(self, req):
        for r in req["moves"]:
            key = (r["topic"], int(r["partition"]), int(r["broker"]))
            tp = key[:2]
            if tp not in self.partitions:
                raise KeyError(f"unknown partition {tp}")
            stuck = key[2] in self.failed_brokers
            self._logdir[key] = {
                "ticks": -1 if stuck else self.polls_to_finish,
                "target": int(r["logdir"]),
            }
        self._track_inflight()

    def op_elect_leaders(self, req):
        for r in req["partitions"]:
            key = (r["topic"], int(r["partition"]))
            if key not in self.partitions:
                raise KeyError(f"unknown partition {key}")
            # Preferred = explicit target when given, else first alive
            # replica in assignment order (ExecutorUtils.scala:94-114).
            self._election[key] = {"ticks": 1,
                                   "leader": r.get("leader")}

    def op_list_partition_reassignments(self, req):
        return {"reassignments": [
            {"topic": t, "partition": p} for t, p in sorted(self._reassign)]}

    def op_is_done(self, req):
        kind = req.get("kind", "reassign")
        key = (req["topic"], int(req["partition"]))
        if kind == "reassign":
            return {"done": self._advance(self._reassign, key,
                                          self._apply_reassign)}
        if kind == "logdir":
            k3 = (*key, int(req["broker"]))
            return {"done": self._advance(self._logdir, k3,
                                          self._apply_logdir)}
        if kind == "leader":
            return {"done": self._advance(self._election, key,
                                          self._apply_election)}
        raise ValueError(f"unknown kind {kind!r}")

    def _advance(self, table, key, apply_fn) -> bool:
        mv = table.get(key)
        if mv is None:
            return True
        if mv["ticks"] < 0:          # stuck on a failed broker
            return False
        mv["ticks"] -= 1
        if mv["ticks"] > 0:
            return False
        apply_fn(key, mv)
        del table[key]
        return True

    def _apply_reassign(self, key: TP, mv) -> None:
        st = self.partitions[key]
        st["replicas"] = list(mv["target"])
        for b, d in mv["logdirs"].items():
            st["logdirs"][b] = d
        for b in list(st["logdirs"]):
            if b not in mv["target"]:
                del st["logdirs"][b]
        # Kafka keeps the current leader unless it was removed.
        if st["leader"] not in st["replicas"]:
            st["leader"] = st["replicas"][0]

    def _apply_logdir(self, key, mv) -> None:
        t, p, b = key
        self.partitions[(t, p)]["logdirs"][b] = mv["target"]

    def _apply_election(self, key: TP, mv) -> None:
        st = self.partitions[key]
        want = mv.get("leader")
        if want is not None and int(want) in st["replicas"] \
                and int(want) not in self.failed_brokers:
            st["leader"] = int(want)
            return
        for b in st["replicas"]:
            if b not in self.failed_brokers:
                st["leader"] = b
                break

    # -- configs (throttles)

    def op_incremental_alter_configs(self, req):
        entity_type = req["entity_type"]
        entity = req["entity"]
        table = (self.broker_configs.setdefault(int(entity), {})
                 if entity_type == "broker"
                 else self.topic_configs.setdefault(str(entity), {}))
        for c in req["ops"]:
            if c.get("op", "set") == "delete":
                table.pop(c["name"], None)
            else:
                table[c["name"]] = str(c["value"])
            self.config_log.append({"entity_type": entity_type,
                                    "entity": entity, **c})

    def op_describe_configs(self, req):
        """Single entity (``entity``) or batched (``entities`` list — the
        Kafka AdminClient describeConfigs shape, one round trip for many)."""
        def lookup(entity):
            if req["entity_type"] == "broker":
                return dict(self.broker_configs.get(int(entity), {}))
            return dict(self.topic_configs.get(str(entity), {}))

        if "entities" in req:
            return {"configs_by_entity": {str(e): lookup(e)
                                          for e in req["entities"]}}
        return {"configs": lookup(req["entity"])}

    # -- fault injection / introspection (test-only surface)

    def op_fail_broker(self, req):
        self.failed_brokers.add(int(req["broker"]))
        for mv in self._reassign.values():
            if self.failed_brokers.intersection(mv["brokers"]):
                mv["ticks"] = -1

    def op_restore_broker(self, req):
        self.failed_brokers.discard(int(req["broker"]))

    def op_fail_logdir(self, req):
        """Fault injection: mark one broker logdir offline (the state the
        reference's DiskFailureDetector reads via describeLogDirs)."""
        self.offline_logdirs.setdefault(int(req["broker"]), set()).add(
            int(req["logdir"]))

    def op_restore_logdir(self, req):
        dirs = self.offline_logdirs.get(int(req["broker"]))
        if dirs:
            dirs.discard(int(req["logdir"]))

    def op_describe_log_dirs(self, req):
        return {"offline": {str(b): sorted(d)
                            for b, d in self.offline_logdirs.items() if d}}

    def op_stats(self, req):
        return {"max_inflight": self.max_inflight,
                "max_inflight_per_broker": {
                    str(b): n for b, n in self.max_inflight_per_broker.items()},
                "config_log": self.config_log}

    def op_chaos(self, req):
        """Set the fault-injection knobs over the wire (any subset); replies
        with the resulting configuration.  ``seed`` re-seeds the chaos RNG so
        a storm run is replayable from its seed alone."""
        for k in ("delay_p", "delay_ms", "drop_p", "reset_p"):
            if k in req:
                self.chaos[k] = float(req[k])
        if "seed" in req:
            self._chaos_rng = random.Random(int(req["seed"]))
        return {"chaos": dict(self.chaos)}

    def chaos_action(self, op: Optional[str]) -> Optional[str]:
        """Roll the chaos dice for one request: None (serve normally),
        "drop" (swallow the request, send no reply), or "reset" (close the
        connection mid-protocol).  Control-plane ops are immune so a test
        can always re-arm/disarm chaos and shut the simulator down."""
        if op in _CHAOS_IMMUNE:
            return None
        ch, rng = self.chaos, self._chaos_rng
        if ch["delay_p"] > 0 and rng.random() < ch["delay_p"]:
            time.sleep(ch["delay_ms"] / 1000.0)
        if ch["reset_p"] > 0 and rng.random() < ch["reset_p"]:
            return "reset"
        if ch["drop_p"] > 0 and rng.random() < ch["drop_p"]:
            return "drop"
        return None

    def op_ping(self, req):
        return {}

    def op_auth(self, req):
        # Re-auth on an already-authenticated (or auth-free) stream is a
        # no-op success, so a client configured with a token works against a
        # token-free peer too.
        return {}


# Ops exempt from fault injection: chaos must stay steerable, auth failures
# must be deterministic, and a shutdown/bootstrap must always land.
_CHAOS_IMMUNE = {"chaos", "auth", "shutdown", "bootstrap", None}


def _serve_stream(sim: "BrokerSimulator", lines, write) -> Optional[str]:
    """Drain one JSON-lines stream; returns "shutdown" when a shutdown op
    arrived, "reset" when chaos cut the connection, None on EOF."""
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except json.JSONDecodeError as e:
            write(json.dumps({"ok": False, "error": f"bad json: {e}"}) + "\n")
            continue
        if req.get("op") == "shutdown":
            write(json.dumps({"id": req.get("id"), "ok": True}) + "\n")
            return "shutdown"
        action = sim.chaos_action(req.get("op"))
        if action == "reset":
            return "reset"
        if action == "drop":
            continue
        resp = sim.handle(req)
        resp["id"] = req.get("id")
        write(json.dumps(resp) + "\n")
    return None


def _serve_tcp(sim: "BrokerSimulator", port: int,
               auth_token: Optional[str] = None,
               ssl_cert: Optional[str] = None,
               ssl_key: Optional[str] = None,
               bind: str = "127.0.0.1") -> int:
    """Network-facing mode: the same JSON-lines admin protocol over a TCP
    socket (the shape of the reference's AdminClient->broker network edge —
    which inherits the cluster's SASL/SSL security).  Prints the bound port
    on stdout so a parent with port 0 can connect.

    Clients are served thread-per-connection — a real admin endpoint holds
    the service's long-lived driver connection AND operator tooling at once
    — with op handlers serialized by a lock, so cluster state stays
    consistent across concurrent clients.

    With ``auth_token`` set, each connection's first frame must be
    ``{"op": "auth", "token": <token>}``; anything else gets one error reply
    and a disconnect — an unauthenticated peer cannot move replicas or read
    cluster state.  ``ssl_cert``/``ssl_key`` wrap the listener in TLS,
    protecting the token and the admin stream in transit."""
    import errno
    import hmac
    import socket
    import threading

    srv = socket.create_server((bind, port))
    ssl_ctx = None
    if ssl_cert:
        from cruise_control_tpu.utils.netsec import server_ssl_context
        ssl_ctx = server_ssl_context(ssl_cert, ssl_key)
    print(json.dumps({"listening": srv.getsockname()[1]}), flush=True)
    state_lock = threading.Lock()
    shutdown_evt = threading.Event()
    raw_handle = sim.handle

    def locked_handle(req):
        with state_lock:
            return raw_handle(req)

    sim.handle = locked_handle

    def serve_client(conn):
        with conn:
            conn.settimeout(None)   # the accept loop's poll timeout must
            if ssl_ctx is not None:  # never cut a blocking client read
                # Handshake in the per-connection thread (never the accept
                # loop), bounded so a silent peer can't pin its thread.
                try:
                    conn.settimeout(15.0)
                    conn = ssl_ctx.wrap_socket(conn, server_side=True)
                    conn.settimeout(None)
                except OSError:
                    return
            rfile = conn.makefile("r", encoding="utf-8")
            wfile = conn.makefile("w", encoding="utf-8")

            def write(s: str) -> None:
                wfile.write(s)
                wfile.flush()

            try:
                if auth_token is not None:
                    first = rfile.readline()
                    try:
                        req = json.loads(first)
                    except (ValueError, TypeError):
                        req = {}
                    if not isinstance(req, dict):
                        # Valid-but-non-object JSON ('5', '[]') must be an
                        # auth rejection, not an AttributeError that unwinds
                        # the handler.
                        req = {}
                    if req.get("op") != "auth" or not hmac.compare_digest(
                            str(req.get("token", "")), auth_token):
                        write(json.dumps(
                            {"id": req.get("id"), "ok": False,
                             "error": "authentication required"}) + "\n")
                        return
                    write(json.dumps(
                        {"id": req.get("id"), "ok": True}) + "\n")
                outcome = _serve_stream(sim, rfile, write)
                if outcome == "shutdown":
                    shutdown_evt.set()
                # "reset": fall through — closing the socket mid-protocol
                # IS the injected fault the client observes.
            except OSError:
                # Unclean client disconnect (reset mid-read, broken pipe on
                # reply) must not kill the listener — cluster state survives
                # across connections.
                pass

    try:
        srv.settimeout(0.5)
        while not shutdown_evt.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError as e:
                # TLS handshake failure from a bad client must not kill the
                # listener — but a listener whose own socket is gone (closed
                # fd, ENOTSOCK, EINVAL from shutdown) will fail every accept
                # forever; continuing would busy-spin at 2 Hz for the life of
                # the process.  Per-connection errors keep looping; fatal
                # listener errors end the serve loop.
                if e.errno in (errno.EBADF, errno.ENOTSOCK, errno.EINVAL):
                    print(json.dumps({"error": f"listener socket unusable: "
                                               f"{e}"}), file=sys.stderr,
                          flush=True)
                    return 1
                continue
            threading.Thread(target=serve_client, args=(conn,),
                             daemon=True).start()
        return 0
    finally:
        srv.close()


def main(argv: Optional[List[str]] = None) -> int:
    polls = 2
    args = list(sys.argv[1:] if argv is None else argv)
    if "--polls-to-finish" in args:
        polls = int(args[args.index("--polls-to-finish") + 1])
    sim = BrokerSimulator(polls_to_finish=polls)
    # Fault injection from the command line (same knobs as op_chaos), so a
    # chaos soak needs no control connection to arm the faults.
    for flag, key in (("--chaos-delay-p", "delay_p"),
                      ("--chaos-delay-ms", "delay_ms"),
                      ("--chaos-drop-p", "drop_p"),
                      ("--chaos-reset-p", "reset_p")):
        if flag in args:
            sim.chaos[key] = float(args[args.index(flag) + 1])
    if "--chaos-seed" in args:
        sim._chaos_rng = random.Random(
            int(args[args.index("--chaos-seed") + 1]))
    if "--listen" in args:
        token = None
        if "--auth-token-file" in args:
            # A file, not argv: command lines are world-readable (/proc).
            from cruise_control_tpu.utils.netsec import read_secret_file
            token = read_secret_file(
                args[args.index("--auth-token-file") + 1], "admin auth token")
        cert = (args[args.index("--ssl-cert") + 1]
                if "--ssl-cert" in args else None)
        key = (args[args.index("--ssl-key") + 1]
               if "--ssl-key" in args else None)
        # Remote admin topologies (the reason the auth/TLS flags exist) need
        # a non-loopback bind; keep loopback the safe default.
        bind = (args[args.index("--bind") + 1]
                if "--bind" in args else "127.0.0.1")
        return _serve_tcp(sim, int(args[args.index("--listen") + 1]),
                          auth_token=token, ssl_cert=cert, ssl_key=key,
                          bind=bind)

    out = sys.stdout

    def write(s: str) -> None:
        out.write(s)
        out.flush()

    _serve_stream(sim, sys.stdin, write)
    return 0


if __name__ == "__main__":
    sys.exit(main())
