"""Cluster admin backend — the seam to the managed cluster.

Reference split: ``executor/ExecutorUtils.scala:31-114`` (reassignment znode
writes, preferred-leader election, in-flight queries),
``ExecutorAdminUtils.java`` (logdir moves), ``ReplicationThrottleHelper.java``
(throttle configs).  Here one protocol covers all three; the fake
implementation drives a ``FakeMetadataBackend`` and completes movements after
a configurable number of progress polls — the in-process stand-in for the
reference's embedded-broker integration harness.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Protocol, Sequence, Set, Tuple

from cruise_control_tpu.executor.tasks import ExecutionTask
from cruise_control_tpu.monitor.metadata import FakeMetadataBackend

TP = Tuple[str, int]


class ClusterAdminBackend(Protocol):
    def execute_replica_reassignments(self, tasks: Sequence[ExecutionTask]) -> None: ...

    def execute_logdir_moves(self, tasks: Sequence[ExecutionTask]) -> None: ...

    def execute_preferred_leader_election(self, tasks: Sequence[ExecutionTask]) -> None: ...

    def in_progress_reassignments(self) -> Set[TP]: ...

    def finished(self, task: ExecutionTask) -> bool: ...

    def offline_logdirs(self) -> Dict[int, List[int]]:
        """broker id → offline logdir ids (reference:
        ``AdminClient.describeLogDirs`` as used by
        ``DiskFailureDetector.java:1-118``); the disk-failure detector polls
        this through the executor's backend."""
        ...

    def set_throttles(self, rate_bytes_per_s: Optional[int],
                      partitions: Sequence[TP],
                      brokers: Sequence[int] = (),
                      proposals: Sequence = ()) -> None:
        """``brokers`` = every broker involved in the movements (old ∪ new
        replicas) and ``proposals`` the ExecutionProposals themselves —
        ReplicationThrottleHelper derives everything from the proposals:
        destinations that hold nothing yet still get rate configs, and the
        ADDING replicas go into the follower throttled-replicas lists."""
        ...

    def clear_throttles(self) -> None: ...


class FakeClusterBackend:
    """Applies movements to a FakeMetadataBackend after N polls per task."""

    def __init__(self, metadata_backend: FakeMetadataBackend, polls_to_finish: int = 2):
        self.metadata = metadata_backend
        self.polls_to_finish = polls_to_finish
        self._lock = threading.Lock()
        self._in_flight: Dict[int, int] = {}       # execution_id -> polls left
        self._tasks: Dict[int, ExecutionTask] = {}
        self.throttle_rate: Optional[int] = None
        self.throttled_partitions: List[TP] = []
        self.throttled_brokers: List[int] = []
        self.reassignment_log: List[TP] = []
        # Fault injection for disk-failure tests: broker → offline dir ids.
        self.offline_disks: Dict[int, List[int]] = {}

    # ------------------------------------------------------------- execute

    def execute_replica_reassignments(self, tasks) -> None:
        with self._lock:
            for t in tasks:
                self._in_flight[t.execution_id] = self.polls_to_finish
                self._tasks[t.execution_id] = t
                tp = t.proposal.topic_partition
                self.reassignment_log.append((tp.topic, tp.partition))

    def execute_logdir_moves(self, tasks) -> None:
        self.execute_replica_reassignments(tasks)

    def execute_preferred_leader_election(self, tasks) -> None:
        with self._lock:
            for t in tasks:
                self._in_flight[t.execution_id] = 1
                self._tasks[t.execution_id] = t

    # ------------------------------------------------------------ progress

    def in_progress_reassignments(self) -> Set[TP]:
        with self._lock:
            out = set()
            for tid in self._in_flight:
                tp = self._tasks[tid].proposal.topic_partition
                out.add((tp.topic, tp.partition))
            return out

    def finished(self, task: ExecutionTask) -> bool:
        with self._lock:
            left = self._in_flight.get(task.execution_id)
            if left is None:
                return True
            left -= 1
            if left <= 0:
                self._apply(task)
                del self._in_flight[task.execution_id]
                del self._tasks[task.execution_id]
                return True
            self._in_flight[task.execution_id] = left
            return False

    def _apply(self, task: ExecutionTask) -> None:
        p = task.proposal
        tp = p.topic_partition
        new = tuple(r.broker_id for r in p.new_replicas)
        self.metadata.apply_reassignment(tp.topic, tp.partition, new, new[0])

    # ----------------------------------------------------------- throttles

    def set_throttles(self, rate_bytes_per_s, partitions, brokers=(),
                      proposals=()) -> None:
        self.throttle_rate = rate_bytes_per_s
        self.throttled_partitions = list(partitions)
        self.throttled_brokers = list(brokers)

    def offline_logdirs(self) -> Dict[int, List[int]]:
        return {b: list(d) for b, d in self.offline_disks.items() if d}

    def clear_throttles(self) -> None:
        self.throttle_rate = None
        self.throttled_partitions = []
