"""Out-of-process cluster admin driver.

The first real (non-fake) ``ClusterAdminBackend``: it drives a cluster that
lives in ANOTHER PROCESS over JSON-lines pipes — the same three admin seams
the reference's executor drives against Kafka:

- replica reassignments        (``ExecutorUtils.scala:31-93``)
- logdir moves                 (``ExecutorAdminUtils.java:33-124``)
- preferred-leader election    (``ExecutorUtils.scala:94-114``)
- replication throttles        (``ReplicationThrottleHelper.java:29-321`` —
  the same ``(leader|follower).replication.throttled.(rate|replicas)``
  dynamic-config keys, set before an execution and removed after, preserving
  any pre-existing values we did not write)

The peer is normally ``broker_simulator`` (spawned by :meth:`spawn`), but
anything speaking the protocol works.  Transport failures during progress
polling surface as "not finished" so the executor's task-alert timeout path
(``Executor.java:1457-1540`` dead-task handling) — not an exception in the
progress thread — decides the outcome; submission failures raise.
"""

from __future__ import annotations

import json
import select
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu.executor.broker_simulator import (
    FOLLOWER_THROTTLED_RATE,
    FOLLOWER_THROTTLED_REPLICAS,
    LEADER_THROTTLED_RATE,
    LEADER_THROTTLED_REPLICAS,
)
from cruise_control_tpu.executor.tasks import ExecutionTask, TaskType

TP = Tuple[str, int]


class BackendTransportError(RuntimeError):
    """The admin peer died or broke protocol."""


class BackendCircuitOpenError(BackendTransportError):
    """The admin-backend circuit breaker is open: the call was refused
    without touching the transport.  Raised by the reconnecting wrapper
    (``resilience/reconnect.py``); defined here, next to its base, so the
    executor can catch it without importing the resilience package (which
    imports this module)."""


class SubprocessClusterBackend:
    """ClusterAdminBackend over a child process speaking JSON lines."""

    def __init__(self, proc: subprocess.Popen, request_timeout_s: float = 10.0):
        self.proc = proc
        self.request_timeout_s = request_timeout_s
        self._lock = threading.Lock()
        self._next_id = 0
        if proc is not None:
            self._rstream = proc.stdout
            self._wstream = proc.stdin
        # Configs we set (entity_type, entity, name) and replica-list entries
        # we merged in — clear_throttles removes exactly these, never a
        # pre-existing operator-set throttle.
        self._set_throttle_keys: List[Tuple[str, object, str]] = []
        self._added_list_entries: List[Tuple[str, str, List[str]]] = []

    # ---------------------------------------------------------------- spawn

    @classmethod
    def spawn(cls, partitions: Sequence[Dict], polls_to_finish: int = 2,
              request_timeout_s: float = 10.0) -> "SubprocessClusterBackend":
        """Start a broker_simulator child and bootstrap it with
        ``partitions`` (dicts: topic/partition/replicas/leader/logdirs)."""
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "cruise_control_tpu.executor.broker_simulator",
             "--polls-to-finish", str(polls_to_finish)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        backend = cls(proc, request_timeout_s=request_timeout_s)
        backend.request("bootstrap", partitions=list(partitions))
        return backend

    def close(self) -> None:
        try:
            self.request("shutdown")
        except BackendTransportError:
            pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    # ------------------------------------------------------------ transport

    def request(self, op: str, **kwargs) -> Dict:
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            msg = json.dumps({"id": rid, "op": op, **kwargs})
            try:
                self._wstream.write(msg + "\n")
                self._wstream.flush()
            except (BrokenPipeError, OSError, ValueError) as e:
                # A write timeout (possible now that sockets carry one)
                # leaves an indeterminate partial frame on a possibly-live
                # peer — poison so the desync cannot corrupt later requests.
                self._poison(f"write failed: {e}")
                raise BackendTransportError(f"peer write failed: {e}") from e
            line = self._read_line()
            try:
                resp = json.loads(line)
            except json.JSONDecodeError as e:
                self._poison(f"bad reply {line!r}")
                raise BackendTransportError(f"bad reply {line!r}") from e
            if resp.get("id") != rid:
                # The stream is now desynced: a late reply to THIS request
                # would be read by the NEXT one, failing every future call
                # against a healthy peer.  Kill the peer so subsequent
                # requests fail fast as transport errors instead.
                self._poison(f"reply id {resp.get('id')} != {rid}")
                raise BackendTransportError(
                    f"reply id {resp.get('id')} != request id {rid}")
        if not resp.get("ok"):
            raise BackendTransportError(resp.get("error", "peer error"))
        return resp

    def _poison(self, why: str) -> None:
        """The request/response framing is unrecoverable (timeout left an
        unread reply in flight, or garbage on the pipe): terminate the peer
        so the failure mode is a clean dead-peer, not an off-by-one reply
        stream."""
        if self.proc is None:
            return
        try:
            self.proc.kill()
        except OSError:
            pass

    def _read_line(self) -> str:
        ready, _, _ = select.select([self._rstream], [],
                                    [], self.request_timeout_s)
        if not ready:
            alive = self.proc.poll() is None if self.proc else False
            # A late reply would desync every subsequent request (it reads
            # the previous answer); poison the peer so this stays a clean
            # transport failure.
            self._poison("request timeout")
            raise BackendTransportError(
                f"no reply within {self.request_timeout_s}s "
                f"(peer was alive={alive})")
        try:
            line = self._rstream.readline()
        except OSError as e:
            # Socket resets / mid-line timeouts are transport failures like
            # any other, and leave the stream desynced.
            self._poison(f"read failed: {e}")
            raise BackendTransportError(f"peer read failed: {e}") from e
        if not line:
            raise BackendTransportError("peer closed the pipe")
        return line

    # ------------------------------------------- ClusterAdminBackend surface

    def execute_replica_reassignments(self, tasks: Sequence[ExecutionTask]) -> None:
        reassignments = []
        for t in tasks:
            p = t.proposal
            reassignments.append({
                "topic": p.topic_partition.topic,
                "partition": p.topic_partition.partition,
                "replicas": [r.broker_id for r in p.new_replicas],
                "logdirs": {str(r.broker_id): r.logdir
                            for r in p.new_replicas if r.logdir is not None},
            })
        if reassignments:
            self.request("alter_partition_reassignments",
                         reassignments=reassignments)

    def execute_logdir_moves(self, tasks: Sequence[ExecutionTask]) -> None:
        moves = []
        for t in tasks:
            p = t.proposal
            for old, new in p.replicas_to_move_between_disks:
                moves.append({"topic": p.topic_partition.topic,
                              "partition": p.topic_partition.partition,
                              "broker": old.broker_id,
                              "logdir": new.logdir})
        if moves:
            self.request("alter_replica_log_dirs", moves=moves)

    def execute_preferred_leader_election(self, tasks: Sequence[ExecutionTask]) -> None:
        # The preferred leader is position 0 of the PROPOSAL's replica order —
        # against Kafka the reassignment has already reordered the assignment
        # and a plain electLeaders suffices (ExecutorUtils.scala:94-114); the
        # wire op carries the target explicitly so the peer need not have
        # observed the reorder.
        parts = [{"topic": t.proposal.topic_partition.topic,
                  "partition": t.proposal.topic_partition.partition,
                  "leader": t.proposal.new_leader.broker_id}
                 for t in tasks]
        if parts:
            self.request("elect_leaders", partitions=parts)

    def in_progress_reassignments(self) -> Set[TP]:
        resp = self.request("list_partition_reassignments")
        return {(r["topic"], int(r["partition"]))
                for r in resp["reassignments"]}

    def offline_logdirs(self) -> Dict[int, List[int]]:
        resp = self.request("describe_log_dirs")
        return {int(b): [int(x) for x in dirs]
                for b, dirs in resp.get("offline", {}).items()}

    def finished(self, task: ExecutionTask,
                 raise_transport_errors: bool = False) -> bool:
        p = task.proposal
        try:
            if task.task_type is TaskType.LEADER_ACTION:
                return self._is_done("leader", p)
            if task.task_type is TaskType.INTRA_BROKER_REPLICA_ACTION:
                return all(
                    self._is_done("logdir", p, broker=old.broker_id)
                    for old, _ in p.replicas_to_move_between_disks)
            return self._is_done("reassign", p)
        except BackendTransportError:
            if raise_transport_errors:
                # The reconnecting wrapper wants the raw signal: it decides
                # between rebuilding the transport and pausing the executor.
                raise
            # Let the executor's alert-timeout mark the task dead instead of
            # blowing up the progress loop (Executor.java:1457-1540).
            return False

    def _is_done(self, kind: str, proposal, **extra) -> bool:
        resp = self.request("is_done", kind=kind,
                            topic=proposal.topic_partition.topic,
                            partition=proposal.topic_partition.partition,
                            **extra)
        return bool(resp["done"])

    # ----------------------------------------------------------- throttles

    def set_throttles(self, rate_bytes_per_s: Optional[int],
                      partitions: Sequence[TP],
                      brokers: Sequence[int] = (),
                      proposals: Sequence = ()) -> None:
        """ReplicationThrottleHelper.setThrottles: rate configs on every
        involved broker (old ∪ new replicas — a destination holding nothing
        yet still needs its follower rate), LEADER throttled-replica lists
        from the OLD replicas (they serve the catch-up reads), FOLLOWER
        lists from the ADDING replicas (they issue the catch-up fetches)."""
        if rate_bytes_per_s is None or not (partitions or proposals):
            return
        involved: Set[int] = set(brokers)
        leader_by_topic: Dict[str, List[str]] = {}
        follower_by_topic: Dict[str, List[str]] = {}
        if proposals:
            for p in proposals:
                tp = p.topic_partition
                old = [r.broker_id for r in p.old_replicas]
                adding = [r.broker_id for r in p.replicas_to_add]
                involved.update(old)
                involved.update(adding)
                leader_by_topic.setdefault(tp.topic, []).extend(
                    f"{tp.partition}:{b}" for b in old)
                follower_by_topic.setdefault(tp.topic, []).extend(
                    f"{tp.partition}:{b}" for b in adding)
        else:
            # Partition-only callers (no proposals): fall back to the current
            # assignment for both lists.
            assignment = {
                (d["topic"], int(d["partition"])): [int(b) for b in d["replicas"]]
                for d in self.request("describe_topics")["partitions"]}
            wanted = set(map(tuple, partitions))
            for (topic, part), replicas in assignment.items():
                if (topic, part) not in wanted:
                    continue
                involved.update(replicas)
                leader_by_topic.setdefault(topic, []).extend(
                    f"{part}:{b}" for b in replicas)
                follower_by_topic.setdefault(topic, []).extend(
                    f"{part}:{b}" for b in replicas)
        # Rate configs: set only where NOT already set by an operator
        # (ReplicationThrottleHelper.setThrottledRateIfUnset), recording what
        # we set so cleanup removes exactly that.  Existing configs are read
        # with ONE batched describe per entity type (Kafka AdminClient
        # describeConfigs takes a collection) — 2.6K sequential round trips
        # before the first movement is not a startup cost to pay.
        broker_cfgs = self.request(
            "describe_configs", entity_type="broker",
            entities=sorted(involved))["configs_by_entity"] if involved else {}
        topics = sorted(set(leader_by_topic) | set(follower_by_topic))
        topic_cfgs = self.request(
            "describe_configs", entity_type="topic",
            entities=topics)["configs_by_entity"] if topics else {}
        for b in sorted(involved):
            existing = broker_cfgs.get(str(b), {})
            ops = [{"name": name, "value": rate_bytes_per_s}
                   for name in (LEADER_THROTTLED_RATE, FOLLOWER_THROTTLED_RATE)
                   if name not in existing]
            if ops:
                self._alter("broker", b, ops)
        # Replica lists: MERGE our entries into any operator-set list and
        # remember only our additions (setLeaderThrottledReplicas merge +
        # removeLeaderThrottledReplicasFromTopic restore).
        for topic in topics:
            existing = topic_cfgs.get(topic, {})
            ops = []
            for name, wanted in ((LEADER_THROTTLED_REPLICAS,
                                  leader_by_topic.get(topic)),
                                 (FOLLOWER_THROTTLED_REPLICAS,
                                  follower_by_topic.get(topic))):
                if not wanted:
                    continue
                prior = [e for e in (existing.get(name) or "").split(",") if e]
                if prior == ["*"]:
                    continue    # operator throttles ALL replicas already
                added = sorted(set(wanted) - set(prior))
                if not added:
                    continue
                ops.append({"name": name, "value": ",".join(prior + added)})
                self._added_list_entries.append((topic, name, added))
            if ops:
                self.request("incremental_alter_configs", entity_type="topic",
                             entity=topic, ops=ops)

    def _alter(self, entity_type: str, entity, ops: List[Dict]) -> None:
        self.request("incremental_alter_configs", entity_type=entity_type,
                     entity=entity, ops=ops)
        for c in ops:
            key = (entity_type, entity, c["name"])
            if c.get("op", "set") != "delete" and key not in self._set_throttle_keys:
                self._set_throttle_keys.append(key)

    def clear_throttles(self) -> None:
        """Restore exactly the pre-execution throttle state: delete the rate
        keys WE set, and strip OUR entries from the replica lists, leaving
        operator-set values untouched (ReplicationThrottleHelper
        .removeThrottles semantics)."""
        keys, self._set_throttle_keys = self._set_throttle_keys, []
        entries, self._added_list_entries = self._added_list_entries, []
        try:
            for entity_type, entity, name in keys:
                self.request("incremental_alter_configs",
                             entity_type=entity_type, entity=entity,
                             ops=[{"name": name, "op": "delete"}])
            for topic, name, added in entries:
                current = self.request(
                    "describe_configs", entity_type="topic",
                    entity=topic)["configs"].get(name, "")
                keep = [e for e in current.split(",")
                        if e and e not in set(added)]
                op = ({"name": name, "value": ",".join(keep)} if keep
                      else {"name": name, "op": "delete"})
                self.request("incremental_alter_configs", entity_type="topic",
                             entity=topic, ops=[op])
        except BackendTransportError:
            pass  # peer gone — nothing left to throttle

    # --------------------------------------------------------- test surface

    def describe_topics(self) -> List[Dict]:
        return self.request("describe_topics")["partitions"]

    def stats(self) -> Dict:
        return self.request("stats")


class SocketClusterBackend(SubprocessClusterBackend):
    """The same admin driver over a TCP SOCKET — the network-facing edge.

    Where SubprocessClusterBackend pipes to a child it owns, this connects
    to an admin endpoint by address (a ``broker_simulator --listen`` peer,
    or anything speaking the protocol), the way the reference's executor
    reaches brokers through a networked AdminClient.  ``spawn_networked``
    starts a listener child on an ephemeral port and connects to it —
    executor traffic then crosses a real socket, not inherited pipes.
    """

    def __init__(self, host: str, port: int, request_timeout_s: float = 10.0,
                 proc: Optional[subprocess.Popen] = None,
                 auth_secret: Optional[str] = None,
                 ssl_enable: bool = False,
                 ssl_cafile: Optional[str] = None):
        import socket

        sock = socket.create_connection((host, port),
                                        timeout=request_timeout_s)
        if ssl_enable or ssl_cafile:
            from cruise_control_tpu.utils.netsec import client_ssl_context
            sock = client_ssl_context(ssl_cafile).wrap_socket(sock)
        self._sock = sock
        # Keep a socket timeout as the mid-line backstop: select() only
        # bounds time-to-FIRST-byte, so a peer stalling after half a reply
        # would otherwise block readline() forever with self._lock held.  A
        # mid-line timeout raises OSError in _read_line, which poisons the
        # stream — the desync is moot because the peer is killed.
        self._sock.settimeout(request_timeout_s)
        super().__init__(proc, request_timeout_s=request_timeout_s)
        self._rstream = self._sock.makefile("r", encoding="utf-8")
        self._wstream = self._sock.makefile("w", encoding="utf-8")
        if auth_secret is not None:
            # First frame on the wire must authenticate (broker_simulator
            # --auth-token-file semantics); a rejection surfaces as the
            # BackendTransportError this raises.
            self.request("auth", token=auth_secret)

    @classmethod
    def spawn_networked(cls, partitions: Sequence[Dict],
                        polls_to_finish: int = 2,
                        request_timeout_s: float = 10.0,
                        auth_token_file: Optional[str] = None,
                        auth_secret: Optional[str] = None,
                        ssl_cert: Optional[str] = None,
                        ssl_key: Optional[str] = None,
                        ssl_cafile: Optional[str] = None) -> "SocketClusterBackend":
        cmd = [sys.executable, "-m",
               "cruise_control_tpu.executor.broker_simulator",
               "--polls-to-finish", str(polls_to_finish), "--listen", "0"]
        if auth_token_file:
            cmd += ["--auth-token-file", auth_token_file]
        if ssl_cert:
            cmd += ["--ssl-cert", ssl_cert]
        if ssl_key:
            cmd += ["--ssl-key", ssl_key]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        # The listener prints its bound port as the first line.  Any failure
        # from here on must reap the child — an orphaned listener survives
        # in accept() holding a port.
        try:
            ready, _, _ = select.select([proc.stdout], [], [],
                                        request_timeout_s)
            if not ready:
                raise BackendTransportError("listener did not report a port")
            first = proc.stdout.readline()
            try:
                port = int(json.loads(first)["listening"])
            except (ValueError, KeyError, TypeError) as e:
                # Child died before/while printing the port (EOF reads as
                # ''): a transport failure, not a parse bug.
                raise BackendTransportError(
                    f"bad listener banner {first!r}: {e}") from e
            backend = cls("127.0.0.1", port,
                          request_timeout_s=request_timeout_s, proc=proc,
                          auth_secret=auth_secret,
                          ssl_enable=bool(ssl_cert or ssl_cafile),
                          ssl_cafile=ssl_cafile)
            backend.request("bootstrap", partitions=list(partitions))
            return backend
        except Exception:
            proc.kill()
            raise

    def _poison(self, why: str) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        super()._poison(why)

    def close(self) -> None:
        super().close()
        try:
            self._sock.close()
        except OSError:
            pass
