"""Replica-movement ordering strategies.

Reference: ``executor/strategy/*`` — ``ReplicaMovementStrategy`` SPI with
chainable orderings: ``BaseReplicaMovementStrategy`` (execution-id order),
postpone-URP (under-replicated partitions last... reference: Postpone =
prioritize moves of partitions that are NOT under-replicated),
prioritize-large / prioritize-small replica movements.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Protocol, Set, Tuple

from cruise_control_tpu.executor.tasks import ExecutionTask


class ReplicaMovementStrategy(Protocol):
    def order(self, tasks: List[ExecutionTask]) -> List[ExecutionTask]: ...


class AbstractReplicaMovementStrategy:
    """Chainable comparator strategy (AbstractReplicaMovementStrategy.java)."""

    def __init__(self, key: Optional[Callable[[ExecutionTask], Tuple]] = None):
        self._keys: List[Callable[[ExecutionTask], Tuple]] = [key] if key else []

    def chain(self, other: "AbstractReplicaMovementStrategy"
              ) -> "AbstractReplicaMovementStrategy":
        s = AbstractReplicaMovementStrategy()
        s._keys = self._keys + other._keys
        return s

    def order(self, tasks: List[ExecutionTask]) -> List[ExecutionTask]:
        def sort_key(t: ExecutionTask):
            return tuple(k(t) for k in self._keys) + (t.execution_id,)
        return sorted(tasks, key=sort_key)


class BaseReplicaMovementStrategy(AbstractReplicaMovementStrategy):
    """Execution-id (creation) order — the default tie-breaker."""


class PrioritizeLargeReplicaMovementStrategy(AbstractReplicaMovementStrategy):
    def __init__(self):
        super().__init__(lambda t: (-t.proposal.partition_size,))


class PrioritizeSmallReplicaMovementStrategy(AbstractReplicaMovementStrategy):
    def __init__(self):
        super().__init__(lambda t: (t.proposal.partition_size,))


class PostponeUrpReplicaMovementStrategy(AbstractReplicaMovementStrategy):
    """Move healthy partitions first; URP set supplied per execution."""

    def __init__(self, urp: Optional[Set[Tuple[str, int]]] = None):
        urp = urp or set()
        super().__init__(lambda t: (
            1 if (t.proposal.topic_partition.topic,
                  t.proposal.topic_partition.partition) in urp else 0,))


def strategy_by_name(name: str, urp=None) -> AbstractReplicaMovementStrategy:
    bare = name.rsplit(".", 1)[-1]
    table = {
        "BaseReplicaMovementStrategy": BaseReplicaMovementStrategy,
        "PrioritizeLargeReplicaMovementStrategy": PrioritizeLargeReplicaMovementStrategy,
        "PrioritizeSmallReplicaMovementStrategy": PrioritizeSmallReplicaMovementStrategy,
        "PostponeUrpReplicaMovementStrategy":
            lambda: PostponeUrpReplicaMovementStrategy(urp),
    }
    try:
        return table[bare]()
    except KeyError:
        raise ValueError(f"unknown replica movement strategy: {name}") from None
