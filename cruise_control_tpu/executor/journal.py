"""Crash-safe execution journal: a write-ahead record of accepted proposal
batches and per-task state transitions.

Layout: one JSON object per line in a single file —

    {"event": "batch_start", "batchId": ..., "tasks": [...]}
    {"event": "transition", "tid": ..., "to": "in_progress", "tsMs": ...}
    ...
    {"event": "batch_end", "batchId": ..., "outcome": {...}}

A new batch truncates the file (the previous batch either ended or was
already reconciled at startup), so the journal is bounded by one execution.
``batch_start`` and ``batch_end`` are fsynced; per-transition records are
flushed to the OS (sufficient for kill -9 / process crash — fsync-per-move
would put a disk round-trip on the movement hot loop for power-loss
protection the reference doesn't offer either).

``replay()`` tolerates a torn final line (the crash can land mid-write) and
returns the last batch with each task's final journaled state;
``Executor.recover_from_journal`` reconciles that against the live
``in_progress_reassignments()`` to re-adopt, complete, or roll back.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

LOG = logging.getLogger(__name__)

_TERMINAL = frozenset({"completed", "aborted", "dead"})


@dataclass
class JournaledTask:
    execution_id: int
    task_type: str               # TaskType.value
    topic: str
    partition: int
    old_replicas: List[List[Optional[int]]]
    new_replicas: List[List[Optional[int]]]
    last_state: str = "pending"  # ExecutionTaskState.value

    @property
    def terminal(self) -> bool:
        return self.last_state in _TERMINAL

    @property
    def topic_partition(self):
        return (self.topic, self.partition)

    def to_execution_task(self):
        """Rebuild a live ExecutionTask so re-adoption can actively drive
        the backend: real transports only advance a reassignment when it is
        polled with ``finished()``, so watching ``in_progress_reassignments``
        alone would never drain an adopted task."""
        from cruise_control_tpu.common.actions import (
            ExecutionProposal,
            ReplicaPlacementInfo,
            TopicPartition,
        )
        from cruise_control_tpu.executor.tasks import (
            ExecutionTask,
            ExecutionTaskState,
            TaskType,
        )

        old = tuple(ReplicaPlacementInfo(int(b), d)
                    for b, d in self.old_replicas)
        new = tuple(ReplicaPlacementInfo(int(b), d)
                    for b, d in self.new_replicas)
        proposal = ExecutionProposal(
            topic_partition=TopicPartition(self.topic, self.partition),
            partition_size=0.0, old_leader=old[0],
            old_replicas=old, new_replicas=new)
        return ExecutionTask(proposal, TaskType(self.task_type),
                             execution_id=self.execution_id,
                             state=ExecutionTaskState(self.last_state))


@dataclass
class JournalReplay:
    batch_id: int
    complete: bool               # batch_end record present
    tasks: Dict[int, JournaledTask] = field(default_factory=dict)
    outcome: Optional[dict] = None

    def orphans(self) -> List[JournaledTask]:
        """Tasks the crashed process never drove to a terminal state."""
        return [t for t in self.tasks.values() if not t.terminal]


class ExecutionJournal:
    """Append-only, single-writer (the executor thread holds the batch)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._f = None
        self._batch_id: Optional[int] = None
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    # -- write side --------------------------------------------------------

    def begin_batch(self, tasks, meta: Optional[dict] = None) -> int:
        """Record batch acceptance BEFORE the first backend submission.
        ``meta`` (e.g. the requesting principal / X-Request-ID) merges into
        the batch_start record; ``replay()`` readers use ``.get`` so older
        journals without it stay readable."""
        with self._lock:
            self._close_locked()
            batch_id = int(time.time() * 1000)
            self._batch_id = batch_id
            self._f = open(self.path, "w", encoding="utf-8")
            record = {
                "event": "batch_start",
                "batchId": batch_id,
                "tsMs": batch_id,
                **{k: v for k, v in (meta or {}).items() if v is not None},
                "tasks": [self._task_record(t) for t in tasks],
            }
            self._f.write(json.dumps(record) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
            return batch_id

    @staticmethod
    def _task_record(task) -> dict:
        p = task.proposal
        rec = {
            "tid": task.execution_id,
            "type": task.task_type.value,
            "topic": p.topic_partition.topic,
            "partition": p.topic_partition.partition,
            "oldReplicas": [[r.broker_id, r.logdir] for r in p.old_replicas],
            "newReplicas": [[r.broker_id, r.logdir] for r in p.new_replicas],
            "state": task.state.value,
        }
        if getattr(p, "provenance", None) is not None:
            # Move provenance rides the journal line so a crash-recovered
            # batch keeps its decision lineage (replay tolerates absence).
            rec["provenance"] = p.provenance
        return rec

    def record_transition(self, task, to_state) -> None:
        with self._lock:
            if self._f is None:
                return
            self._f.write(json.dumps({
                "event": "transition",
                "tid": task.execution_id,
                "to": to_state.value,
                "tsMs": int(time.time() * 1000),
            }) + "\n")
            self._f.flush()

    def end_batch(self, outcome: Optional[dict] = None) -> None:
        with self._lock:
            if self._f is None:
                return
            self._f.write(json.dumps({
                "event": "batch_end",
                "batchId": self._batch_id,
                "outcome": outcome or {},
            }) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
            self._close_locked()

    def _close_locked(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
            self._batch_id = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    # -- read side ---------------------------------------------------------

    def replay(self) -> Optional[JournalReplay]:
        """Parse the journal; None when absent/empty.  A torn trailing line
        (crash mid-write) is dropped, not fatal."""
        try:
            with open(self.path, encoding="utf-8") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        replay: Optional[JournalReplay] = None
        for lineno, line in enumerate(raw.splitlines(), 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                LOG.warning("journal %s: dropping torn record at line %d",
                            self.path, lineno)
                continue
            event = rec.get("event")
            if event == "batch_start":
                replay = JournalReplay(batch_id=int(rec.get("batchId", 0)),
                                       complete=False)
                for t in rec.get("tasks", ()):
                    jt = JournaledTask(
                        execution_id=int(t["tid"]),
                        task_type=str(t["type"]),
                        topic=str(t["topic"]),
                        partition=int(t["partition"]),
                        old_replicas=t.get("oldReplicas", []),
                        new_replicas=t.get("newReplicas", []),
                        last_state=str(t.get("state", "pending")),
                    )
                    replay.tasks[jt.execution_id] = jt
            elif event == "transition" and replay is not None:
                jt = replay.tasks.get(int(rec.get("tid", -1)))
                if jt is not None:
                    jt.last_state = str(rec.get("to", jt.last_state))
            elif event == "batch_end" and replay is not None:
                replay.complete = True
                replay.outcome = rec.get("outcome") or {}
        if replay is None or not replay.tasks:
            return None
        return replay

    def lag(self) -> int:
        """Journaled tasks of the last batch not yet terminal — 0 for a
        cleanly ended (or absent) journal.  The /health journal probe."""
        replay = self.replay()
        if replay is None or replay.complete:
            return 0
        return len(replay.orphans())

    def mark_recovered(self) -> None:
        """Startup reconciliation finished: retire the journal file."""
        with self._lock:
            self._close_locked()
            try:
                os.remove(self.path)
            except FileNotFoundError:
                pass
