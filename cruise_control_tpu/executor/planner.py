"""Proposal → task translation and concurrency-aware batching.

Reference: ``executor/ExecutionTaskPlanner.java:63-446`` — splits proposals
into inter-broker / intra-broker / leadership tasks, keeps strategy-ordered
pending queues, and hands out batches that respect per-broker in-flight caps
(``getInterBrokerReplicaMovementTasks`` :317-389 round-robins over ready
brokers).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu.common.actions import ExecutionProposal
from cruise_control_tpu.executor.strategies import (
    AbstractReplicaMovementStrategy,
    BaseReplicaMovementStrategy,
)
from cruise_control_tpu.executor.tasks import ExecutionTask, TaskType


class ExecutionTaskPlanner:
    def __init__(self, strategy: Optional[AbstractReplicaMovementStrategy] = None):
        self._strategy = strategy or BaseReplicaMovementStrategy()
        self._inter: List[ExecutionTask] = []
        self._intra: List[ExecutionTask] = []
        self._leadership: List[ExecutionTask] = []

    def add_proposals(self, proposals: Sequence[ExecutionProposal]) -> List[ExecutionTask]:
        created: List[ExecutionTask] = []
        for p in proposals:
            if p.has_replica_action:
                created.append(ExecutionTask(p, TaskType.INTER_BROKER_REPLICA_ACTION))
            if p.replicas_to_move_between_disks:
                created.append(ExecutionTask(p, TaskType.INTRA_BROKER_REPLICA_ACTION))
            if p.has_leader_action and not p.has_replica_action:
                # Leadership embedded in a replica move happens with it.
                created.append(ExecutionTask(p, TaskType.LEADER_ACTION))
        for t in created:
            if t.task_type is TaskType.INTER_BROKER_REPLICA_ACTION:
                self._inter.append(t)
            elif t.task_type is TaskType.INTRA_BROKER_REPLICA_ACTION:
                self._intra.append(t)
            else:
                self._leadership.append(t)
        self._inter = self._strategy.order(self._inter)
        return created

    # ------------------------------------------------------------- queries

    @property
    def remaining_inter_broker_tasks(self) -> List[ExecutionTask]:
        return list(self._inter)

    @property
    def remaining_intra_broker_tasks(self) -> List[ExecutionTask]:
        return list(self._intra)

    @property
    def remaining_leadership_tasks(self) -> List[ExecutionTask]:
        return list(self._leadership)

    # ------------------------------------------------------------- batches

    def inter_broker_tasks(self, ready_brokers: Dict[int, int],
                           in_flight: Dict[int, int],
                           max_total: int = 2 ** 31) -> List[ExecutionTask]:
        """Next batch honoring per-broker caps (planner :317-389).

        ``ready_brokers``: broker -> max concurrent movements;
        ``in_flight``: broker -> currently executing movements.
        """
        out: List[ExecutionTask] = []
        counts = dict(in_flight)
        for task in list(self._inter):
            if len(out) >= max_total:
                break
            involved = task.brokers_involved
            if all(counts.get(b, 0) < ready_brokers.get(b, 0) for b in involved):
                for b in involved:
                    counts[b] = counts.get(b, 0) + 1
                out.append(task)
                self._inter.remove(task)
        return out

    def intra_broker_tasks(self, ready_brokers: Dict[int, int],
                           in_flight: Dict[int, int]) -> List[ExecutionTask]:
        out: List[ExecutionTask] = []
        counts = dict(in_flight)
        for task in list(self._intra):
            b = task.proposal.old_leader.broker_id
            involved = {r.broker_id for r in task.proposal.old_replicas}
            if all(counts.get(x, 0) < ready_brokers.get(x, 0) for x in involved):
                for x in involved:
                    counts[x] = counts.get(x, 0) + 1
                out.append(task)
                self._intra.remove(task)
        return out

    def leadership_tasks(self, max_batch: int) -> List[ExecutionTask]:
        batch = self._leadership[:max_batch]
        self._leadership = self._leadership[max_batch:]
        return batch

    @property
    def empty(self) -> bool:
        return not (self._inter or self._intra or self._leadership)

    def clear(self) -> List[ExecutionTask]:
        dropped = self._inter + self._intra + self._leadership
        self._inter, self._intra, self._leadership = [], [], []
        return dropped
