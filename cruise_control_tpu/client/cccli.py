"""``tpucc`` — command-line client.

Reference: ``cruise-control-client/cruisecontrolclient/client/cccli.py`` (the
``cccli`` console script), ``client/Endpoint.py:14-430`` (one spec per REST
endpoint with its allowed parameters) and ``client/Responder.py`` (HTTP with
progress polling on 202 responses).  The offline ``propose`` subcommand runs
the analyzer locally on a snapshot file without a server — the round-1
end-to-end slice.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

USER_TASK_HEADER = "User-Task-ID"


def _bool_param(raw: str) -> str:
    v = raw.strip().lower()
    if v not in ("true", "false", "1", "0", "yes", "no"):
        raise ValueError(f"expected a boolean, got {raw!r}")
    return "true" if v in ("true", "1", "yes") else "false"


def _int_param(raw: str) -> str:
    int(raw)          # raises ValueError with context via argparse
    return raw.strip()


def _pos_int_param(raw: str) -> str:
    if int(raw) <= 0:
        raise ValueError(f"expected a positive integer, got {raw!r}")
    return raw.strip()


def _float_param(raw: str) -> str:
    float(raw)
    return raw.strip()


def _csv_int_param(raw: str) -> str:
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    if not parts:
        raise ValueError("expected a comma-separated id list")
    for p in parts:
        int(p)
    return ",".join(parts)


def _csv_str_param(raw: str) -> str:
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    if not parts:
        raise ValueError("expected a comma-separated list")
    return ",".join(parts)


def _str_param(raw: str) -> str:
    return raw


_ANOMALY_TYPES = ("broker_failure", "goal_violation", "disk_failure",
                  "metric_anomaly", "topic_anomaly", "maintenance_event")


def _anomaly_type_param(raw: str) -> str:
    """CSV of anomaly types (the server accepts a list)."""
    parts = [p.strip().lower() for p in raw.split(",") if p.strip()]
    if not parts:
        raise ValueError("expected at least one anomaly type")
    for p in parts:
        if p not in _ANOMALY_TYPES:
            raise ValueError(f"expected one of {_ANOMALY_TYPES}, got {p!r}")
    return ",".join(parts)


# Typed parameter registry (the reference's CCParameter classes —
# cruise-control-client/.../client/CCParameter/* — one validator per
# parameter; bad values are rejected client-side before any HTTP).
PARAMETERS: Dict[str, "Parameter"] = {}


@dataclass(frozen=True)
class Parameter:
    name: str
    validator: "object"
    help: str = ""

    def __post_init__(self):
        PARAMETERS[self.name] = self


Parameter("verbose", _bool_param, "include verbose sections")
Parameter("entries", _pos_int_param, "max records returned")
Parameter("goals", _csv_str_param, "comma-separated goal names")
Parameter("excluded_topics", _csv_str_param, "topics to leave untouched")
Parameter("dryrun", _bool_param, "propose only, do not execute")
Parameter("kafka_assigner", _bool_param, "use kafka-assigner mode goals")
Parameter("destination_broker_ids", _csv_int_param, "allowed destinations")
Parameter("brokerid", _csv_int_param, "target broker id(s)")
Parameter("start", _float_param, "range start (ms)")
Parameter("end", _float_param, "range end (ms)")
Parameter("topic", _str_param, "topic name")
Parameter("replication_factor", _pos_int_param, "target replication factor")
Parameter("reason", _str_param, "free-form reason")
Parameter("approve", _csv_int_param, "review id(s) to approve")
Parameter("discard", _csv_int_param, "review id(s) to discard")
Parameter("enable_self_healing_for", _anomaly_type_param, "anomaly type")
Parameter("disable_self_healing_for", _anomaly_type_param, "anomaly type")
Parameter("concurrent_partition_movements_per_broker", _pos_int_param,
          "executor concurrency cap")


@dataclass(frozen=True)
class EndpointSpec:
    """One REST endpoint: method + the parameters it accepts
    (client/Endpoint.py's Endpoint classes)."""

    name: str
    method: str
    params: Tuple[str, ...] = ()
    help: str = ""

    def __post_init__(self):
        unknown = [p for p in self.params if p not in PARAMETERS]
        assert not unknown, f"{self.name}: unregistered parameters {unknown}"


ENDPOINTS: Dict[str, EndpointSpec] = {e.name: e for e in [
    EndpointSpec("state", "GET", ("verbose",), "cruise control state"),
    EndpointSpec("load", "GET", (), "broker-level load stats"),
    EndpointSpec("partition_load", "GET", ("entries",), "per-partition loads"),
    EndpointSpec("kafka_cluster_state", "GET", (), "broker/partition state"),
    EndpointSpec("user_tasks", "GET", (), "async task list"),
    EndpointSpec("review_board", "GET", (), "two-step review board"),
    EndpointSpec("proposals", "GET", ("goals", "excluded_topics"),
                 "compute (cached) proposals"),
    EndpointSpec("bootstrap", "GET", ("start", "end"), "re-ingest sample range"),
    EndpointSpec("train", "GET", ("start", "end"), "train the CPU model"),
    EndpointSpec("rebalance", "POST", ("dryrun", "goals", "excluded_topics",
                                       "destination_broker_ids",
                                       "kafka_assigner"), "rebalance"),
    EndpointSpec("add_broker", "POST", ("brokerid", "dryrun", "goals"),
                 "move load onto new brokers"),
    EndpointSpec("remove_broker", "POST", ("brokerid", "dryrun", "goals"),
                 "decommission brokers"),
    EndpointSpec("demote_broker", "POST", ("brokerid", "dryrun"),
                 "move leadership off brokers"),
    EndpointSpec("fix_offline_replicas", "POST", ("dryrun", "goals"),
                 "relocate offline replicas"),
    EndpointSpec("topic_configuration", "POST",
                 ("topic", "replication_factor", "dryrun", "goals"),
                 "change topic replication factor"),
    EndpointSpec("stop_proposal_execution", "POST", (), "stop ongoing execution"),
    EndpointSpec("pause_sampling", "POST", ("reason",), "pause metric sampling"),
    EndpointSpec("resume_sampling", "POST", ("reason",), "resume metric sampling"),
    EndpointSpec("admin", "POST", ("enable_self_healing_for",
                                   "disable_self_healing_for",
                                   "concurrent_partition_movements_per_broker"),
                 "admin toggles"),
    EndpointSpec("review", "POST", ("approve", "discard", "reason"),
                 "approve/discard parked requests"),
]}


class Responder:
    """HTTP with 202 progress polling (client/Responder.py semantics)."""

    def __init__(self, base_url: str, poll_interval_s: float = 0.5,
                 max_wait_s: float = 600.0,
                 auth_header: Optional[str] = None):
        self.base = base_url.rstrip("/")
        self.poll_interval_s = poll_interval_s
        self.max_wait_s = max_wait_s
        self.auth_header = auth_header

    def request(self, spec: EndpointSpec, params: Dict[str, str]) -> Dict:
        qs = urllib.parse.urlencode({k: v for k, v in params.items() if v is not None})
        url = f"{self.base}/kafkacruisecontrol/{spec.name}"
        if qs:
            url += f"?{qs}"
        task_id: Optional[str] = None
        deadline = time.time() + self.max_wait_s
        while True:
            req = urllib.request.Request(url, method=spec.method)
            if self.auth_header:
                req.add_header("Authorization", self.auth_header)
            if task_id:
                req.add_header(USER_TASK_HEADER, task_id)
            try:
                with urllib.request.urlopen(req) as resp:
                    payload = json.loads(resp.read().decode())
                    status = resp.status
                    task_id = resp.headers.get(USER_TASK_HEADER, task_id)
            except urllib.error.HTTPError as e:
                return {"httpStatus": e.code,
                        **json.loads(e.read().decode() or "{}")}
            if status != 202 or time.time() > deadline:
                payload["httpStatus"] = status
                return payload
            time.sleep(self.poll_interval_s)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpucc", description="TPU-native Cruise Control client")
    parser.add_argument("-a", "--address", default="http://127.0.0.1:9090",
                        help="server base URL")
    parser.add_argument("--username", default=None,
                        help="HTTP Basic username (secured servers)")
    parser.add_argument("--password", default=None,
                        help="HTTP Basic password")
    parser.add_argument("--token", default=None,
                        help="Bearer token (JWT-secured servers)")
    sub = parser.add_subparsers(dest="command")
    sub.required = False

    propose = sub.add_parser("propose",
                             help="offline: compute proposals for a snapshot file")
    propose.add_argument("--snapshot", required=True,
                         help="path to a cluster snapshot (.json or .npz)")
    propose.add_argument("--goals", default=None,
                         help="comma-separated goal names")
    propose.add_argument("--verbose", action="store_true")

    for spec in ENDPOINTS.values():
        p = sub.add_parser(spec.name, help=spec.help)
        for param in spec.params:
            meta = PARAMETERS[param]
            # argparse runs the validator and reports ValueError as a
            # clean usage error — no malformed value ever reaches the wire.
            p.add_argument(f"--{param}", default=None, type=meta.validator,
                           help=meta.help)
    return parser


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 0
    if args.command == "propose":
        # Imported lazily: jax startup is slow and irrelevant for --help.
        from cruise_control_tpu.client.propose import run_propose
        return run_propose(args)
    spec = ENDPOINTS[args.command]
    params = {p: getattr(args, p, None) for p in spec.params}
    auth = None
    if args.token:
        auth = f"Bearer {args.token}"
    elif args.username is not None:
        import base64
        creds = f"{args.username}:{args.password or ''}".encode()
        auth = "Basic " + base64.b64encode(creds).decode()
    result = Responder(args.address, auth_header=auth).request(spec, params)
    print(json.dumps(result, indent=2))
    return 0 if result.get("httpStatus", 200) < 400 else 1


if __name__ == "__main__":
    sys.exit(main())
