"""``tpucc`` — command-line client.

Reference: ``cruise-control-client/cruisecontrolclient/client/cccli.py`` (the
``cccli`` console script), ``client/Endpoint.py:14-430`` (one spec per REST
endpoint with its allowed parameters) and ``client/Responder.py`` (HTTP with
progress polling on 202 responses).  The offline ``propose`` subcommand runs
the analyzer locally on a snapshot file without a server — the round-1
end-to-end slice.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

USER_TASK_HEADER = "User-Task-ID"


@dataclass(frozen=True)
class EndpointSpec:
    """One REST endpoint: method + the parameters it accepts
    (client/Endpoint.py's Endpoint classes)."""

    name: str
    method: str
    params: Tuple[str, ...] = ()
    help: str = ""


ENDPOINTS: Dict[str, EndpointSpec] = {e.name: e for e in [
    EndpointSpec("state", "GET", ("verbose",), "cruise control state"),
    EndpointSpec("load", "GET", (), "broker-level load stats"),
    EndpointSpec("partition_load", "GET", ("entries",), "per-partition loads"),
    EndpointSpec("kafka_cluster_state", "GET", (), "broker/partition state"),
    EndpointSpec("user_tasks", "GET", (), "async task list"),
    EndpointSpec("review_board", "GET", (), "two-step review board"),
    EndpointSpec("proposals", "GET", ("goals", "excluded_topics"),
                 "compute (cached) proposals"),
    EndpointSpec("bootstrap", "GET", ("start", "end"), "re-ingest sample range"),
    EndpointSpec("train", "GET", ("start", "end"), "train the CPU model"),
    EndpointSpec("rebalance", "POST", ("dryrun", "goals", "excluded_topics",
                                       "destination_broker_ids"), "rebalance"),
    EndpointSpec("add_broker", "POST", ("brokerid", "dryrun", "goals"),
                 "move load onto new brokers"),
    EndpointSpec("remove_broker", "POST", ("brokerid", "dryrun", "goals"),
                 "decommission brokers"),
    EndpointSpec("demote_broker", "POST", ("brokerid", "dryrun"),
                 "move leadership off brokers"),
    EndpointSpec("fix_offline_replicas", "POST", ("dryrun", "goals"),
                 "relocate offline replicas"),
    EndpointSpec("topic_configuration", "POST",
                 ("topic", "replication_factor", "dryrun", "goals"),
                 "change topic replication factor"),
    EndpointSpec("stop_proposal_execution", "POST", (), "stop ongoing execution"),
    EndpointSpec("pause_sampling", "POST", ("reason",), "pause metric sampling"),
    EndpointSpec("resume_sampling", "POST", ("reason",), "resume metric sampling"),
    EndpointSpec("admin", "POST", ("enable_self_healing_for",
                                   "disable_self_healing_for",
                                   "concurrent_partition_movements_per_broker"),
                 "admin toggles"),
    EndpointSpec("review", "POST", ("approve", "discard", "reason"),
                 "approve/discard parked requests"),
]}


class Responder:
    """HTTP with 202 progress polling (client/Responder.py semantics)."""

    def __init__(self, base_url: str, poll_interval_s: float = 0.5,
                 max_wait_s: float = 600.0):
        self.base = base_url.rstrip("/")
        self.poll_interval_s = poll_interval_s
        self.max_wait_s = max_wait_s

    def request(self, spec: EndpointSpec, params: Dict[str, str]) -> Dict:
        qs = urllib.parse.urlencode({k: v for k, v in params.items() if v is not None})
        url = f"{self.base}/kafkacruisecontrol/{spec.name}"
        if qs:
            url += f"?{qs}"
        task_id: Optional[str] = None
        deadline = time.time() + self.max_wait_s
        while True:
            req = urllib.request.Request(url, method=spec.method)
            if task_id:
                req.add_header(USER_TASK_HEADER, task_id)
            try:
                with urllib.request.urlopen(req) as resp:
                    payload = json.loads(resp.read().decode())
                    status = resp.status
                    task_id = resp.headers.get(USER_TASK_HEADER, task_id)
            except urllib.error.HTTPError as e:
                return {"httpStatus": e.code,
                        **json.loads(e.read().decode() or "{}")}
            if status != 202 or time.time() > deadline:
                payload["httpStatus"] = status
                return payload
            time.sleep(self.poll_interval_s)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpucc", description="TPU-native Cruise Control client")
    parser.add_argument("-a", "--address", default="http://127.0.0.1:9090",
                        help="server base URL")
    sub = parser.add_subparsers(dest="command")
    sub.required = False

    propose = sub.add_parser("propose",
                             help="offline: compute proposals for a snapshot file")
    propose.add_argument("--snapshot", required=True,
                         help="path to a cluster snapshot (.json or .npz)")
    propose.add_argument("--goals", default=None,
                         help="comma-separated goal names")
    propose.add_argument("--verbose", action="store_true")

    for spec in ENDPOINTS.values():
        p = sub.add_parser(spec.name, help=spec.help)
        for param in spec.params:
            p.add_argument(f"--{param}", default=None)
    return parser


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 0
    if args.command == "propose":
        # Imported lazily: jax startup is slow and irrelevant for --help.
        from cruise_control_tpu.client.propose import run_propose
        return run_propose(args)
    spec = ENDPOINTS[args.command]
    params = {p: getattr(args, p, None) for p in spec.params}
    result = Responder(args.address).request(spec, params)
    print(json.dumps(result, indent=2))
    return 0 if result.get("httpStatus", 200) < 400 else 1


if __name__ == "__main__":
    sys.exit(main())
