"""``tpucc`` — command-line client.

Reference: ``cruise-control-client/cruisecontrolclient/client/cccli.py`` (the
``cccli`` console script).  Subcommands mirror the REST endpoints; offline
subcommands (``propose``) run the analyzer locally on a snapshot file without
a server — the round-1 end-to-end slice.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpucc",
        description="TPU-native Cruise Control client",
    )
    sub = parser.add_subparsers(dest="command")
    sub.required = False

    propose = sub.add_parser("propose", help="compute rebalance proposals for a snapshot file")
    propose.add_argument("--snapshot", required=True, help="path to a cluster snapshot (.json)")
    propose.add_argument("--goals", default=None,
                         help="comma-separated goal names (default: default.goals config)")
    propose.add_argument("--verbose", action="store_true")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command is None:
        build_parser().print_help()
        return 0
    if args.command == "propose":
        # Imported lazily: jax startup is slow and irrelevant for --help.
        from cruise_control_tpu.client.propose import run_propose
        return run_propose(args)
    return 1


if __name__ == "__main__":
    sys.exit(main())
