"""``tpucc propose`` — the offline end-to-end slice.

Snapshot file → tensors → GoalOptimizer → proposals printed as JSON
(SURVEY.md §7 step 4: the first milestone and parity gate; reference flow is
``POST /rebalance?dryrun=true`` via RebalanceRunnable → GoalOptimizer).
"""

from __future__ import annotations

import json
import sys


def run_propose(args) -> int:
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.common.exceptions import OptimizationFailureError
    from cruise_control_tpu.model import snapshot as snap

    if args.snapshot.endswith(".npz"):
        state, placement, meta = snap.load_npz(args.snapshot)
    else:
        cm = snap.load_json(args.snapshot)
        state, placement, meta = cm.freeze()

    goal_names = args.goals.split(",") if args.goals else None
    optimizer = GoalOptimizer(goal_names=goal_names)
    try:
        result = optimizer.optimizations(state, placement, meta)
    except OptimizationFailureError as e:
        print(json.dumps({"error": str(e)}), file=sys.stderr)
        return 2

    out = {
        "proposals": [p.to_dict() for p in result.proposals],
        "summary": result.to_dict(),
        "elapsedSeconds": result.elapsed_s,
    }
    if not getattr(args, "verbose", False):
        out.pop("summary")
    print(json.dumps(out, indent=2))
    return 0
