"""Metamorphic invariant harness over :mod:`cruise_control_tpu.testing.verifier`.

Absolute postconditions ("zero hard-goal violations") are wrong for an
adversarial corpus — a scenario with two dead racks may be unsatisfiable
by construction.  Every check here is therefore *relational*: the solve
must never make things worse (hard goals, soft-goal stats), its output
must be executable and conservative (proposals, loads), and independent
execution strategies must agree (mesh vs single-chip, chunked vs
unchunked lanes).  The last two are the safety net the ROADMAP's solver
rewrites need: any kernel change that breaks parity fails EVERY scenario
kind that carries the invariant, not just a hand-picked unit test.

Each invariant is a function ``(Materialized) -> List[str]`` returning
failure details (empty = holds); the registry keys are the names used in
:data:`cruise_control_tpu.fuzzsvc.scenario.Scenario.invariants` and in
docs/FUZZING.md.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from cruise_control_tpu.analyzer.budget import SolveBudget
from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.context import build_context, compute_aggregates
from cruise_control_tpu.analyzer.goals.registry import goal_by_name
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.analyzer.options import OptimizationOptions
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.fuzzsvc.scenario import Scenario
from cruise_control_tpu.model import ops
from cruise_control_tpu.testing.verifier import verify_placement


@dataclass
class InvariantResult:
    name: str
    ok: bool
    detail: str = ""
    elapsed_s: float = 0.0

    def __str__(self) -> str:
        tag = "ok" if self.ok else "FAIL"
        return f"{self.name}: {tag}" + (f" — {self.detail}" if self.detail else "")


@dataclass
class Materialized:
    """One scenario's frozen snapshot plus the lazily-shared base solve.

    Every invariant needs the same ``optimizations()`` result; computing it
    once per scenario (instead of once per invariant) is what keeps an
    8-scenario smoke inside the tier-1 timeout.
    """

    scenario: Scenario
    state: object = None
    placement: object = None
    meta: object = None
    _base: object = field(default=None, repr=False)

    def __post_init__(self):
        if self.state is None:
            self.state, self.placement, self.meta = self.scenario.materialize()

    @property
    def base(self):
        if self._base is None:
            opt = GoalOptimizer(goal_names=list(self.scenario.goal_names))
            self._base = opt.optimizations(self.state, self.placement, self.meta)
        return self._base

    def goal_context(self, placement):
        gctx = build_context(self.state, self.placement, self.meta,
                             BalancingConstraint(), OptimizationOptions())
        return gctx, compute_aggregates(gctx, placement)


# --------------------------------------------------------------------------
# base invariants (every scenario kind)
# --------------------------------------------------------------------------

def hard_goals_never_worsen(m: Materialized) -> List[str]:
    """Per hard goal, the violated-broker count after the solve is <= the
    count before it (metamorphic — the scenario may be unsatisfiable, but
    a balancer must never manufacture NEW hard violations)."""
    out: List[str] = []
    final = m.base.final_placement
    gctx, agg0 = m.goal_context(m.placement)
    _, agg1 = m.goal_context(final)
    for name in m.scenario.goal_names:
        goal = goal_by_name(name)
        if not goal.is_hard:
            continue
        before = int(np.sum(np.asarray(goal.violated_brokers(gctx, m.placement, agg0))))
        after = int(np.sum(np.asarray(goal.violated_brokers(gctx, final, agg1))))
        if after > before:
            out.append(f"{name}: violated brokers {before} -> {after}")
    return out


def soft_goals_no_regression(m: Materialized) -> List[str]:
    """The verifier's REGRESSION comparator over the base solve's per-goal
    stats: no goal that actually ran may end with a worse metric."""
    fails = verify_placement(
        m.state, m.placement, m.meta, m.base.final_placement,
        goal_names=(), verifications=("REGRESSION",),
        goal_infos=m.base.goal_infos)
    return [str(f) for f in fails if f.check == "REGRESSION"]


def proposals_executable(m: Materialized) -> List[str]:
    """Every emitted proposal must be executable against the model: old
    replicas match the starting placement, new replicas are distinct known
    alive brokers (on alive disks), and the new leader is in the new set."""
    out: List[str] = []
    n = m.meta.num_replicas
    part = np.asarray(m.state.partition)[:n]
    b0 = np.asarray(m.placement.broker)[:n]
    l0 = np.asarray(m.placement.is_leader)[:n]
    alive = np.asarray(m.state.alive)
    disk_alive = np.asarray(m.state.disk_alive)
    broker_ids = set(m.meta.broker_ids)
    bindex = m.meta.broker_index

    # (topic name, partition number) -> partition row id.
    pid_of = {(m.meta.topics[t], pn): pid
              for pid, (t, pn) in enumerate(m.meta.partitions)}

    for prop in m.base.proposals:
        tp = prop.topic_partition
        pid = pid_of.get((tp.topic, tp.partition))
        if pid is None:
            out.append(f"{tp}: unknown partition")
            continue
        rows = np.nonzero(part == pid)[0]
        have_old = {int(b) for b in b0[rows]}
        said_old = {r.broker_id for r in prop.old_replicas}
        if have_old != said_old:
            out.append(f"{tp}: old replicas {sorted(said_old)} != "
                       f"model placement {sorted(have_old)}")
        leader_rows = rows[l0[rows]]
        if leader_rows.size != 1 or int(b0[leader_rows[0]]) != prop.old_leader.broker_id:
            out.append(f"{tp}: old leader {prop.old_leader.broker_id} "
                       "does not match model leadership")
        new = [r.broker_id for r in prop.new_replicas]
        if len(set(new)) != len(new):
            out.append(f"{tp}: duplicate brokers in new replicas {new}")
        for r in prop.new_replicas:
            if r.broker_id not in broker_ids:
                out.append(f"{tp}: new replica on unknown broker {r.broker_id}")
                continue
            bi = bindex[r.broker_id]
            if not alive[bi]:
                out.append(f"{tp}: new replica on dead broker {r.broker_id}")
            if r.logdir is not None and not disk_alive[bi, r.logdir]:
                out.append(f"{tp}: new replica on dead disk "
                           f"{r.broker_id}.{r.logdir}")
        if prop.new_leader.broker_id not in set(new):
            out.append(f"{tp}: new leader outside the new replica set")
    return out


def load_conservation(m: Materialized) -> List[str]:
    """Applying the proposals moves load, never creates or destroys it:
    exactly one leader per partition, replication-invariant resource
    totals (disk, nw-in) conserved, and the verifier's LOAD_CONSISTENCY
    recompute agrees with the jax aggregation."""
    out: List[str] = []
    final = m.base.final_placement
    n = m.meta.num_replicas
    part = np.asarray(m.state.partition)[:n]
    leaders = np.bincount(part[np.asarray(final.is_leader)[:n]],
                          minlength=len(m.meta.partitions))
    bad = np.nonzero(leaders != 1)[0]
    if bad.size:
        out.append(f"{bad.size} partitions without exactly one leader "
                   f"(first: p{int(bad[0])} has {int(leaders[bad[0]])})")
    # Disk / NW_IN are identical for leaders and followers, so their
    # cluster totals must survive any placement + leadership shuffle.
    before = np.asarray(ops.broker_load(m.state, m.placement)).sum(axis=0)
    after = np.asarray(ops.broker_load(m.state, final)).sum(axis=0)
    for res in (Resource.DISK, Resource.NW_IN):
        if not np.isclose(before[res], after[res], rtol=1e-4, atol=1e-3):
            out.append(f"{res.name} total changed "
                       f"{before[res]:.6g} -> {after[res]:.6g}")
    out.extend(str(f) for f in verify_placement(
        m.state, m.placement, m.meta, final, verifications=()))
    return out


def resident_delta_equivalence(m: Materialized) -> List[str]:
    """Metamorphic check of the resident-model delta path: after rounds of
    random journalled mutations (loads, leadership, broker liveness,
    replica create/delete), the tensors produced by scatter-applying the
    collected deltas must be BITWISE equal to a fresh full freeze of the
    same builder.  Any dtype/rounding/ordering divergence between the two
    paths would let solver answers depend on how the model reached the
    device, which the steady-state resident cache must never allow."""
    from cruise_control_tpu.model.builder import builder_from_snapshot
    from cruise_control_tpu.model.state import apply_deltas

    pad_r, pad_b = m.scenario.pad_replicas_to, m.scenario.pad_brokers_to
    cm = builder_from_snapshot(m.state, m.placement, m.meta)
    cm.enable_delta_tracking()
    # NOTE: apply_deltas DONATES its inputs — these locals are rebound on
    # every apply and the donated arrays are never touched again.
    state, placement, _ = cm.freeze(pad_replicas_to=pad_r,
                                    pad_brokers_to=pad_b)
    rng = np.random.default_rng(m.scenario.seed ^ 0x5EED)
    out: List[str] = []
    applied = 0
    for _ in range(3):
        parts = list(cm.partitions().keys())
        broker_ids = [b.broker_id for b in cm.brokers()]
        for _ in range(8):
            t, p = parts[int(rng.integers(len(parts)))]
            rs = cm.partition(t, p)
            if not rs:
                continue
            op = int(rng.integers(0, 4))
            if op == 0:
                for r in list(rs):
                    cm.set_replica_load(t, p, r.broker_id,
                                        rng.uniform(0.5, 40.0, size=4))
            elif op == 1 and len(rs) >= 2:
                leader = next((r for r in rs if r.is_leader), None)
                follower = next((r for r in rs if not r.is_leader), None)
                if leader is not None and follower is not None:
                    cm.relocate_leadership(t, p, leader.broker_id,
                                           follower.broker_id)
            elif op == 2:
                b = cm.broker(broker_ids[int(rng.integers(len(broker_ids)))])
                cm.set_broker_state(b.broker_id, alive=not b.alive)
            elif len(rs) >= 2 and int(rng.integers(2)):
                cm.delete_replica(t, p, rs[-1].broker_id)
            else:
                held = {r.broker_id for r in rs}
                free = [b for b in broker_ids if b not in held]
                if free:
                    cm.create_replica(t, p, broker_id=free[0], index=len(rs),
                                      is_leader=False)
                    cm.set_replica_load(t, p, free[0],
                                        rng.uniform(0.5, 40.0, size=4))
        delta = cm.collect_delta()
        if delta is None:
            # Inexpressible edit / overflow: the service would full-freeze
            # here, which is trivially equivalent — re-anchor and continue.
            state, placement, _ = cm.freeze(pad_replicas_to=pad_r,
                                            pad_brokers_to=pad_b)
            continue
        state, placement = apply_deltas(state, placement, delta,
                                        pad_replica_updates_to=256,
                                        pad_broker_updates_to=16)
        applied += 1
    want_s, want_p, _ = cm.freeze(pad_replicas_to=pad_r,
                                  pad_brokers_to=pad_b)
    for name in ("leader_load", "follower_load", "partition", "topic", "pos",
                 "orig_broker", "offline", "valid", "capacity", "alive",
                 "new_broker", "broker_valid", "disk_capacity", "disk_alive"):
        a = np.asarray(getattr(state, name))
        b = np.asarray(getattr(want_s, name))
        if a.dtype != b.dtype or a.shape != b.shape or not (a == b).all():
            out.append(f"state.{name}: delta path != fresh freeze")
    for name in ("broker", "disk", "is_leader"):
        a = np.asarray(getattr(placement, name))
        b = np.asarray(getattr(want_p, name))
        if not (a == b).all():
            out.append(f"placement.{name}: delta path != fresh freeze")
    if applied == 0:
        out.append("no delta was ever applied (mutation stream degenerate)")
    return out


def convergence_curve_coherent(m: Materialized) -> List[str]:
    """The trace.solver.rounds telemetry is honest on this scenario:
    re-solving with the round recorder on must yield, per goal, a curve
    whose length equals the reported round count, whose summed applied
    column equals ``moves_applied``, and — for hard goals — whose violated
    count never increases across rounds (the solver only accepts
    non-worsening batches).  Any solver rewrite that desyncs the recorded
    buffer from the loop it instruments fails here on every scenario kind."""
    from cruise_control_tpu.analyzer import solver as solver_mod
    from cruise_control_tpu.obsvc.convergence import (
        ROUND_COL_APPLIED, ROUND_COL_VIOLATED)

    prev = solver_mod.round_recording_enabled()
    solver_mod.set_round_recording(True)
    try:
        res = GoalOptimizer(goal_names=list(m.scenario.goal_names)
                            ).optimizations(m.state, m.placement, m.meta)
    finally:
        solver_mod.set_round_recording(prev)
    out: List[str] = []
    for info in res.goal_infos:
        curve = info.round_curve
        if curve is None:
            out.append(f"{info.goal_name}: recorder on but no curve")
            continue
        arr = np.asarray(curve)
        if len(arr) != info.rounds:
            out.append(f"{info.goal_name}: curve length {len(arr)} != "
                       f"reported rounds {info.rounds}")
        applied = int(arr[:, ROUND_COL_APPLIED].sum()) if len(arr) else 0
        if applied != info.moves_applied:
            out.append(f"{info.goal_name}: summed per-round applied "
                       f"{applied} != moves_applied {info.moves_applied}")
        if goal_by_name(info.goal_name).is_hard and len(arr) >= 2:
            viol = arr[:, ROUND_COL_VIOLATED]
            if np.any(np.diff(viol) > 0):
                out.append(f"{info.goal_name}: violated-broker count "
                           f"increased mid-solve: {viol.tolist()}")
    return out


def memory_ledger_balanced(m: Materialized) -> List[str]:
    """The device-buffer ledger's books close over a full resident-model
    lifecycle on this scenario: a pinned freeze posts live bytes, a
    journalled mutation takes the donation path without moving the total,
    release/invalidate drain pins and bytes back to zero, no post ever
    drives a subsystem negative (imbalance counter), and the tracked total
    stays within tolerance of backend-reported stats where the backend
    exposes them.  Runs against a scenario-private ledger so fuzz processes
    with ``memory.enabled=false`` still exercise the accounting."""
    from cruise_control_tpu.model.builder import builder_from_snapshot
    from cruise_control_tpu.model.resident import ResidentModelService
    from cruise_control_tpu.obsvc.memory import (
        SUBSYS_RESIDENT, DeviceMemoryLedger, memory_ledger, set_memory_ledger)

    prev = memory_ledger()
    ledger = DeviceMemoryLedger()
    ledger.configure(enabled=True, analysis_mode="off")
    set_memory_ledger(ledger)
    imb0 = ledger.imbalance_count
    out: List[str] = []
    try:
        svc = ResidentModelService(enabled=True)
        cm = builder_from_snapshot(m.state, m.placement, m.meta)
        pad = (m.scenario.pad_replicas_to, m.scenario.pad_brokers_to)
        svc.snapshot(cm, lambda r, b: pad, pin=True)
        frozen = ledger.live_bytes(SUBSYS_RESIDENT)
        if frozen <= 0:
            out.append("pinned full freeze posted no resident live bytes")
        if ledger.pins(SUBSYS_RESIDENT) != 1:
            out.append(f"pin count after pinned snapshot: "
                       f"{ledger.pins(SUBSYS_RESIDENT)} != 1")
        svc.release()
        # One journalled load edit → the next snapshot rides the delta
        # (donation) path: an event, not a byte movement.
        (t, p), _ = next(iter(cm.partitions().items()))
        rs = cm.partition(t, p)
        if rs:
            cm.set_replica_load(t, p, rs[0].broker_id,
                                np.full(4, 7.0, dtype=np.float64))
        svc.snapshot(cm, lambda r, b: pad)
        if ledger.live_bytes(SUBSYS_RESIDENT) != frozen:
            out.append(f"delta apply moved resident bytes: {frozen} -> "
                       f"{ledger.live_bytes(SUBSYS_RESIDENT)}")
        svc.invalidate("fuzz memory_ledger_balanced")
        if ledger.live_bytes() != 0:
            out.append(f"live bytes after invalidate: {ledger.live_bytes()}")
        ev = ledger.events()
        if ev.get("alloc", 0) != ev.get("free", 0):
            out.append(f"alloc/free events unbalanced: {ev}")
        if ev.get("pin", 0) != ev.get("release", 0):
            out.append(f"pin/release events unbalanced: {ev}")
        if rs and not ev.get("donate"):
            out.append("delta apply posted no donation event")
        if ledger.imbalance_count != imb0:
            out.append(f"{ledger.imbalance_count - imb0} post imbalances "
                       "(a free exceeded tracked bytes or a release had "
                       "no pin)")
        out.extend(ledger.verify_balanced())
    finally:
        set_memory_ledger(prev)
    return out


# --------------------------------------------------------------------------
# kind-specific invariants
# --------------------------------------------------------------------------

def stranded_cleared(m: Materialized) -> List[str]:
    """Dead-broker / dead-disk scenarios: the solve must evacuate every
    offline replica (the verifier's DEAD_BROKERS postcondition)."""
    fails = verify_placement(
        m.state, m.placement, m.meta, m.base.final_placement,
        verifications=("DEAD_BROKERS",))
    return [str(f) for f in fails if f.check == "DEAD_BROKERS"]


def mesh_parity(m: Materialized) -> List[str]:
    """solver(mesh) == solver(single-chip) on this scenario (same
    violated-broker outcomes per goal; near-identical final CV)."""
    import jax
    from cruise_control_tpu.parallel import make_solver_mesh
    n_dev = len(jax.devices())
    if n_dev < 2 or m.scenario.pad_replicas_to % n_dev:
        return []  # single device (or indivisible pad): nothing to compare
    mesh = make_solver_mesh(n_dev)
    sharded = GoalOptimizer(goal_names=list(m.scenario.goal_names),
                            mesh=mesh).optimizations(
        m.state, m.placement, m.meta)
    out: List[str] = []
    for b, s in zip(m.base.goal_infos, sharded.goal_infos):
        if s.violated_brokers_after != b.violated_brokers_after:
            out.append(f"{b.goal_name}: violated_after mesh="
                       f"{s.violated_brokers_after} single={b.violated_brokers_after}")
    cv_base = np.asarray(m.base.stats_after.cv())
    cv_shard = np.asarray(sharded.stats_after.cv())
    if not np.allclose(cv_shard, cv_base, rtol=0.05, atol=5e-3):
        out.append(f"final CV diverged: mesh={cv_shard} single={cv_base}")
    return out


def chunked_parity(m: Materialized) -> List[str]:
    """chunked == unchunked what-if lane solves on this scenario's
    remove/add sets (exact equality: vmap lanes are independent, so lane
    routing must be invisible)."""
    from cruise_control_tpu.compilesvc import (
        CompileService, ShapeBucketPolicy, compile_service, set_compile_service)
    sets = m.scenario.whatif_remove or m.scenario.whatif_add
    if not sets:
        return []
    # Two goals keep the per-variant compile cost bounded; parity over a
    # subset of the stack is still parity of the lane-routing machinery.
    goals = list(m.scenario.goal_names[:2])
    batch = ("batch_remove_scenarios" if m.scenario.whatif_remove
             else "batch_add_scenarios")
    prev = compile_service()
    try:
        set_compile_service(CompileService(policy=ShapeBucketPolicy(max_lane_bucket=2)))
        chunked = getattr(GoalOptimizer(goal_names=goals), batch)(
            m.state, m.placement, m.meta, sets, num_candidates=64)
        set_compile_service(CompileService(chunking_enabled=False))
        plain = getattr(GoalOptimizer(goal_names=goals), batch)(
            m.state, m.placement, m.meta, sets, num_candidates=64)
    finally:
        set_compile_service(prev)
    out: List[str] = []
    for name in ("violated_after", "moves", "stranded_after"):
        a, b = np.asarray(getattr(chunked, name)), np.asarray(getattr(plain, name))
        if not np.array_equal(a, b):
            out.append(f"{batch}.{name}: chunked != unchunked")
    for s in range(len(sets)):
        a, b = chunked.placement_for(s), plain.placement_for(s)
        if not (np.array_equal(np.asarray(a.broker), np.asarray(b.broker))
                and np.array_equal(np.asarray(a.is_leader),
                                   np.asarray(b.is_leader))):
            out.append(f"lane {s}: final placement diverged")
    return out


class _SegmentCountdown(SolveBudget):
    """A budget that self-cancels after N ``stop_reason`` probes.

    Deadlines are wall-clock and therefore irreproducible in a fuzzer; a
    countdown preempts at an exact, seed-chosen segment/goal boundary so a
    failing scenario replays to the same partial placement every time."""

    def __init__(self, segments: int):
        super().__init__(segmented=True)
        self._segments_left = int(segments)

    def stop_reason(self) -> Optional[str]:
        reason = super().stop_reason()
        if reason is not None:
            return reason
        self._segments_left -= 1
        if self._segments_left <= 0:
            self.cancel("fuzz-preempt")
            return self.cancel_reason
        return None


def partial_solve_safe(m: Materialized) -> List[str]:
    """Preempt the solve at a random segment boundary: the partial
    placement must still satisfy every safety property the full solve
    guarantees — no new hard-goal violations, conserved loads, and
    executable proposals.  The anytime contract is exactly that stopping
    early degrades *quality*, never *safety*."""
    rng = np.random.default_rng(m.scenario.seed ^ 0xCA11)
    budget = _SegmentCountdown(int(rng.integers(1, 7)))
    res = GoalOptimizer(goal_names=list(m.scenario.goal_names)
                        ).optimizations(m.state, m.placement, m.meta,
                                        budget=budget)
    out: List[str] = []
    if budget.cancelled() and not res.partial:
        out.append("budget cancelled mid-solve but result not tagged partial")
    if res.partial and not any(i.preempted for i in res.goal_infos):
        out.append("partial result but no goal reports preempted")
    shadow = Materialized(m.scenario, state=m.state, placement=m.placement,
                          meta=m.meta, _base=res)
    for check in (hard_goals_never_worsen, load_conservation,
                  proposals_executable):
        out.extend(f"[partial] {d}" for d in check(shadow))
    return out


def relaxation_sound(m: Materialized) -> List[str]:
    """Convex-relaxation fast path soundness: re-solve the scenario with
    ``solver.relaxation.enabled`` on.  The relax+round+repair result must
    pass the same safety net as any solve (hard goals never worsen, load
    conservation, executable proposals) and each goal's final soft metric
    must land within ``solver.relaxation.tolerance`` of pure greedy's —
    the fast path is allowed to trade exact tie-breaking for speed, never
    balance quality beyond the configured slack."""
    from cruise_control_tpu.analyzer import relax as relax_mod

    prev = relax_mod.relaxation_enabled()
    relax_mod.set_relaxation(True)
    try:
        res = GoalOptimizer(goal_names=list(m.scenario.goal_names)
                            ).optimizations(m.state, m.placement, m.meta)
    finally:
        relax_mod.set_relaxation(prev)
    out: List[str] = []
    shadow = Materialized(m.scenario, state=m.state, placement=m.placement,
                          meta=m.meta, _base=res)
    for check in (hard_goals_never_worsen, load_conservation,
                  proposals_executable):
        out.extend(f"[relax] {d}" for d in check(shadow))
    tol = relax_mod.relaxation_tolerance()
    base_by_name = {i.goal_name: i for i in m.base.goal_infos}
    for info in res.goal_infos:
        b = base_by_name.get(info.goal_name)
        if b is None:
            continue
        slack = tol * max(abs(b.metric_before), abs(b.metric_after)) + 1e-6
        if info.metric_after > b.metric_after + slack:
            out.append(f"{info.goal_name}: relaxed metric "
                       f"{info.metric_after:.6g} trails greedy "
                       f"{b.metric_after:.6g} beyond tolerance {tol}")
    return out


def provenance_complete(m: Materialized) -> List[str]:
    """Execution-observatory provenance is total on this scenario: with the
    flight recorder on, every proposal the optimizer emits resolves to
    exactly one provenance record whose path is a known pipeline stage
    (relax/rounding/repair/greedy), naming a goal the solve actually ran,
    and the path histogram sums to the proposal count — no move can reach
    the executor without a decision lineage."""
    from cruise_control_tpu.obsvc.execution import (
        PATHS, execution, path_histogram)

    rec = execution()
    prev = rec.enabled
    rec.configure(enabled=True)
    try:
        res = GoalOptimizer(goal_names=list(m.scenario.goal_names)
                            ).optimizations(m.state, m.placement, m.meta)
    finally:
        rec.configure(enabled=prev)
    out: List[str] = []
    solved = {i.goal_name for i in res.goal_infos}
    for p in res.proposals:
        prov = getattr(p, "provenance", None)
        if not prov:
            out.append(f"{p.topic_partition}: move without provenance")
            continue
        if prov.get("path") not in PATHS:
            out.append(f"{p.topic_partition}: unknown provenance path "
                       f"{prov.get('path')!r}")
        if prov.get("goal") not in solved:
            out.append(f"{p.topic_partition}: provenance goal "
                       f"{prov.get('goal')!r} was never solved")
    hist = path_histogram(res.proposals)
    if sum(hist.values()) != len(res.proposals):
        out.append(f"path histogram {hist} sums to {sum(hist.values())} "
                   f"!= {len(res.proposals)} proposals")
    if hist.get("unknown"):
        out.append(f"{hist['unknown']} moves fell into the 'unknown' "
                   "provenance bucket")
    return out


def fingerprint_coherent(m: Materialized) -> List[str]:
    """Model-fidelity accounting is honest on this scenario: a fingerprint
    condensed from a synthetic aggregation (windows/gaps derived from the
    scenario seed) must agree with an independent per-entity recount of the
    aggregator's extrapolation output, and with the fidelity recorder live
    every proposal the optimizer emits carries exactly one fingerprint
    whose generation matches the model the solve actually read — no move
    can reach the executor without a data-quality lineage."""
    from cruise_control_tpu.monitor.aggregator import (
        AggregationOptions, MetricSampleAggregator)
    from cruise_control_tpu.monitor.metric_def import COMMON_METRIC_DEF
    from cruise_control_tpu.obsvc.fidelity import (
        EXTRAPOLATION_KINDS, ModelFidelityRecorder, fidelity)

    out: List[str] = []
    rng = np.random.default_rng(m.scenario.seed ^ 0xF1D0)
    window_ms, n_windows = 1_000, 6
    agg = MetricSampleAggregator(COMMON_METRIC_DEF,
                                 num_windows=n_windows, window_ms=window_ms,
                                 min_samples_per_window=2,
                                 max_allowed_extrapolations_per_entity=4)
    n_metrics = COMMON_METRIC_DEF.size
    entities = [("t", p) for p in range(8)]
    for w in range(n_windows + 1):
        for e in entities:
            # Seeded gap pattern: each entity-window gets 0..3 samples, so
            # the corpus exercises every extrapolation kind over time.
            for _ in range(int(rng.integers(0, 4))):
                agg.add_sample(e, w * window_ms + 10,
                               rng.uniform(1.0, 9.0, size=n_metrics))
    try:
        result = agg.aggregate(0, (n_windows + 1) * window_ms,
                               AggregationOptions(min_valid_windows=1))
    except Exception as exc:  # noqa: BLE001 — degenerate corpus, not a bug
        return [f"synthetic aggregation raised {type(exc).__name__}: {exc}"]
    comp = result.completeness

    # Independent recount from the per-entity extrapolation maps (valid
    # entities only — exactly what values_and_extrapolations holds).
    recount = {k: 0 for k in EXTRAPOLATION_KINDS}
    for ve in result.values_and_extrapolations.values():
        for kind in ve.extrapolations.values():
            if kind.name in recount:
                recount[kind.name] += 1
    counted = {"AVG_AVAILABLE": comp.num_windows_avg_available,
               "AVG_ADJACENT": comp.num_windows_avg_adjacent,
               "FORECAST": comp.num_windows_forecast}
    if recount != counted:
        out.append(f"completeness by-kind counts {counted} != independent "
                   f"recount {recount}")
    want_windows = (len(result.values_and_extrapolations)
                    * len(comp.valid_windows))
    if comp.num_entity_windows != want_windows:
        out.append(f"num_entity_windows {comp.num_entity_windows} != "
                   f"valid entities x windows {want_windows}")

    rec = ModelFidelityRecorder(enabled=True)
    fp = rec.record_fingerprint(comp, window_ms=window_ms)
    if fp is None:
        return out + ["record_fingerprint returned None while enabled"]
    if fp["validWindows"] != len(comp.valid_windows):
        out.append(f"fingerprint validWindows {fp['validWindows']} != "
                   f"{len(comp.valid_windows)}")
    if abs(fp["validPartitionRatio"] - comp.valid_entity_ratio) > 1e-6:
        out.append(f"fingerprint ratio {fp['validPartitionRatio']} != "
                   f"completeness {comp.valid_entity_ratio}")
    denom = max(comp.num_entity_windows, 1)
    for kind in EXTRAPOLATION_KINDS:
        want = recount[kind] / denom
        got = fp["extrapolatedFraction"][kind]
        if abs(got - want) > 1e-6:
            out.append(f"extrapolatedFraction[{kind}] {got} != recounted "
                       f"{want:.6f}")
    if fp["generation"] != agg.generation:
        out.append(f"fingerprint generation {fp['generation']} != aggregator "
                   f"generation {agg.generation}")

    # Solve with the recorder live: every proposal carries exactly the
    # fingerprint of the model generation the solve read.  One goal from
    # the shared smoke stack is enough — stamping happens at the result
    # level, so goal count adds cost, not coverage (the distribution goal
    # is the one that reliably emits moves on fuzzed skew).
    live = fidelity()
    prev_enabled, prev_fp = live.enabled, live._fingerprint
    live.configure(enabled=True)
    live._fingerprint = fp
    try:
        stamp_goals = [g for g in m.scenario.goal_names
                       if g == "ReplicaDistributionGoal"] \
            or list(m.scenario.goal_names)[:1]
        res = GoalOptimizer(goal_names=stamp_goals
                            ).optimizations(m.state, m.placement, m.meta)
    finally:
        live.configure(enabled=prev_enabled)
        live._fingerprint = prev_fp
    if res.fingerprint is None:
        out.append("result carries no fingerprint with the recorder live")
    elif res.fingerprint["generation"] != fp["generation"]:
        out.append(f"result fingerprint generation "
                   f"{res.fingerprint['generation']} != {fp['generation']}")
    for p in res.proposals:
        pfp = getattr(p, "fingerprint", None)
        if pfp is None:
            out.append(f"{p.topic_partition}: move without a fingerprint")
        elif pfp["generation"] != fp["generation"]:
            out.append(f"{p.topic_partition}: fingerprint generation "
                       f"{pfp['generation']} != {fp['generation']}")
    return out


INVARIANTS: Dict[str, Callable[[Materialized], List[str]]] = {
    "hard_goals_never_worsen": hard_goals_never_worsen,
    "soft_goals_no_regression": soft_goals_no_regression,
    "proposals_executable": proposals_executable,
    "load_conservation": load_conservation,
    "resident_delta_equivalence": resident_delta_equivalence,
    "convergence_curve_coherent": convergence_curve_coherent,
    "partial_solve_safe": partial_solve_safe,
    "relaxation_sound": relaxation_sound,
    "memory_ledger_balanced": memory_ledger_balanced,
    "provenance_complete": provenance_complete,
    "fingerprint_coherent": fingerprint_coherent,
    "stranded_cleared": stranded_cleared,
    "mesh_parity": mesh_parity,
    "chunked_parity": chunked_parity,
}


def run_invariants(scenario: Scenario,
                   which: Optional[Sequence[str]] = None,
                   materialized: Optional[Materialized] = None,
                   ) -> List[InvariantResult]:
    """Run the scenario's invariant set (or ``which``) and collect results;
    an invariant that raises is itself a failure, not a crash of the run."""
    m = materialized or Materialized(scenario)
    results: List[InvariantResult] = []
    for name in (which or scenario.invariants):
        fn = INVARIANTS.get(name)
        t0 = time.monotonic()
        if fn is None:
            results.append(InvariantResult(name, False, "unknown invariant"))
            continue
        try:
            details = fn(m)
        except Exception as exc:  # noqa: BLE001 — report, keep fuzzing
            details = [f"raised {type(exc).__name__}: {exc}"]
        results.append(InvariantResult(
            name, ok=not details, detail="; ".join(details),
            elapsed_s=time.monotonic() - t0))
    return results
