"""fuzzsvc: property-based scenario fuzzer + chaos rebalance suite.

See docs/FUZZING.md for the scenario taxonomy, invariant list, replay
workflow, and corpus layout.
"""

from cruise_control_tpu.fuzzsvc.invariants import (
    INVARIANTS,
    InvariantResult,
    Materialized,
    run_invariants,
)
from cruise_control_tpu.fuzzsvc.runner import (
    FuzzConfig,
    FuzzReport,
    ScenarioOutcome,
    fuzz_sensors,
    main,
    run_fuzz,
    run_one,
    shrink,
)
from cruise_control_tpu.fuzzsvc.scenario import (
    SCENARIO_KINDS,
    Scenario,
    StormEvent,
    generate_scenario,
    shrink_steps,
)
from cruise_control_tpu.fuzzsvc.storm import (
    InProcessSimBackend,
    StormReport,
    audit_coherence,
    build_storm_stack,
    run_storm,
)

__all__ = [
    "INVARIANTS", "InvariantResult", "Materialized", "run_invariants",
    "FuzzConfig", "FuzzReport", "ScenarioOutcome", "fuzz_sensors", "main",
    "run_fuzz", "run_one", "shrink",
    "SCENARIO_KINDS", "Scenario", "StormEvent", "generate_scenario",
    "shrink_steps",
    "InProcessSimBackend", "StormReport", "audit_coherence",
    "build_storm_stack", "run_storm",
]
