"""Fuzz campaign runner: corpus management, budgets, shrinking, replay.

``python -m cruise_control_tpu.fuzzsvc`` drives seed-deterministic campaigns:
each scenario runs its invariant set (and optionally a chaos storm); a
failure saves the scenario JSON into the corpus, greedily shrinks it to a
minimal still-failing form, and prints a one-line replay command.  The
``Fuzz.*`` counters land on the shared metrics registry so nightly soak
runs show up on ``/metrics`` like every other subsystem.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, List, Optional, Sequence

from cruise_control_tpu.common.metrics import registry
from cruise_control_tpu.fuzzsvc.invariants import (
    InvariantResult,
    Materialized,
    run_invariants,
)
from cruise_control_tpu.fuzzsvc.scenario import (
    SCENARIO_KINDS,
    Scenario,
    generate_scenario,
    shrink_steps,
)
from cruise_control_tpu.fuzzsvc.storm import StormReport, run_storm


def fuzz_sensors() -> dict:
    """Register (idempotently) and return the Fuzz.* counters.  Called from
    ``main.build_app`` too, so the sensors exist on ``/metrics`` from boot —
    the drift guard (scripts/check_sensors.py) diffs docs/SENSORS.md against
    a live scrape in both directions."""
    reg = registry()
    return {
        "scenarios": reg.counter("Fuzz.scenarios-run"),
        "failures": reg.counter("Fuzz.scenario-failures"),
        "invariant_failures": reg.counter("Fuzz.invariant-failures"),
        "storm_cycles": reg.counter("Fuzz.storm-cycles"),
        "shrink_steps": reg.counter("Fuzz.shrink-steps"),
    }


@dataclass
class FuzzConfig:
    num_scenarios: int = 8
    base_seed: int = 100
    budget_s: float = 120.0          # per-scenario soft budget (reported)
    corpus_dir: str = ".fuzz-corpus"
    storm_cycles: int = 1            # 0 disables the chaos storm
    shrink_max_steps: int = 8
    kinds: Sequence[str] = ()        # empty = every kind round-robin

    @classmethod
    def from_cc_config(cls, config) -> "FuzzConfig":
        def _get(key, default):
            try:
                v = config.get(key)
            except Exception:   # noqa: BLE001 — missing key -> default
                return default
            return default if v is None else v

        return cls(
            num_scenarios=int(_get("fuzz.num.scenarios", 8)),
            base_seed=int(_get("fuzz.seed.base", 100)),
            budget_s=float(_get("fuzz.scenario.budget.s", 120.0)),
            corpus_dir=str(_get("fuzz.corpus.dir", ".fuzz-corpus")),
            storm_cycles=int(_get("fuzz.storm.cycles", 1)),
            shrink_max_steps=int(_get("fuzz.shrink.max.steps", 8)),
        )


@dataclass
class ScenarioOutcome:
    scenario: Scenario
    invariants: List[InvariantResult] = field(default_factory=list)
    storm: Optional[StormReport] = None
    elapsed_s: float = 0.0
    over_budget: bool = False

    @property
    def failures(self) -> List[str]:
        out = [str(r) for r in self.invariants if not r.ok]
        if self.storm is not None:
            out.extend(f"storm: {p}" for p in self.storm.problems)
        return out

    @property
    def ok(self) -> bool:
        return not self.failures


@dataclass
class FuzzReport:
    outcomes: List[ScenarioOutcome] = field(default_factory=list)
    replay_lines: List[str] = field(default_factory=list)
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return all(o.ok for o in self.outcomes)


def run_one(scenario: Scenario, storm_cycles: int = 1,
            budget_s: float = 0.0,
            which: Optional[Sequence[str]] = None) -> ScenarioOutcome:
    """One scenario end to end: materialize, invariants, optional storm."""
    sensors = fuzz_sensors()
    t0 = time.monotonic()
    out = ScenarioOutcome(scenario=scenario)
    try:
        m = Materialized(scenario)
        out.invariants = run_invariants(scenario, which=which, materialized=m)
    except Exception as exc:  # noqa: BLE001 — a crashing scenario is a finding
        out.invariants = [InvariantResult(
            "materialize", False, f"raised {type(exc).__name__}: {exc}")]
    if storm_cycles > 0:
        out.storm = run_storm(scenario, cycles=storm_cycles)
        sensors["storm_cycles"].inc(out.storm.cycles_run)
    out.elapsed_s = time.monotonic() - t0
    out.over_budget = bool(budget_s) and out.elapsed_s > budget_s
    sensors["scenarios"].inc()
    sensors["invariant_failures"].inc(
        sum(1 for r in out.invariants if not r.ok))
    if not out.ok:
        sensors["failures"].inc()
    return out


def shrink(scenario: Scenario, still_fails: Callable[[Scenario], bool],
           max_steps: int = 8) -> tuple:
    """Greedy descent: take the first candidate that still fails, restart
    from it; stop when no candidate fails or the step budget runs out."""
    sensors = fuzz_sensors()
    current, trail = scenario, []
    for _ in range(max_steps):
        for label, cand in shrink_steps(current):
            sensors["shrink_steps"].inc()
            if still_fails(cand):
                current, trail = cand, trail + [label]
                break
        else:
            break
    return current, trail


def _save_corpus(corpus_dir: str, scenario: Scenario,
                 suffix: str = "") -> str:
    d = Path(corpus_dir) / "failing"
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"{scenario.name}{suffix}.json"
    path.write_text(scenario.to_json())
    return str(path)


def run_fuzz(cfg: FuzzConfig, log=print) -> FuzzReport:
    report = FuzzReport()
    t0 = time.monotonic()
    kinds = list(cfg.kinds) or list(SCENARIO_KINDS)
    for i in range(cfg.num_scenarios):
        seed = cfg.base_seed + i
        scenario = generate_scenario(seed, kind=kinds[i % len(kinds)])
        out = run_one(scenario, storm_cycles=cfg.storm_cycles,
                      budget_s=cfg.budget_s)
        report.outcomes.append(out)
        status = "ok" if out.ok else "FAIL"
        log(f"[fuzz] {scenario.name}: {status} ({out.elapsed_s:.1f}s"
            + (", over budget" if out.over_budget else "") + ")")
        if out.ok:
            continue
        for f in out.failures:
            log(f"[fuzz]   {f}")
        path = _save_corpus(cfg.corpus_dir, scenario)

        def still_fails(cand: Scenario) -> bool:
            # Invariants only during shrinking: the storm's wall-clock would
            # dominate the descent, and storm-only failures replay directly.
            return not run_one(cand, storm_cycles=0).ok

        storm_only = all(r.ok for r in out.invariants)
        shrunk, trail = (scenario, []) if storm_only else shrink(
            scenario, still_fails, max_steps=cfg.shrink_max_steps)
        if trail:
            spath = _save_corpus(cfg.corpus_dir, shrunk, suffix=".min")
            log(f"[fuzz]   shrunk via {' > '.join(trail)} -> {spath}")
            report.replay_lines.append(shrunk.replay_command(spath))
        report.replay_lines.append(scenario.replay_command(path))
        report.replay_lines.append(scenario.replay_command())
    report.elapsed_s = time.monotonic() - t0
    for line in report.replay_lines:
        log(f"[fuzz] replay: {line}")
    log(f"[fuzz] {len(report.outcomes)} scenarios, "
        f"{sum(not o.ok for o in report.outcomes)} failing, "
        f"{report.elapsed_s:.1f}s")
    return report


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cruise_control_tpu.fuzzsvc",
        description="Property-based scenario fuzzer + chaos storm suite.")
    ap.add_argument("--num", type=int, default=8,
                    help="number of scenarios (seeds base..base+num-1)")
    ap.add_argument("--base-seed", type=int, default=100)
    ap.add_argument("--seed", type=int, default=None,
                    help="run exactly one scenario from this seed")
    ap.add_argument("--kind", choices=SCENARIO_KINDS, default=None)
    ap.add_argument("--replay", metavar="JSON",
                    help="re-run a saved corpus scenario")
    ap.add_argument("--storm-cycles", type=int, default=1)
    ap.add_argument("--budget-s", type=float, default=120.0)
    ap.add_argument("--corpus-dir", default=".fuzz-corpus")
    ap.add_argument("--shrink-max-steps", type=int, default=8)
    ap.add_argument("--list-kinds", action="store_true")
    args = ap.parse_args(argv)

    if args.list_kinds:
        print("\n".join(SCENARIO_KINDS))
        return 0

    if args.replay or args.seed is not None:
        if args.replay:
            scenario = Scenario.from_json(Path(args.replay).read_text())
        else:
            scenario = generate_scenario(args.seed, kind=args.kind)
        out = run_one(scenario, storm_cycles=args.storm_cycles,
                      budget_s=args.budget_s)
        for r in out.invariants:
            print(f"[fuzz] {scenario.name} {r}")
        if out.storm is not None:
            for p in out.storm.problems:
                print(f"[fuzz] {scenario.name} storm: {p}")
        print(f"[fuzz] {scenario.name}: "
              + ("ok" if out.ok else "FAIL") + f" ({out.elapsed_s:.1f}s)")
        return 0 if out.ok else 1

    cfg = FuzzConfig(num_scenarios=args.num, base_seed=args.base_seed,
                     budget_s=args.budget_s, corpus_dir=args.corpus_dir,
                     storm_cycles=args.storm_cycles,
                     shrink_max_steps=args.shrink_max_steps,
                     kinds=(args.kind,) if args.kind else ())
    report = run_fuzz(cfg)
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
