import sys

from cruise_control_tpu.fuzzsvc.runner import main

sys.exit(main())
