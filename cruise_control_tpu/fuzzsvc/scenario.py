"""Seed-deterministic adversarial scenario generator.

Composes :mod:`cruise_control_tpu.testing.random_cluster` (never forks it)
into the taxonomy the ROADMAP's fuzzer item names: heterogeneous racks and
capacity tiers, exponential partition-size skew, dead brokers and dead
disks, maintenance windows, and mid-flight broker add/remove what-ifs.
Everything about a scenario derives from ``(seed, kind)`` through one
``np.random.default_rng(seed)`` stream, so a one-line replay command
reproduces any failure bit-for-bit; the JSON round-trip exists for the
shrinker, whose reduced scenarios no longer match any seed.

Shape discipline: every smoke-profile scenario pads to the SAME
``(pad_replicas_to, pad_brokers_to)`` targets and runs the SAME goal stack,
so eight scenarios share one compiled solve per goal instead of paying
eight cold XLA compiles (compilesvc's bucket idea applied to the fuzzer's
own workload).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Tuple

import numpy as np

from cruise_control_tpu.testing import random_cluster as rc

# Kinds double as the taxonomy in docs/FUZZING.md — keep the two in sync.
SCENARIO_KINDS: Tuple[str, ...] = (
    "uniform_baseline",
    "exp_skew",
    "hetero_racks",
    "dead_brokers",
    "dead_disks",
    "maintenance_window",
    "broker_add",
    "broker_remove",
)

# One fixed stack for the whole smoke corpus: capacity + structure + one
# distribution goal — small enough to compile fast, wide enough that every
# scenario kind has a goal that reacts to it.
SMOKE_GOALS: Tuple[str, ...] = (
    "RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
    "ReplicaDistributionGoal",
)

BASE_INVARIANTS: Tuple[str, ...] = (
    "hard_goals_never_worsen", "soft_goals_no_regression",
    "proposals_executable", "load_conservation",
    "resident_delta_equivalence", "convergence_curve_coherent",
    "partial_solve_safe", "relaxation_sound", "memory_ledger_balanced",
    "provenance_complete", "fingerprint_coherent",
)

# Shared padded shapes for the smoke profile (see module docstring).
SMOKE_PAD_REPLICAS = 1024
SMOKE_PAD_BROKERS = 16


@dataclass
class StormEvent:
    """One chaos injection inside a storm cycle."""

    kind: str            # fail_broker | fail_disk | stuck_broker |
    #                      maintenance | stop_mid_flight
    at_cycle: int = 0
    broker: int = -1
    disk: int = 0
    plan: str = ""       # maintenance plan name when kind == "maintenance"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "StormEvent":
        return cls(**d)


@dataclass
class Scenario:
    """A fully-specified fuzz case: cluster properties + goal stack +
    what-if lanes + storm events + the invariants that must hold."""

    name: str
    kind: str
    seed: int
    props: rc.ClusterProperties
    goal_names: List[str] = field(default_factory=lambda: list(SMOKE_GOALS))
    invariants: Tuple[str, ...] = BASE_INVARIANTS
    whatif_remove: List[List[int]] = field(default_factory=list)
    whatif_add: List[List[int]] = field(default_factory=list)
    events: List[StormEvent] = field(default_factory=list)
    pad_replicas_to: int = SMOKE_PAD_REPLICAS
    pad_brokers_to: int = SMOKE_PAD_BROKERS

    # ------------------------------------------------------------ material
    def materialize(self):
        """(state, placement, meta) — the frozen SoA snapshot."""
        return rc.generate(self.props, pad_replicas_to=self.pad_replicas_to,
                           pad_brokers_to=self.pad_brokers_to)

    # ---------------------------------------------------------------- json
    def to_json(self) -> str:
        props = dataclasses.asdict(self.props)
        props["distribution"] = self.props.distribution.name
        return json.dumps({
            "name": self.name, "kind": self.kind, "seed": self.seed,
            "props": props, "goal_names": list(self.goal_names),
            "invariants": list(self.invariants),
            "whatif_remove": self.whatif_remove,
            "whatif_add": self.whatif_add,
            "events": [e.to_dict() for e in self.events],
            "pad_replicas_to": self.pad_replicas_to,
            "pad_brokers_to": self.pad_brokers_to,
        }, indent=1, sort_keys=True)

    @classmethod
    def from_json(cls, raw: str) -> "Scenario":
        d = json.loads(raw)
        props = dict(d["props"])
        props["distribution"] = rc.Distribution[props["distribution"]]
        if props.get("dead_broker_ids") is not None:
            props["dead_broker_ids"] = tuple(props["dead_broker_ids"])
        if props.get("dead_disk_ids") is not None:
            props["dead_disk_ids"] = tuple(
                (int(b), int(k)) for b, k in props["dead_disk_ids"])
        return cls(
            name=d["name"], kind=d["kind"], seed=int(d["seed"]),
            props=rc.ClusterProperties(**props),
            goal_names=list(d["goal_names"]),
            invariants=tuple(d["invariants"]),
            whatif_remove=[list(map(int, s)) for s in d["whatif_remove"]],
            whatif_add=[list(map(int, s)) for s in d["whatif_add"]],
            events=[StormEvent.from_dict(e) for e in d["events"]],
            pad_replicas_to=int(d["pad_replicas_to"]),
            pad_brokers_to=int(d["pad_brokers_to"]),
        )

    def replay_command(self, corpus_path: Optional[str] = None) -> str:
        """The one-liner that reproduces this scenario."""
        if corpus_path:
            return ("JAX_PLATFORMS=cpu python -m cruise_control_tpu.fuzzsvc "
                    f"--replay {corpus_path}")
        return ("JAX_PLATFORMS=cpu python -m cruise_control_tpu.fuzzsvc "
                f"--seed {self.seed} --kind {self.kind}")


def generate_scenario(seed: int, kind: Optional[str] = None) -> Scenario:
    """Deterministic scenario from ``(seed, kind)``; ``kind=None`` lets the
    seed pick one, so a bare ``--seed N`` replay is still complete."""
    rng = np.random.default_rng(seed)
    # Draw the kind from the stream even when given, so the rest of the
    # stream is identical either way and --seed/--kind replays agree.
    drawn = SCENARIO_KINDS[int(rng.integers(0, len(SCENARIO_KINDS)))]
    kind = kind or drawn
    if kind not in SCENARIO_KINDS:
        raise ValueError(f"unknown scenario kind {kind!r}; "
                         f"expected one of {SCENARIO_KINDS}")

    num_brokers = 12
    props = rc.ClusterProperties(
        num_brokers=num_brokers,
        num_racks=4,
        num_topics=int(rng.integers(18, 28)),
        num_replicas=int(rng.integers(420, 500)),
        min_replication=3, max_replication=3,
        mean_cpu=0.02,
        num_disks=1,
        distribution=rc.Distribution.UNIFORM,
        seed=seed,
    )
    invariants = list(BASE_INVARIANTS)
    whatif_remove: List[List[int]] = []
    whatif_add: List[List[int]] = []
    events: List[StormEvent] = []

    if kind == "uniform_baseline":
        invariants.append("mesh_parity")
    elif kind == "exp_skew":
        props = dataclasses.replace(
            props, distribution=rc.Distribution.EXPONENTIAL)
        invariants.append("mesh_parity")
    elif kind == "hetero_racks":
        props = dataclasses.replace(
            props, rack_skew=float(1.0 + 2.0 * rng.random()),
            capacity_tiers=3)
    elif kind == "dead_brokers":
        dead = rng.choice(num_brokers, 2, replace=False)
        props = dataclasses.replace(
            props, dead_broker_ids=tuple(int(b) for b in sorted(dead)))
        invariants.append("stranded_cleared")
    elif kind == "dead_disks":
        props = dataclasses.replace(props, num_disks=3)
        bad = rng.choice(num_brokers, 2, replace=False)
        props = dataclasses.replace(
            props, dead_disk_ids=tuple(
                (int(b), int(rng.integers(0, 3))) for b in sorted(bad)))
        invariants.append("stranded_cleared")
    elif kind == "maintenance_window":
        target = int(rng.integers(0, num_brokers))
        events.append(StormEvent(kind="maintenance", plan="remove_broker",
                                 broker=target))
    elif kind == "broker_add":
        # The last brokers are provisioned-but-down expansion candidates;
        # each what-if lane revives a subset.
        cand = [num_brokers - 3, num_brokers - 2, num_brokers - 1]
        props = dataclasses.replace(props, dead_broker_ids=tuple(cand))
        whatif_add = [[cand[0]], [cand[1]], [cand[1], cand[2]]]
        invariants.append("chunked_parity")
    elif kind == "broker_remove":
        picks = rng.choice(num_brokers, 4, replace=False)
        whatif_remove = [[int(picks[0])], [int(picks[1])],
                         [int(picks[2]), int(picks[3])]]
        invariants.append("chunked_parity")

    return Scenario(
        name=f"{kind}-s{seed}", kind=kind, seed=seed, props=props,
        invariants=tuple(invariants), whatif_remove=whatif_remove,
        whatif_add=whatif_add, events=events,
    )


def shrink_steps(s: Scenario) -> Iterator[Tuple[str, Scenario]]:
    """Greedy-shrinker candidates, most-aggressive first: each yields a
    strictly simpler copy (fewer topics/replicas/racks, fewer faults,
    fewer events/lanes/goals).  The runner keeps any candidate that still
    fails and restarts from it."""
    p = s.props

    def with_props(label: str, **changes) -> Tuple[str, Scenario]:
        return label, dataclasses.replace(
            s, name=f"{s.name}~{label}",
            props=dataclasses.replace(p, **changes))

    if p.num_topics > 4:
        yield with_props("halve-topics", num_topics=max(4, p.num_topics // 2))
    if p.num_replicas > 60:
        yield with_props("halve-replicas",
                         num_replicas=max(60, p.num_replicas // 2))
    if p.num_racks > 2:
        yield with_props("halve-racks", num_racks=max(2, p.num_racks // 2))
    if p.rack_skew > 0.0:
        yield with_props("drop-rack-skew", rack_skew=0.0)
    if p.capacity_tiers > 1:
        yield with_props("drop-tiers", capacity_tiers=1)
    if p.distribution is not rc.Distribution.UNIFORM:
        yield with_props("uniform-dist",
                         distribution=rc.Distribution.UNIFORM)
    if p.dead_broker_ids:
        for i, b in enumerate(p.dead_broker_ids):
            rest = tuple(x for x in p.dead_broker_ids if x != b) or None
            yield with_props(f"drop-dead-broker-{b}", dead_broker_ids=rest)
    if p.dead_disk_ids:
        for b, k in p.dead_disk_ids:
            rest = tuple(x for x in p.dead_disk_ids if x != (b, k)) or None
            yield with_props(f"drop-dead-disk-{b}.{k}", dead_disk_ids=rest)
    for i in range(len(s.events)):
        ev = s.events[i]
        yield (f"drop-event-{i}-{ev.kind}", dataclasses.replace(
            s, name=f"{s.name}~drop-event-{i}",
            events=s.events[:i] + s.events[i + 1:]))
    for i in range(len(s.whatif_remove)):
        yield (f"drop-whatif-remove-{i}", dataclasses.replace(
            s, name=f"{s.name}~drop-whatif-remove-{i}",
            whatif_remove=s.whatif_remove[:i] + s.whatif_remove[i + 1:]))
    for i in range(len(s.whatif_add)):
        yield (f"drop-whatif-add-{i}", dataclasses.replace(
            s, name=f"{s.name}~drop-whatif-add-{i}",
            whatif_add=s.whatif_add[:i] + s.whatif_add[i + 1:]))
    if len(s.goal_names) > 2:
        yield ("drop-last-goal", dataclasses.replace(
            s, name=f"{s.name}~drop-last-goal",
            goal_names=s.goal_names[:-1]))
