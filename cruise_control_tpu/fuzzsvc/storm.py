"""Chaos storm runner: continuous detector → self-healing → executor cycles.

Drives the REAL pipeline — LoadMonitor, anomaly detectors, façade fixer,
Executor — against :class:`~cruise_control_tpu.executor.broker_simulator.
BrokerSimulator` held in-process behind the production
``SubprocessClusterBackend`` translation layer (only the pipe transport is
replaced, so every admin op crosses the exact wire-shape code the
subprocess/socket backends use).  Each cycle injects faults (broker deaths,
dead disks, stuck movements, maintenance plans, mid-flight aborts), runs one
detection sweep, and waits for the executor to converge or degrade; at the
end the obsvc audit ring must tell a coherent detector→action→outcome story.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from cruise_control_tpu.detector.anomalies import AnomalyType, MaintenanceEvent
from cruise_control_tpu.detector.notifier import SelfHealingNotifier
from cruise_control_tpu.executor.broker_simulator import BrokerSimulator
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.executor.subprocess_backend import (
    BackendTransportError,
    SubprocessClusterBackend,
)
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.fuzzsvc.scenario import Scenario, StormEvent
from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.metadata import (
    BrokerInfo,
    FakeMetadataBackend,
    MetadataClient,
    PartitionInfo,
)
from cruise_control_tpu.monitor.sampler import SyntheticWorkloadSampler
from cruise_control_tpu.monitor.task_runner import LoadMonitorTaskRunner
from cruise_control_tpu.obsvc.audit import audit_log

_W = 1000  # monitor window ms

EVENT_KINDS = ("fail_broker", "fail_disk", "stuck_broker", "maintenance",
               "stop_mid_flight")


class InProcessSimBackend(SubprocessClusterBackend):
    """The production admin driver with the pipe replaced by a direct
    :meth:`BrokerSimulator.handle` call — every protocol translation
    (reassignments, logdir moves, elections, throttles) still runs."""

    def __init__(self, sim: BrokerSimulator):
        super().__init__(None)
        self.sim = sim

    def request(self, op: str, **kwargs) -> Dict:
        with self._lock:
            self._next_id += 1
            resp = self.sim.handle({"id": self._next_id, "op": op, **kwargs})
        if not resp.get("ok"):
            raise BackendTransportError(resp.get("error", "sim error"))
        return resp

    def close(self) -> None:
        pass


@dataclass
class StormStack:
    cc: CruiseControl
    metadata: FakeMetadataBackend
    # In-process transport only; None when the simulator runs out-of-process.
    sim: Optional[BrokerSimulator]
    # InProcessSimBackend, or a ReconnectingBackend over the real socket
    # transport (transport="socket") — both expose request()/describe_topics.
    backend: object
    num_brokers: int
    transport: str = "inprocess"
    proc: Optional[subprocess.Popen] = None
    # Simulator admin port (socket transport only) — lets a test open a raw
    # side-channel to steer chaos when the primary backend's circuit is open.
    port: Optional[int] = None

    def sim_op(self, op: str, **kwargs) -> Dict:
        """Route a simulator control op (fault injection, stats) through
        whichever transport this stack uses."""
        if self.sim is not None:
            return self.sim.handle({"op": op, **kwargs})
        return self.backend.request(op, **kwargs)


@dataclass
class StormReport:
    scenario: str
    cycles_run: int = 0
    anomalies_detected: int = 0
    fixes_started: int = 0
    dead_tasks: int = 0
    aborted_tasks: int = 0
    problems: List[str] = field(default_factory=list)
    audit: List[Dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.problems


def spawn_simulator(polls_to_finish: int = 2,
                    extra_args: Optional[List[str]] = None
                    ) -> "tuple[subprocess.Popen, int]":
    """Launch the broker simulator as a real child process in TCP mode and
    return (proc, bound_port) once its listening banner arrives."""
    cmd = [sys.executable, "-m",
           "cruise_control_tpu.executor.broker_simulator",
           "--listen", "0", "--polls-to-finish", str(polls_to_finish)]
    cmd += list(extra_args or ())
    proc = subprocess.Popen(cmd, stdin=subprocess.DEVNULL,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    banner = proc.stdout.readline()
    try:
        port = int(json.loads(banner)["listening"])
    except (ValueError, KeyError, TypeError):
        proc.kill()
        raise RuntimeError(
            f"simulator failed to start (banner: {banner!r})") from None
    return proc, port


def build_storm_stack(scenario: Scenario, num_brokers: int = 6,
                      partitions: int = 16, rf: int = 2,
                      polls_to_finish: int = 2,
                      transport: str = "inprocess",
                      chaos: Optional[Dict] = None) -> StormStack:
    """A small live stack seeded from the scenario: the storm fuzzes the
    control loop, not the solver, so its topology stays executor-sized
    while the scenario's seed decides leader/replica spread."""
    rng = np.random.default_rng(scenario.seed)
    brokers = [BrokerInfo(i, rack=str(i % 3), host=f"h{i}")
               for i in range(num_brokers)]
    parts = []
    for p in range(partitions):
        first = int(rng.integers(0, num_brokers))
        replicas = tuple((first + i) % num_brokers for i in range(rf))
        parts.append(PartitionInfo("ST", p, leader=replicas[0],
                                   replicas=replicas,
                                   in_sync=replicas))
    metadata = FakeMetadataBackend(brokers, parts)
    client = MetadataClient(metadata, ttl_ms=0)
    lm = LoadMonitor(client, num_windows=5, window_ms=_W,
                     min_samples_per_window=1)
    runner = LoadMonitorTaskRunner(lm, SyntheticWorkloadSampler(),
                                   sampling_interval_ms=_W)
    runner.bootstrap(0, 6 * _W)

    proc = None
    if transport == "socket":
        # Real process boundary + real socket framing: transport faults
        # (chaos resets/drops, a killed child) hit the reconnecting wrapper
        # exactly as they would in production.
        from cruise_control_tpu.executor.subprocess_backend import (
            SocketClusterBackend,
        )
        from cruise_control_tpu.resilience import (
            CircuitBreaker,
            ReconnectingBackend,
            RetryPolicy,
        )
        proc, sim_port = spawn_simulator(polls_to_finish=polls_to_finish)

        def factory():
            # proc stays None on the transport: poisoning a connection must
            # drop the socket, not kill the shared simulator child.
            return SocketClusterBackend("127.0.0.1", sim_port,
                                        request_timeout_s=2.0)

        backend = ReconnectingBackend(
            factory,
            policy=RetryPolicy(max_attempts=6, base_delay_s=0.02,
                               max_delay_s=0.2, deadline_s=15.0),
            circuit=CircuitBreaker("storm-backend", failure_threshold=8,
                                   reset_timeout_s=0.2),
            name="storm-backend")
        sim = None
        port = sim_port
    elif transport == "inprocess":
        sim = BrokerSimulator(polls_to_finish=polls_to_finish)
        backend = InProcessSimBackend(sim)
        port = None
    else:
        raise ValueError(f"unknown storm transport {transport!r}")
    backend.request("bootstrap", partitions=[
        {"topic": p.topic, "partition": p.partition,
         "replicas": list(p.replicas), "leader": p.leader,
         "logdirs": {str(b): 0 for b in p.replicas}}
        for p in parts])
    if chaos:
        backend.request("chaos", **chaos)

    ex = Executor(backend, ExecutorConfig(
        progress_check_interval_s=0.001,
        task_execution_alert_timeout_s=0.4))
    notifier = SelfHealingNotifier(
        self_healing_enabled=True, clock=lambda: time.time() * 1000,
        broker_failure_alert_threshold_ms=0,
        broker_failure_self_healing_threshold_ms=0)
    cc = CruiseControl(lm, ex, task_runner=runner, notifier=notifier,
                       default_goals=list(scenario.goal_names),
                       self_healing_goals=list(scenario.goal_names),
                       anomaly_detection_interval_s=3600.0)
    return StormStack(cc=cc, metadata=metadata, sim=sim, backend=backend,
                      num_brokers=num_brokers, transport=transport,
                      proc=proc, port=port)


def default_storm_events(scenario: Scenario, cycles: int) -> List[StormEvent]:
    """One injected fault per cycle, seed-deterministic, cycling through
    every fault kind so even a 1-cycle smoke exercises an injection."""
    rng = np.random.default_rng(scenario.seed ^ 0x570B)
    out = []
    for c in range(cycles):
        kind = EVENT_KINDS[c % len(EVENT_KINDS)]
        out.append(StormEvent(kind=kind, at_cycle=c,
                              broker=int(rng.integers(1, 6)),
                              plan="remove_broker"))
    return out


def _wait_idle(cc: CruiseControl, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while cc.executor.has_ongoing_execution:
        if time.monotonic() > deadline:
            return False
        time.sleep(0.01)
    return True


def _inject(stack: StormStack, ev: StormEvent) -> bool:
    """Apply one event; returns True when a mid-flight stop is pending."""
    b = ev.broker % stack.num_brokers if ev.broker >= 0 else 1
    if ev.kind == "fail_broker":
        stack.metadata.kill_broker(b)
    elif ev.kind == "fail_disk":
        stack.sim_op("fail_logdir", broker=b, logdir=ev.disk)
    elif ev.kind == "stuck_broker":
        # The sim-side failure only: movements touching b retro-stick, so the
        # executor's task-alert timeout (not an exception) must resolve them.
        stack.sim_op("fail_broker", broker=b)
    elif ev.kind == "maintenance":
        det = stack.cc.anomaly_detector.detectors[AnomalyType.MAINTENANCE_EVENT]
        det.submit(MaintenanceEvent(plan=ev.plan or "remove_broker",
                                    broker_ids=(b,)))
    elif ev.kind == "stop_mid_flight":
        stack.metadata.kill_broker(b)
        return True
    return False


def audit_coherence(entries: List[Dict]) -> List[str]:
    """The detector→action→outcome chain must be internally consistent."""
    problems: List[str] = []
    last_id = 0
    for e in entries:
        tag = f"audit #{e.get('id')}"
        if e["id"] <= last_id:
            problems.append(f"{tag}: ids not strictly increasing")
        last_id = e["id"]
        if e["decision"] not in ("IGNORED", "CHECK_WITH_DELAY", "FIX"):
            problems.append(f"{tag}: unknown decision {e['decision']!r}")
        if e["decision"] == "FIX":
            if e["outcome"] not in ("FIX_STARTED", "FIX_FAILED_TO_START"):
                problems.append(f"{tag}: FIX entry with outcome "
                                f"{e['outcome']!r}")
        else:
            if e["outcome"] is not None:
                problems.append(f"{tag}: {e['decision']} entry has outcome")
            if e["action"] is not None:
                problems.append(f"{tag}: {e['decision']} entry has action")
        exo = e.get("executionOutcome")
        if exo is not None:
            if e["outcome"] != "FIX_STARTED":
                problems.append(f"{tag}: executionOutcome without FIX_STARTED")
            if min(exo["completed"], exo["dead"], exo["aborted"]) < 0 \
                    or exo["completed"] + exo["dead"] + exo["aborted"] == 0:
                problems.append(f"{tag}: implausible execution counts {exo}")
    return problems


def run_storm(scenario: Scenario, cycles: int = 1,
              idle_timeout_s: float = 60.0,
              stack: Optional[StormStack] = None) -> StormReport:
    """Run ``cycles`` inject→detect→heal→converge rounds and audit the ring."""
    owns_stack = stack is None
    stack = stack or build_storm_stack(scenario)
    report = StormReport(scenario=scenario.name)
    events = scenario.events or default_storm_events(scenario, cycles)
    audit_log().clear()
    stuck: List[int] = []
    try:
        for c in range(cycles):
            stop_pending = False
            for ev in events:
                if ev.at_cycle == c:
                    stop_pending |= _inject(stack, ev)
                    if ev.kind == "stuck_broker":
                        stuck.append(ev.broker % stack.num_brokers)
            report.anomalies_detected += \
                stack.cc.anomaly_detector.run_detection_once(handle=True)
            if stop_pending and stack.cc.executor.has_ongoing_execution:
                stack.cc.stop_execution()
            if not _wait_idle(stack.cc, idle_timeout_s):
                report.problems.append(
                    f"cycle {c}: executor still running after "
                    f"{idle_timeout_s}s (neither converged nor degraded)")
                break
            # Heal the sim-side stuck brokers so later cycles can move again
            # (the reference operator restarting a wedged broker).
            for b in stuck:
                stack.sim_op("restore_broker", broker=b)
            stuck.clear()
            # Mirror the executed assignment back into the monitor's
            # metadata so the next cycle models the post-heal cluster.
            for p in stack.backend.describe_topics():
                stack.metadata.apply_reassignment(
                    p["topic"], int(p["partition"]),
                    tuple(int(x) for x in p["replicas"]),
                    new_leader=int(p["leader"]))
            report.cycles_run += 1
    finally:
        stack.cc.anomaly_detector.shutdown()
        if owns_stack and stack.transport == "socket":
            try:
                stack.backend.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            if stack.proc is not None:
                stack.proc.kill()
                stack.proc.wait(timeout=5)
    report.audit = audit_log().entries()
    report.problems.extend(audit_coherence(report.audit))
    for e in report.audit:
        if e["outcome"] == "FIX_STARTED":
            report.fixes_started += 1
        exo = e.get("executionOutcome")
        if exo:
            report.dead_tasks += exo["dead"]
            report.aborted_tasks += exo["aborted"]
    if not report.audit:
        report.problems.append("storm produced no audit entries "
                               "(detectors saw nothing?)")
    return report
