"""Lane-chunk planning: route an N-lane what-if batch through the largest
already-compiled lane executable.

A what-if batch's lanes are independent vmap lanes, so a 64-lane request is
semantically identical to four 16-lane requests — but a fresh 64-lane
compile costs minutes while the 16-lane executable usually already exists
(the round-comparable bench rows, the warmup daemon, any earlier what-if).
The planner prefers compiled widths, falls back to the smallest ladder
bucket wide enough for the remainder, and pads ragged tails (padding lanes
duplicate a real lane's masks; the runner discards their results).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Sequence

from cruise_control_tpu.compilesvc.buckets import ladder_bucket


@dataclass(frozen=True)
class LaneChunk:
    size: int     # executable lane width (a ladder bucket)
    start: int    # first real lane index covered by this chunk
    n_real: int   # real lanes in this chunk (<= size; rest is padding)

    @property
    def padded(self) -> bool:
        return self.n_real < self.size


def plan_lane_chunks(n_lanes: int, ladder: Sequence[int],
                     compiled: Iterable[int] = (),
                     max_chunk: int | None = None) -> List[LaneChunk]:
    """Chunks covering ``n_lanes`` lanes, preferring compiled widths.

    Selection per remaining span: the largest already-compiled ladder width
    that fits (reuse beats everything); otherwise the smallest ladder bucket
    >= the span, capped at ``max_chunk`` — one fresh compile at a canonical
    width the next request can reuse.  64 with {16} compiled -> 4x16; 70 ->
    4x16 + 1x8 (the 8-chunk carries 6 real lanes + 2 padding).
    """
    if n_lanes <= 0:
        raise ValueError(f"n_lanes must be >= 1, got {n_lanes}")
    ladder = sorted({int(s) for s in ladder if int(s) >= 1})
    if not ladder:
        raise ValueError("empty lane ladder")
    cap = max(ladder) if max_chunk is None else int(max_chunk)
    usable = [s for s in ladder if s <= cap] or [min(ladder)]
    compiled_usable = sorted({int(s) for s in compiled} & set(usable))

    chunks: List[LaneChunk] = []
    start = 0
    while start < n_lanes:
        remaining = n_lanes - start
        fit = [s for s in compiled_usable if s <= remaining]
        if fit:
            size = max(fit)
        else:
            # Nothing compiled fits whole; if a compiled width covers the
            # remainder with LESS padding than a fresh bucket would need to
            # compile, ride it — reuse beats a fresh compile outright.
            cover = [s for s in compiled_usable if s >= remaining]
            size = min(cover) if cover else min(
                ladder_bucket(remaining, usable), max(usable))
        n_real = min(size, remaining)
        chunks.append(LaneChunk(size=size, start=start, n_real=n_real))
        start += n_real
    return chunks


def plan_is_identity(chunks: Sequence[LaneChunk], n_lanes: int) -> bool:
    """True when the plan is a single unpadded chunk over all lanes — the
    caller can run its original unchunked path."""
    return (len(chunks) == 1 and chunks[0].size == n_lanes
            and chunks[0].n_real == n_lanes)
