"""CompileService: the facade that owns every XLA executable.

One object ties the subsystem together: the shape-bucket policy decides the
canonical padded shapes (R, B, C, L), the lane-chunk planner routes what-if
batches through already-compiled lane widths, the persistent cache manager
survives process restarts, and telemetry counts every hit/miss/compile.

Callers never talk to jit directly about shapes:

- ``facade.CruiseControl`` asks ``pad_targets`` when freezing snapshots;
- ``analyzer.optimizer`` asks ``plan_lanes``/``note_lanes_compiled`` around
  the batched scenario runner;
- ``main.build_app`` calls ``configure(config)`` once at startup and the
  warmup daemon AOT-warms the configured goal stack's bucket set;
- ``servlet`` renders ``snapshot()`` as the ``compile_cache`` admin view.

A process-wide instance (``compile_service()``) exists so code deep in the
solver does not need plumbing; ``set_compile_service`` swaps it in tests.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Union

from cruise_control_tpu.compilesvc.buckets import ShapeBucketPolicy
from cruise_control_tpu.compilesvc.cache import PersistentCompileCache
from cruise_control_tpu.compilesvc.chunking import LaneChunk, plan_lane_chunks
from cruise_control_tpu.compilesvc.telemetry import CompileTelemetry, telemetry


def goal_stack_hash(goal_names: Iterable[str]) -> str:
    """Order-sensitive short hash of a goal stack — part of the persistent
    cache key and of the compiled-lane-width registry key."""
    raw = "\x1f".join(str(n) for n in goal_names)
    return hashlib.sha256(raw.encode()).hexdigest()[:12]


class CompileService:
    def __init__(self,
                 policy: Optional[ShapeBucketPolicy] = None,
                 cache: Optional[PersistentCompileCache] = None,
                 compile_telemetry: Optional[CompileTelemetry] = None,
                 chunking_enabled: bool = True,
                 warmup_enabled: bool = False,
                 warmup_lanes: Union[int, Sequence[int]] = 4):
        self.policy = policy or ShapeBucketPolicy()
        self.cache = cache or PersistentCompileCache()
        self.telemetry = compile_telemetry or telemetry()
        self.chunking_enabled = bool(chunking_enabled)
        self.warmup_enabled = bool(warmup_enabled)
        # ``warmup_lanes`` is a LADDER: every width in it gets its own warm
        # what-if task, so chunked wide batches find each block width already
        # compiled.  A scalar is accepted for back-compat (one-rung ladder).
        if isinstance(warmup_lanes, (int, float, str)):
            rungs = [int(warmup_lanes)]
        else:
            rungs = [int(w) for w in warmup_lanes]
        self.warmup_lane_ladder: Tuple[int, ...] = tuple(
            sorted({max(1, w) for w in rungs})) or (1,)
        self._lock = threading.Lock()
        # (stack_hash, R_padded, B_padded, C) -> lane widths already compiled
        self._compiled_lanes: Dict[Tuple, Set[int]] = {}

    @property
    def warmup_lanes(self) -> int:
        """Widest ladder rung — the historical scalar accessor."""
        return self.warmup_lane_ladder[-1]

    # ------------------------------------------------------------- shapes

    def pad_targets(self, n_replicas: int, n_brokers: int) -> Tuple[int, int]:
        return self.policy.pad_targets(n_replicas, n_brokers)

    def bucket_label(self, num_replicas_padded: int, num_candidates: int,
                     lanes: Optional[int] = None) -> str:
        return self.policy.bucket_label(num_replicas_padded, num_candidates,
                                        lanes)

    # ------------------------------------------------------ lane chunking

    def lane_key(self, goal_names: Iterable[str], num_replicas_padded: int,
                 num_brokers_padded: int, num_candidates: int) -> Tuple:
        return (goal_stack_hash(goal_names), int(num_replicas_padded),
                int(num_brokers_padded), int(num_candidates))

    def compiled_lane_widths(self, key: Tuple) -> Set[int]:
        with self._lock:
            return set(self._compiled_lanes.get(key, ()))

    def note_lanes_compiled(self, key: Tuple, width: int) -> None:
        with self._lock:
            self._compiled_lanes.setdefault(key, set()).add(int(width))

    def plan_lanes(self, n_lanes: int, key: Optional[Tuple] = None
                   ) -> List[LaneChunk]:
        """Chunk plan for an ``n_lanes``-wide what-if batch.  With chunking
        disabled the plan is the identity (one chunk at the native width)."""
        if not self.chunking_enabled:
            return [LaneChunk(size=int(n_lanes), start=0,
                              n_real=int(n_lanes))]
        compiled = self.compiled_lane_widths(key) if key is not None else set()
        return plan_lane_chunks(
            n_lanes, self.policy.lane_ladder, compiled=compiled,
            max_chunk=self.policy.max_lane_bucket)

    # ------------------------------------------------------------- admin

    def snapshot(self) -> Dict:
        with self._lock:
            lane_registry = {
                f"{k[0]}/R{k[1]}-B{k[2]}-C{k[3]}": sorted(v)
                for k, v in sorted(self._compiled_lanes.items())}
        return {
            "policy": {
                "replica_floor": self.policy.replica_floor,
                "broker_floor": self.policy.broker_floor,
                "growth": self.policy.growth,
                "lane_ladder": list(self.policy.lane_ladder),
                "max_lane_bucket": self.policy.max_lane_bucket,
            },
            "chunking_enabled": self.chunking_enabled,
            "warmup_enabled": self.warmup_enabled,
            "warmup_lane_ladder": list(self.warmup_lane_ladder),
            "compiled_lane_widths": lane_registry,
            "persistent_cache": self.cache.stats(),
            "telemetry": self.telemetry.snapshot(),
        }


_GLOBAL: Optional[CompileService] = None
_GLOBAL_LOCK = threading.Lock()


def compile_service() -> CompileService:
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = CompileService()
    return _GLOBAL


def set_compile_service(svc: Optional[CompileService]) -> None:
    """Swap the process-wide service (tests; ``None`` resets to default)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = svc


def _parse_lanes(raw) -> List[int]:
    """``compile.warmup.lanes`` ladder: LIST config yields List[str]; legacy
    scalar ints (and bare "8" strings) still mean a one-rung ladder."""
    if isinstance(raw, (list, tuple)):
        return [int(str(w).strip()) for w in raw if str(w).strip()] or [4]
    return [int(w) for w in str(raw).split(",") if w.strip()] or [4]


def configure(config) -> CompileService:
    """Build the process-wide service from ``compile.*`` config keys and
    install it.  ``config`` is a ``CruiseControlConfig`` (anything with
    ``.get``)."""
    def _get(key, default):
        try:
            v = config.get(key)
        except Exception:   # noqa: BLE001 — missing key -> default
            return default
        return default if v is None else v

    policy = ShapeBucketPolicy(
        replica_floor=int(_get("compile.replica.pad.floor", 64)),
        broker_floor=int(_get("compile.broker.pad.floor", 8)),
        growth=float(_get("compile.bucket.growth", 2.0)),
        max_lane_bucket=int(_get("compile.max.lane.bucket", 16)),
    )
    # CC_TPU_PERSIST_CACHE historically applied only to the TPU bench child.
    # With the feature-checked CPU loader probe the env opt-in can cover an
    # UNSET config key on any backend: activation still runs the probe
    # before touching jax.config on CPU, so "default-on" means "on where
    # the loader demonstrably works".  An explicit config value wins.
    import os
    persist_env = os.environ.get("CC_TPU_PERSIST_CACHE", "")
    explicit = hasattr(config, "originals") and \
        "compile.persistent.cache.enabled" in getattr(config, "originals", {})
    enabled = bool(_get("compile.persistent.cache.enabled", False))
    root = str(_get("compile.persistent.cache.path", "")) or None
    if persist_env and not explicit:
        enabled = True
        if persist_env.lower() not in ("1", "true", "yes") and root is None:
            root = persist_env
    cache = PersistentCompileCache(
        root=root,
        max_bytes=int(_get("compile.persistent.cache.max.bytes", 4 << 30)),
        enabled=enabled,
        cpu_probe=bool(_get("compile.persistent.cache.cpu.probe", True)),
    )
    svc = CompileService(
        policy=policy,
        cache=cache,
        chunking_enabled=bool(_get("compile.lane.chunking.enabled", True)),
        warmup_enabled=bool(_get("compile.warmup.enabled", True)),
        warmup_lanes=_parse_lanes(_get("compile.warmup.lanes", [4])),
    )
    set_compile_service(svc)
    return svc
