"""Compile telemetry: per-bucket compile/hit/miss sensors.

Instruments live in the process-wide ``common.metrics`` registry, so they
surface on ``/metrics`` (Prometheus + JSON) exactly like every other
component's sensors, plus the ``compile_cache`` admin view:

- ``CompileService.compile-count`` / ``.cache-hit-count`` /
  ``.cache-miss-count`` — totals across buckets;
- ``CompileService.<bucket>.{compile,cache-hit,cache-miss}-count`` — the
  per-bucket split (bucket labels come from ShapeBucketPolicy.bucket_label);
- ``CompileService.compile-timer`` — wall time of each detected compile
  (measured around the first invocation of a fresh executable, so it
  includes that call's execution — at solver scale trace+compile dominates).

A *hit* is an executable-family lookup that found the jitted callable
already built; a *miss* builds a new family; a *compile* is an actual XLA
compilation observed inside a family (jit retraces on new shapes, so one
family can compile several buckets).  "Zero recompiles" in tests means the
compile counters did not move.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from cruise_control_tpu.common.metrics import MetricRegistry, registry

_PREFIX = "CompileService"


class CompileTelemetry:
    """Thin facade over the metric registry plus a per-bucket tally the
    ``compile_cache`` admin view renders without scraping sensor names."""

    def __init__(self, metric_registry: Optional[MetricRegistry] = None):
        self._registry = metric_registry
        self._lock = threading.Lock()
        # bucket -> {"compiles": n, "hits": n, "misses": n}
        self._buckets: Dict[str, Dict[str, int]] = {}
        # Cumulative compile wall-clock: the tracer splits a goal span's
        # wall time into compile vs execute by delta-ing this across the
        # solve (the compile-timer's reservoir can't give a reliable delta).
        self._compile_seconds = 0.0

    @property
    def registry(self) -> MetricRegistry:
        return self._registry if self._registry is not None else registry()

    def _bump(self, bucket: str, kind: str) -> None:
        with self._lock:
            row = self._buckets.setdefault(
                bucket, {"compiles": 0, "hits": 0, "misses": 0})
            row[kind] += 1

    def record_hit(self, bucket: str) -> None:
        self.registry.counter(f"{_PREFIX}.cache-hit-count").inc()
        self.registry.counter(f"{_PREFIX}.{bucket}.cache-hit-count").inc()
        self._bump(bucket, "hits")

    def record_miss(self, bucket: str) -> None:
        self.registry.counter(f"{_PREFIX}.cache-miss-count").inc()
        self.registry.counter(f"{_PREFIX}.{bucket}.cache-miss-count").inc()
        self._bump(bucket, "misses")

    def record_compile(self, bucket: str, seconds: float) -> None:
        self.registry.counter(f"{_PREFIX}.compile-count").inc()
        self.registry.counter(f"{_PREFIX}.{bucket}.compile-count").inc()
        self.registry.timer(f"{_PREFIX}.compile-timer").update_ms(
            seconds * 1000.0)
        with self._lock:
            self._compile_seconds += seconds
        self._bump(bucket, "compiles")

    # ------------------------------------------------------------- reads

    def compile_count(self) -> int:
        return self.registry.counter(f"{_PREFIX}.compile-count").count

    def hit_count(self) -> int:
        return self.registry.counter(f"{_PREFIX}.cache-hit-count").count

    def miss_count(self) -> int:
        return self.registry.counter(f"{_PREFIX}.cache-miss-count").count

    def compile_seconds_total(self) -> float:
        with self._lock:
            return self._compile_seconds

    def bucket_table(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._buckets.items())}

    def snapshot(self) -> Dict:
        return {
            "compiles": self.compile_count(),
            "hits": self.hit_count(),
            "misses": self.miss_count(),
            "compile_timer": self.registry.timer(
                f"{_PREFIX}.compile-timer").stats(),
            "buckets": self.bucket_table(),
        }


_GLOBAL: Optional[CompileTelemetry] = None


def telemetry() -> CompileTelemetry:
    """Process-wide compile telemetry (sensors land in the global metric
    registry; solver instances pick this up by default)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = CompileTelemetry()
    return _GLOBAL
