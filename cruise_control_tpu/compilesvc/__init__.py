"""AOT compile-plan subsystem.

Every XLA executable the solver ever needs is owned here: shape-bucket
policy (``buckets``), lane-chunk planning (``chunking``), the persistent
compile-cache manager (``cache``), compile telemetry (``telemetry``), the
service facade tying them together (``service``) and the startup warmup
daemon (``warmup``).  BENCH_r05 showed compilation — not the solve —
dominating cold wall clock (383 s vs ~6 s/lane warm at 64 lanes); the
discipline encoded here is the standard JAX-serving one: compile once per
canonical shape bucket, route everything else through what is already
compiled, and persist what must be compiled.
"""

from cruise_control_tpu.compilesvc.buckets import ShapeBucketPolicy
from cruise_control_tpu.compilesvc.cache import (
    PersistentCompileCache,
    probe_cpu_cache_loader,
)
from cruise_control_tpu.compilesvc.chunking import LaneChunk, plan_lane_chunks
from cruise_control_tpu.compilesvc.service import (
    CompileService,
    compile_service,
    configure,
    set_compile_service,
)
from cruise_control_tpu.compilesvc.telemetry import CompileTelemetry, telemetry
from cruise_control_tpu.compilesvc.warmup import WarmupDaemon

__all__ = [
    "CompileService",
    "CompileTelemetry",
    "LaneChunk",
    "PersistentCompileCache",
    "ShapeBucketPolicy",
    "WarmupDaemon",
    "compile_service",
    "configure",
    "plan_lane_chunks",
    "probe_cpu_cache_loader",
    "set_compile_service",
    "telemetry",
]
