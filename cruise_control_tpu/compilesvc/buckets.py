"""Shape-bucket policy: canonical padded shapes for every solver axis.

jit caches per input *shape*, so two clusters that differ by one replica
compile two full goal stacks unless both pad to the same canonical shape.
The policy here maps the four shape axes the solver sees — replicas R,
brokers B, candidate width C, what-if lanes L — onto a small geometric
ladder of buckets, keeping the number of distinct executables logarithmic
in cluster size instead of linear in cluster-size history.

Interplay with ``model/state.make_state``: its ``pad_replicas_to`` /
``pad_brokers_to`` arguments are pad-to-MULTIPLE floors.  Passing a bucket
value that is >= the raw count as the multiple pads to exactly that bucket,
which is how ``pad_targets`` below is meant to be consumed
(``facade.CruiseControl`` snapshot/operation freezes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence, Tuple

#: Default lane ladder for what-if batches.  16 is the largest default lane
#: executable — BENCH_r05 measured a fresh 64-lane hard-goal-stack compile
#: at >300 s on CPU while a 16-lane one amortizes across the standard rows;
#: anything above ``max_lane_bucket`` is chunked (see chunking.py).
DEFAULT_LANE_LADDER: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)


def geometric_bucket(n: int, floor: int, growth: float = 2.0) -> int:
    """Smallest ``floor * growth**k`` (k >= 0, integer-rounded) >= ``n``."""
    if floor < 1:
        raise ValueError(f"bucket floor must be >= 1, got {floor}")
    if growth <= 1.0:
        raise ValueError(f"bucket growth must be > 1, got {growth}")
    bucket = floor
    n = max(int(n), 1)
    while bucket < n:
        bucket = max(bucket + 1, int(round(bucket * growth)))
    return bucket


def ladder_bucket(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder entry >= ``n`` (the top entry when ``n`` overshoots —
    callers chunk anything beyond the ladder)."""
    if not ladder:
        raise ValueError("empty lane ladder")
    n = max(int(n), 1)
    for step in sorted(ladder):
        if step >= n:
            return int(step)
    return int(max(ladder))


@dataclass(frozen=True)
class ShapeBucketPolicy:
    """Canonical pad targets for (R, B, C, L).

    ``replica_floor``/``broker_floor`` keep the historical facade floors
    (PAD_R=64, PAD_B=8) as the smallest buckets, so small/demo clusters land
    on exactly the shapes every earlier round compiled.
    """

    replica_floor: int = 64
    broker_floor: int = 8
    growth: float = 2.0
    lane_ladder: Tuple[int, ...] = DEFAULT_LANE_LADDER
    #: Largest lane executable the planner may compile fresh; wider batches
    #: are chunked through this (64 -> 4x16 by default).
    max_lane_bucket: int = 16

    def __post_init__(self):
        if self.max_lane_bucket not in self.lane_ladder:
            raise ValueError(
                f"max_lane_bucket {self.max_lane_bucket} not on the lane "
                f"ladder {self.lane_ladder}")

    def replica_bucket(self, n_replicas: int) -> int:
        return geometric_bucket(n_replicas, self.replica_floor, self.growth)

    def broker_bucket(self, n_brokers: int) -> int:
        return geometric_bucket(n_brokers, self.broker_floor, self.growth)

    def lane_bucket(self, n_lanes: int) -> int:
        return min(ladder_bucket(n_lanes, self.lane_ladder),
                   self.max_lane_bucket)

    def pad_targets(self, n_replicas: int, n_brokers: int) -> Tuple[int, int]:
        """(pad_replicas_to, pad_brokers_to) for ``ClusterModel.freeze`` —
        bucket values >= the raw counts, so pad-to-multiple pads to exactly
        the bucket."""
        return self.replica_bucket(n_replicas), self.broker_bucket(n_brokers)

    def bucket_label(self, num_replicas_padded: int, num_candidates: int,
                     lanes: int | None = None) -> str:
        """Stable per-bucket sensor label, e.g. ``R65536-C512`` or
        ``R65536-C512-L16``."""
        label = f"R{int(num_replicas_padded)}-C{int(num_candidates)}"
        if lanes is not None:
            label += f"-L{int(lanes)}"
        return label
