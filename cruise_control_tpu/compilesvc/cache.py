"""Persistent compile-cache manager.

Generalizes the bench's ``CC_TPU_PERSIST_CACHE`` opt-in
(``utils/hermetic.enable_persistent_compilation_cache``) into a managed
cache usable on CPU and TPU:

- **Versioned keys.**  XLA's persistent cache is content-addressed, but a
  content hash does not protect against loading executables built by a
  different jaxlib or for a differently-featured host (XLA:CPU AOT results
  from a machine-feature-skewed process can SIGILL — see tests/conftest.py).
  Entries therefore live under
  ``<root>/v<schema>/<platform>-<machine_fp>/jaxlib-<ver>/<stack>/<bucket>``:
  a jaxlib upgrade, a host change, a goal-stack change or a shape-bucket
  change each land in a fresh directory instead of poisoning an old one.
- **Eviction.**  Oldest-first by mtime down to ``max_bytes`` per activated
  directory, so a long-lived service cannot grow the cache without bound.
- **Corruption-safe fallback.**  A directory whose manifest is unreadable
  or mismatched is quarantined (renamed aside) and recreated; any
  unexpected failure deactivates the cache for this process instead of
  raising — a broken cache must never take down a solve.

Default-off on CPU: the cross-process machine-feature skew above makes a
shared CPU cache genuinely unsafe on this box, so CPU use is an explicit
config opt-in (``compile.persistent.cache.enabled``); the TPU child keeps
its env opt-in, now routed through this manager.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Dict, Optional

LOG = logging.getLogger(__name__)

SCHEMA_VERSION = 1
_MANIFEST = "cc-cache-manifest.json"


def machine_fingerprint() -> str:
    """Short stable fingerprint of the host the executables target."""
    import platform
    import sys
    raw = "|".join((platform.machine(), platform.processor() or "",
                    platform.system(),
                    f"py{sys.version_info[0]}.{sys.version_info[1]}"))
    return hashlib.sha256(raw.encode()).hexdigest()[:12]


def jaxlib_version() -> str:
    try:
        import jaxlib
        return str(jaxlib.__version__)
    except Exception:   # noqa: BLE001 — version probing must not raise
        import jax
        return str(jax.__version__)


def default_root() -> str:
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(root, "cruise_control_tpu", "compile_cache")


class PersistentCompileCache:
    def __init__(self, root: Optional[str] = None,
                 max_bytes: int = 4 << 30,
                 enabled: bool = False):
        self.root = root or default_root()
        self.max_bytes = int(max_bytes)
        self.enabled = bool(enabled)
        self.active_dir: Optional[str] = None
        self.last_warm: bool = False

    # ------------------------------------------------------------ keying

    def cache_dir(self, platform_name: str,
                  goal_stack_hash: str = "anystack",
                  bucket: str = "anyshape") -> str:
        return os.path.join(
            self.root, f"v{SCHEMA_VERSION}",
            f"{platform_name}-{machine_fingerprint()}",
            f"jaxlib-{jaxlib_version()}", goal_stack_hash, bucket)

    def _manifest(self) -> Dict:
        return {"schema": SCHEMA_VERSION, "jaxlib": jaxlib_version(),
                "fingerprint": machine_fingerprint()}

    # ---------------------------------------------------------- lifecycle

    def activate(self, platform_name: Optional[str] = None,
                 goal_stack_hash: str = "anystack",
                 bucket: str = "anyshape") -> bool:
        """Point JAX's persistent compilation cache at the versioned entry
        directory.  Returns True when the entry already holds executables
        ("warm").  Never raises: any failure logs and leaves the cache off.
        """
        if not self.enabled:
            return False
        try:
            if platform_name is None:
                import jax
                platform_name = jax.default_backend()
            path = self.cache_dir(platform_name, goal_stack_hash, bucket)
            os.makedirs(path, exist_ok=True)
            self._validate_or_quarantine(path)
            os.makedirs(path, exist_ok=True)
            self.evict(path)
            warm = any(e.name != _MANIFEST for e in os.scandir(path))
            with open(os.path.join(path, _MANIFEST), "w") as f:
                json.dump(self._manifest(), f)
            import jax
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              1.0)
            self.active_dir = path
            self.last_warm = warm
            return warm
        except Exception as e:   # noqa: BLE001 — cache must never kill a solve
            LOG.warning("persistent compile cache unavailable (%s); "
                        "continuing without it", e)
            self.active_dir = None
            self.last_warm = False
            return False

    def _validate_or_quarantine(self, path: str) -> None:
        """A manifest that cannot be read or does not match this process's
        versioned key means the directory was corrupted or written by an
        incompatible producer — move it aside rather than load from it."""
        manifest_path = os.path.join(path, _MANIFEST)
        populated = any(e.name != _MANIFEST for e in os.scandir(path))
        if not populated and not os.path.exists(manifest_path):
            return   # fresh directory
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
            if (manifest.get("schema") == SCHEMA_VERSION
                    and manifest.get("jaxlib") == jaxlib_version()
                    and manifest.get("fingerprint") == machine_fingerprint()):
                return
            reason = "manifest mismatch"
        except (OSError, ValueError):
            reason = "unreadable manifest"
        quarantine = path + ".quarantined"
        n = 0
        while os.path.exists(quarantine):
            n += 1
            quarantine = f"{path}.quarantined.{n}"
        os.rename(path, quarantine)
        LOG.warning("compile cache %s quarantined to %s (%s)", path,
                    quarantine, reason)

    def evict(self, path: Optional[str] = None) -> int:
        """Drop oldest entries until the directory fits ``max_bytes``;
        returns bytes removed."""
        path = path or self.active_dir
        if path is None or not os.path.isdir(path):
            return 0
        entries = []
        total = 0
        for e in os.scandir(path):
            if not e.is_file() or e.name == _MANIFEST:
                continue
            st = e.stat()
            entries.append((st.st_mtime, st.st_size, e.path))
            total += st.st_size
        removed = 0
        for _mtime, size, fp in sorted(entries):
            if total - removed <= self.max_bytes:
                break
            try:
                os.unlink(fp)
                removed += size
            except OSError:
                pass
        return removed

    # -------------------------------------------------------------- stats

    def stats(self) -> Dict:
        out: Dict = {"enabled": self.enabled, "root": self.root,
                     "max_bytes": self.max_bytes,
                     "active_dir": self.active_dir,
                     "warm": self.last_warm,
                     "entries": 0, "bytes": 0}
        if self.active_dir and os.path.isdir(self.active_dir):
            for e in os.scandir(self.active_dir):
                if e.is_file() and e.name != _MANIFEST:
                    out["entries"] += 1
                    out["bytes"] += e.stat().st_size
        return out
