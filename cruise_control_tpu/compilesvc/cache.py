"""Persistent compile-cache manager.

Generalizes the bench's ``CC_TPU_PERSIST_CACHE`` opt-in
(``utils/hermetic.enable_persistent_compilation_cache``) into a managed
cache usable on CPU and TPU:

- **Versioned keys.**  XLA's persistent cache is content-addressed, but a
  content hash does not protect against loading executables built by a
  different jaxlib or for a differently-featured host (XLA:CPU AOT results
  from a machine-feature-skewed process can SIGILL — see tests/conftest.py).
  Entries therefore live under
  ``<root>/v<schema>/<platform>-<machine_fp>/jaxlib-<ver>/<stack>/<bucket>``:
  a jaxlib upgrade, a host change, a goal-stack change or a shape-bucket
  change each land in a fresh directory instead of poisoning an old one.
- **Eviction.**  Oldest-first by mtime down to ``max_bytes`` per activated
  directory, so a long-lived service cannot grow the cache without bound.
- **Corruption-safe fallback.**  A directory whose manifest is unreadable
  or mismatched is quarantined (renamed aside) and recreated; any
  unexpected failure deactivates the cache for this process instead of
  raising — a broken cache must never take down a solve.

CPU enablement is **feature-checked**, not blanket-off: the first CPU
activation runs :func:`probe_cpu_cache_loader` — a two-subprocess
write-then-load roundtrip through a scratch cache directory — and only
proceeds when the loader demonstrably works on this host (result memoized
per jaxlib+fingerprint, so the probe's two interpreter startups are paid
once).  ``compile.persistent.cache.enabled`` stays the explicit opt-in;
``CC_TPU_PERSIST_CACHE`` now also covers an unset-on-CPU default through
``configure`` instead of applying only to the TPU bench child.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Dict, Optional

LOG = logging.getLogger(__name__)

SCHEMA_VERSION = 1
_MANIFEST = "cc-cache-manifest.json"


def machine_fingerprint() -> str:
    """Short stable fingerprint of the host the executables target."""
    import platform
    import sys
    raw = "|".join((platform.machine(), platform.processor() or "",
                    platform.system(),
                    f"py{sys.version_info[0]}.{sys.version_info[1]}"))
    return hashlib.sha256(raw.encode()).hexdigest()[:12]


def jaxlib_version() -> str:
    try:
        import jaxlib
        return str(jaxlib.__version__)
    except Exception:   # noqa: BLE001 — version probing must not raise
        import jax
        return str(jax.__version__)


def default_root() -> str:
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(root, "cruise_control_tpu", "compile_cache")


# The tiny program both probe children run: compile-or-load one jitted
# reduction through the persistent cache at argv[1].  Child 1 populates the
# entry; child 2 must LOAD it — if XLA:CPU's AOT loader trips on this host
# (machine-feature skew, SIGILL), child 2 dies non-zero and the probe fails.
_PROBE_SCRIPT = """
import sys
import jax
import jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", sys.argv[1])
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
out = jax.jit(lambda v: (v * 2.0).sum())(jnp.arange(16.0))
assert float(out) == 240.0, float(out)
"""


def _default_probe_runner(workdir: str, timeout_s: float) -> bool:
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", _PROBE_SCRIPT, workdir],
                           timeout=timeout_s, env=env,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
        if r.returncode != 0:
            return False
    return True


def probe_cpu_cache_loader(root: Optional[str] = None,
                           timeout_s: float = 120.0,
                           runner=None,
                           refresh: bool = False) -> bool:
    """Feature-check XLA:CPU's persistent-cache loader on THIS host.

    Two child interpreters share one scratch cache dir: the first compiles
    and persists a trivial executable, the second must load and run it.
    The verdict is memoized under ``<root>/v<schema>/`` keyed by jaxlib +
    machine fingerprint (the same axes the cache keys on), so a jaxlib
    upgrade or host move re-probes.  ``runner`` is injectable for tests:
    ``runner(workdir, timeout_s) -> bool``.  Never raises.
    """
    root = root or default_root()
    key = f"cpu-probe-{jaxlib_version()}-{machine_fingerprint()}"
    marker = os.path.join(root, f"v{SCHEMA_VERSION}", key + ".json")
    try:
        if not refresh and os.path.exists(marker):
            with open(marker) as f:
                return bool(json.load(f)["ok"])
    except (OSError, ValueError, KeyError):
        pass   # unreadable marker: re-probe
    workdir = os.path.join(root, f"v{SCHEMA_VERSION}", key + ".work")
    run = runner or _default_probe_runner
    try:
        os.makedirs(workdir, exist_ok=True)
        ok = bool(run(workdir, timeout_s))
    except Exception as e:   # noqa: BLE001 — a broken probe means "unsupported"
        LOG.warning("CPU cache-loader probe failed to run (%s)", e)
        ok = False
    try:
        os.makedirs(os.path.dirname(marker), exist_ok=True)
        with open(marker, "w") as f:
            json.dump({"ok": ok, "jaxlib": jaxlib_version(),
                       "fingerprint": machine_fingerprint()}, f)
    except OSError:
        pass   # no marker: the probe just runs again next process
    LOG.info("XLA:CPU persistent-cache loader probe: %s",
             "supported" if ok else "unsupported")
    return ok


class PersistentCompileCache:
    def __init__(self, root: Optional[str] = None,
                 max_bytes: int = 4 << 30,
                 enabled: bool = False,
                 cpu_probe: bool = True):
        self.root = root or default_root()
        self.max_bytes = int(max_bytes)
        self.enabled = bool(enabled)
        # Gate CPU activations on probe_cpu_cache_loader (False = legacy
        # blind-trust behavior, for operators who have validated the host).
        self.cpu_probe = bool(cpu_probe)
        self.active_dir: Optional[str] = None
        self.last_warm: bool = False

    # ------------------------------------------------------------ keying

    def cache_dir(self, platform_name: str,
                  goal_stack_hash: str = "anystack",
                  bucket: str = "anyshape") -> str:
        return os.path.join(
            self.root, f"v{SCHEMA_VERSION}",
            f"{platform_name}-{machine_fingerprint()}",
            f"jaxlib-{jaxlib_version()}", goal_stack_hash, bucket)

    def _manifest(self) -> Dict:
        return {"schema": SCHEMA_VERSION, "jaxlib": jaxlib_version(),
                "fingerprint": machine_fingerprint()}

    # ---------------------------------------------------------- lifecycle

    def activate(self, platform_name: Optional[str] = None,
                 goal_stack_hash: str = "anystack",
                 bucket: str = "anyshape") -> bool:
        """Point JAX's persistent compilation cache at the versioned entry
        directory.  Returns True when the entry already holds executables
        ("warm").  Never raises: any failure logs and leaves the cache off.
        """
        if not self.enabled:
            return False
        try:
            if platform_name is None:
                import jax
                platform_name = jax.default_backend()
            if platform_name == "cpu" and self.cpu_probe \
                    and not probe_cpu_cache_loader(self.root):
                LOG.warning("XLA:CPU persistent-cache loader failed the "
                            "feature probe on this host; leaving the "
                            "persistent cache off")
                self.active_dir = None
                self.last_warm = False
                return False
            path = self.cache_dir(platform_name, goal_stack_hash, bucket)
            os.makedirs(path, exist_ok=True)
            self._validate_or_quarantine(path)
            os.makedirs(path, exist_ok=True)
            self.evict(path)
            warm = any(e.name != _MANIFEST for e in os.scandir(path))
            with open(os.path.join(path, _MANIFEST), "w") as f:
                json.dump(self._manifest(), f)
            import jax
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              1.0)
            self.active_dir = path
            self.last_warm = warm
            return warm
        except Exception as e:   # noqa: BLE001 — cache must never kill a solve
            LOG.warning("persistent compile cache unavailable (%s); "
                        "continuing without it", e)
            self.active_dir = None
            self.last_warm = False
            return False

    def _validate_or_quarantine(self, path: str) -> None:
        """A manifest that cannot be read or does not match this process's
        versioned key means the directory was corrupted or written by an
        incompatible producer — move it aside rather than load from it."""
        manifest_path = os.path.join(path, _MANIFEST)
        populated = any(e.name != _MANIFEST for e in os.scandir(path))
        if not populated and not os.path.exists(manifest_path):
            return   # fresh directory
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
            if (manifest.get("schema") == SCHEMA_VERSION
                    and manifest.get("jaxlib") == jaxlib_version()
                    and manifest.get("fingerprint") == machine_fingerprint()):
                return
            reason = "manifest mismatch"
        except (OSError, ValueError):
            reason = "unreadable manifest"
        quarantine = path + ".quarantined"
        n = 0
        while os.path.exists(quarantine):
            n += 1
            quarantine = f"{path}.quarantined.{n}"
        os.rename(path, quarantine)
        LOG.warning("compile cache %s quarantined to %s (%s)", path,
                    quarantine, reason)

    def evict(self, path: Optional[str] = None) -> int:
        """Drop oldest entries until the directory fits ``max_bytes``;
        returns bytes removed."""
        path = path or self.active_dir
        if path is None or not os.path.isdir(path):
            return 0
        entries = []
        total = 0
        for e in os.scandir(path):
            if not e.is_file() or e.name == _MANIFEST:
                continue
            st = e.stat()
            entries.append((st.st_mtime, st.st_size, e.path))
            total += st.st_size
        removed = 0
        for _mtime, size, fp in sorted(entries):
            if total - removed <= self.max_bytes:
                break
            try:
                os.unlink(fp)
                removed += size
            except OSError:
                pass
        return removed

    # -------------------------------------------------------------- stats

    def stats(self) -> Dict:
        out: Dict = {"enabled": self.enabled, "root": self.root,
                     "max_bytes": self.max_bytes,
                     "active_dir": self.active_dir,
                     "warm": self.last_warm,
                     "entries": 0, "bytes": 0}
        if self.active_dir and os.path.isdir(self.active_dir):
            for e in os.scandir(self.active_dir):
                if e.is_file() and e.name != _MANIFEST:
                    out["entries"] += 1
                    out["bytes"] += e.stat().st_size
        return out
