"""Warmup daemon: AOT-compile the configured goal stack's bucket set at
startup, in the background.

"AOT" here means *ahead of the first user request*, not ``lower().compile()``
— an AOT-compiled executable does not land in jit's in-process dispatch
cache, so the first real solve would retrace anyway.  Warm tasks instead run
tiny real solves (dryrun proposals, a minimal what-if batch) at exactly the
canonical bucket shapes; jit's own cache then serves every later request at
those shapes, and with the persistent cache active the XLA work is also
written through to disk.

Threading follows the facade's precompute loop: a NON-daemon thread (a
daemon thread killed inside native XLA code aborts the interpreter) that
between tasks polls both its stop event and main-thread liveness, so
interpreter shutdown is never held hostage by a long warmup queue — at
worst one in-flight task finishes.

Idempotent: each task carries a key; a key already warmed is skipped, so
re-running ``start()`` (or re-adding the same bucket set) costs nothing.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Hashable, List, Optional, Set, Tuple

LOG = logging.getLogger(__name__)


class WarmupDaemon:
    def __init__(self, name: str = "compile-warmup"):
        self._name = name
        self._lock = threading.Lock()
        self._tasks: List[Tuple[Hashable, Callable[[], None]]] = []
        self._warmed: Set[Hashable] = set()
        self._errors: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._state = "idle"            # idle -> running -> done|stopped
        self._started_at: Optional[float] = None
        self._finished_at: Optional[float] = None

    # ------------------------------------------------------------- tasks

    def add_task(self, key: Hashable, fn: Callable[[], None]) -> None:
        """Queue one warm task.  ``key`` identifies the executable family
        (stack hash + bucket); duplicate keys run at most once ever."""
        with self._lock:
            self._tasks.append((key, fn))

    def warmed_keys(self) -> Set[Hashable]:
        with self._lock:
            return set(self._warmed)

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Start (or restart after completion) the background warmer.
        Idempotent while running; already-warmed keys never re-run."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._state = "running"
            self._started_at = time.time()
            self._finished_at = None
            self._thread = threading.Thread(
                target=self._run, name=self._name, daemon=False)
            self._thread.start()

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    def should_abort(self) -> bool:
        """Public abort probe for long-running warm tasks (e.g. a task
        waiting for the load monitor's first completed window polls this so
        shutdown is never held hostage by the wait)."""
        return self._stop.is_set() or not threading.main_thread().is_alive()

    _should_abort = should_abort

    def _run(self) -> None:
        idx = 0
        while True:
            if self._should_abort():
                with self._lock:
                    self._state = "stopped"
                    self._finished_at = time.time()
                return
            with self._lock:
                if idx >= len(self._tasks):
                    break
                key, fn = self._tasks[idx]
                skip = key in self._warmed
            idx += 1
            if skip:
                continue
            try:
                t0 = time.monotonic()
                fn()
                LOG.info("warmup %s: %s in %.2fs", self._name, key,
                         time.monotonic() - t0)
                with self._lock:
                    self._warmed.add(key)
            except Exception as e:   # noqa: BLE001 — warmup must never crash
                LOG.warning("warmup task %s failed: %s", key, e)
                with self._lock:
                    self._errors.append(f"{key}: {e}")
        with self._lock:
            self._state = "done"
            self._finished_at = time.time()

    # ------------------------------------------------------------- admin

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "state": self._state,
                "tasks": len(self._tasks),
                "warmed": len(self._warmed),
                "errors": list(self._errors),
                "started_at": self._started_at,
                "finished_at": self._finished_at,
            }
