from cruise_control_tpu.common.resources import Resource, NUM_RESOURCES
from cruise_control_tpu.common.actions import (
    ActionType,
    ActionAcceptance,
    BalancingAction,
    ExecutionProposal,
)
from cruise_control_tpu.common.exceptions import (
    CruiseControlError,
    OptimizationFailureError,
    NotEnoughValidWindowsError,
    OngoingExecutionError,
)
