"""Balancing actions and execution proposals.

Reference: ``analyzer/BalancingAction.java:20-287``, ``analyzer/ActionType.java``,
``analyzer/ActionAcceptance.java``, ``executor/ExecutionProposal.java:25-301``.

A ``BalancingAction`` is the atomic unit the analyzer reasons about; an
``ExecutionProposal`` is the per-partition diff (old vs new replica list) the
executor applies.  Inside solver kernels actions live as int tensors
(see ``analyzer.solver``); these dataclasses are the host-side boundary types
used by proposals, the executor, and the REST responses.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple


class ActionType(enum.IntEnum):
    """Reference: ActionType.java:25-29."""

    INTER_BROKER_REPLICA_MOVEMENT = 0
    INTRA_BROKER_REPLICA_MOVEMENT = 1
    LEADERSHIP_MOVEMENT = 2
    INTER_BROKER_REPLICA_SWAP = 3
    INTRA_BROKER_REPLICA_SWAP = 4


class ActionAcceptance(enum.IntEnum):
    """Reference: ActionAcceptance.java — veto granularity for goal acceptance."""

    ACCEPT = 0
    REPLICA_REJECT = 1  # this replica may not take part in this action
    BROKER_REJECT = 2   # the broker pair may not take part in any such action


@dataclass(frozen=True)
class TopicPartition:
    topic: str
    partition: int

    def __str__(self) -> str:
        return f"{self.topic}-{self.partition}"


@dataclass(frozen=True)
class BalancingAction:
    """One atomic move (reference: BalancingAction.java:20-287)."""

    topic_partition: TopicPartition
    source_broker: Optional[int]
    destination_broker: Optional[int]
    action_type: ActionType
    # For swaps: the partner partition on the destination.
    destination_topic_partition: Optional[TopicPartition] = None
    # For intra-broker moves: logdir (disk) ids.
    source_disk: Optional[int] = None
    destination_disk: Optional[int] = None

    def to_dict(self) -> dict:
        d = {
            "topicPartition": str(self.topic_partition),
            "sourceBrokerId": self.source_broker,
            "destinationBrokerId": self.destination_broker,
            "actionType": self.action_type.name,
        }
        if self.destination_topic_partition is not None:
            d["destinationTopicPartition"] = str(self.destination_topic_partition)
        if self.source_disk is not None:
            d["sourceDisk"] = self.source_disk
        if self.destination_disk is not None:
            d["destinationDisk"] = self.destination_disk
        return d


@dataclass(frozen=True)
class ReplicaPlacementInfo:
    """Broker (+ optional logdir) holding one replica (reference: ReplicaPlacementInfo.java)."""

    broker_id: int
    logdir: Optional[int] = None


@dataclass(frozen=True)
class ExecutionProposal:
    """Per-partition placement diff (reference: ExecutionProposal.java:25-301).

    ``old_replicas``/``new_replicas`` are ordered; index 0 is the (old/new) leader.
    """

    topic_partition: TopicPartition
    partition_size: float  # bytes; used by movement strategies & throttling
    old_leader: ReplicaPlacementInfo
    old_replicas: Tuple[ReplicaPlacementInfo, ...]
    new_replicas: Tuple[ReplicaPlacementInfo, ...]
    # Move provenance (execution observatory): {goal, path, round, solveId,
    # costDelta} stamped by the optimizer when the recorder is on; None when
    # it was off at solve time.  Excluded from eq/hash — two proposals that
    # move the same replicas the same way are the same proposal regardless
    # of which solve produced them.
    provenance: Optional[dict] = field(default=None, compare=False)
    # Model-fidelity fingerprint (fidelity observatory): the quality of the
    # monitor snapshot this proposal was solved from, stamped by the
    # optimizer when the recorder is on.  Excluded from eq/hash for the
    # same reason as provenance.
    fingerprint: Optional[dict] = field(default=None, compare=False)

    @property
    def new_leader(self) -> ReplicaPlacementInfo:
        return self.new_replicas[0]

    @property
    def replicas_to_add(self) -> Tuple[ReplicaPlacementInfo, ...]:
        old = {r.broker_id for r in self.old_replicas}
        return tuple(r for r in self.new_replicas if r.broker_id not in old)

    @property
    def replicas_to_remove(self) -> Tuple[ReplicaPlacementInfo, ...]:
        new = {r.broker_id for r in self.new_replicas}
        return tuple(r for r in self.old_replicas if r.broker_id not in new)

    @property
    def replicas_to_move_between_disks(self) -> Tuple[Tuple[ReplicaPlacementInfo, ReplicaPlacementInfo], ...]:
        """(old, new) pairs where the broker stays but the logdir changes."""
        new_by_broker = {r.broker_id: r for r in self.new_replicas}
        out = []
        for old in self.old_replicas:
            new = new_by_broker.get(old.broker_id)
            if new is not None and old.logdir is not None and new.logdir is not None and old.logdir != new.logdir:
                out.append((old, new))
        return tuple(out)

    @property
    def has_leader_action(self) -> bool:
        return self.old_leader.broker_id != self.new_replicas[0].broker_id

    @property
    def has_replica_action(self) -> bool:
        return {r.broker_id for r in self.old_replicas} != {r.broker_id for r in self.new_replicas}

    @property
    def inter_broker_data_to_move(self) -> float:
        return self.partition_size * len(self.replicas_to_add)

    def to_dict(self, explain: bool = False) -> dict:
        d = {
            "topicPartition": str(self.topic_partition),
            "oldLeader": self.old_leader.broker_id,
            "oldReplicas": [r.broker_id for r in self.old_replicas],
            "newReplicas": [r.broker_id for r in self.new_replicas],
        }
        if explain and self.provenance is not None:
            d["provenance"] = self.provenance
        if explain and self.fingerprint is not None:
            d["modelFingerprint"] = self.fingerprint
        return d


@dataclass
class ProposalSummary:
    """Aggregate movement stats for a proposal set (used in REST responses)."""

    num_inter_broker_replica_movements: int = 0
    num_intra_broker_replica_movements: int = 0
    num_leadership_movements: int = 0
    inter_broker_data_to_move_mb: float = 0.0
    intra_broker_data_to_move_mb: float = 0.0
    num_recent_windows: int = 0
    excluded_topics: Sequence[str] = field(default_factory=list)

    @classmethod
    def of(cls, proposals: Sequence[ExecutionProposal]) -> "ProposalSummary":
        s = cls()
        for p in proposals:
            if p.has_replica_action:
                s.num_inter_broker_replica_movements += len(p.replicas_to_add)
                s.inter_broker_data_to_move_mb += p.inter_broker_data_to_move / 1e6
            moved = p.replicas_to_move_between_disks
            if moved:
                s.num_intra_broker_replica_movements += len(moved)
                s.intra_broker_data_to_move_mb += p.partition_size * len(moved) / 1e6
            if p.has_leader_action:
                s.num_leadership_movements += 1
        return s
