"""Framework exception hierarchy (reference: exception/*.java)."""


class CruiseControlError(Exception):
    """Base class for all framework errors."""


class OptimizationFailureError(CruiseControlError):
    """A hard goal could not be satisfied (reference: OptimizationFailureException)."""


class NotEnoughValidWindowsError(CruiseControlError):
    """Load completeness requirements unmet (reference: NotEnoughValidWindowsException)."""


class OngoingExecutionError(CruiseControlError):
    """An execution is already in progress (reference: OngoingExecutionException)."""


class SamplingError(CruiseControlError):
    """Metric sampling failed (reference: MetricSamplingException)."""


class ConfigError(CruiseControlError):
    """Invalid configuration (reference: ConfigException)."""


class UserRequestError(CruiseControlError):
    """Bad user request (reference: UserRequestException)."""
