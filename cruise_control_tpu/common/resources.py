"""Resource taxonomy.

Mirrors the reference's ``common/Resource.java:18-97``: four resources with
host/broker scoping and utilization-comparison epsilons.  Here a resource is
just an index into axis -1 of every load/capacity tensor, so the enum is an
``IntEnum`` and the scoping/epsilon tables are plain numpy arrays that kernels
can close over.
"""

from __future__ import annotations

import enum

import numpy as np

NUM_RESOURCES = 4


class Resource(enum.IntEnum):
    """CPU is host- and broker-scoped; NW_IN/NW_OUT host-scoped; DISK broker-scoped."""

    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3

    @property
    def resource(self) -> str:
        return _NAMES[self.value]

    @property
    def is_host_resource(self) -> bool:
        return bool(IS_HOST_RESOURCE[self.value])

    @property
    def is_broker_resource(self) -> bool:
        return bool(IS_BROKER_RESOURCE[self.value])

    @classmethod
    def cached_values(cls) -> tuple["Resource", ...]:
        return _CACHED

    @classmethod
    def from_name(cls, name: str) -> "Resource":
        try:
            return _BY_NAME[name.lower()]
        except KeyError:
            raise ValueError(f"unknown resource name: {name!r}") from None

    def epsilon(self, value1: float, value2: float) -> float:
        """Comparison tolerance: max of a per-resource floor and a relative term
        (float-summation noise grows with cluster size; reference uses 0.08%)."""
        return max(float(EPSILON_FLOOR[self.value]), EPSILON_PERCENT * (value1 + value2))


_NAMES = ("cpu", "networkInbound", "networkOutbound", "disk")
_BY_NAME = {"cpu": Resource.CPU, "networkinbound": Resource.NW_IN,
            "networkoutbound": Resource.NW_OUT, "disk": Resource.DISK,
            "nw_in": Resource.NW_IN, "nw_out": Resource.NW_OUT}
_CACHED = (Resource.CPU, Resource.NW_IN, Resource.NW_OUT, Resource.DISK)

# Scoping masks, indexable by resource id inside jitted code.
IS_HOST_RESOURCE = np.array([True, True, True, False])
IS_BROKER_RESOURCE = np.array([True, False, False, True])

# Per-resource absolute epsilon floor and shared relative epsilon.
EPSILON_FLOOR = np.array([0.001, 10.0, 10.0, 100.0], dtype=np.float64)
EPSILON_PERCENT = 0.0008
