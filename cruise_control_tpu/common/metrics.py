"""Self-observability sensor registry.

Reference: the Dropwizard ``MetricRegistry`` wired through every component
(``docs/wiki/User Guide/Sensors.md`` lists ~40 sensors across Executor,
LoadMonitor, UserTaskManager, AnomalyDetector, GoalOptimizer,
MetricFetcherManager and the servlet;
``detector/AnomalyDetectorManager.java:173-192`` registers the
balancedness/provision gauges, ``executor/Executor.java:259-275`` the caps).

One process-wide registry with four instrument kinds:
- Counter   — monotone count (+ rate over a sliding window, the reference's
  Meter one-minute-rate analog);
- Gauge     — callback sampled at read time;
- Timer     — count / mean / max / p50 / p999 over a bounded reservoir;
- SettableGauge — last-written value (for components without a callback).

``snapshot()`` feeds the ``/state`` JSON; ``prometheus_text()`` renders the
``/metrics`` exposition format.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

_RATE_WINDOW_S = 60.0

SCRAPE_ERRORS_SENSOR = "MetricRegistry.sensor-scrape-errors"


def _sanitize(name: str) -> str:
    """The Prometheus-name mapping used by ``prometheus_text``'s clean()."""
    return "".join(ch if ch.isalnum() else "_" for ch in name)


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._events: deque = deque()
        self._first_ts: Optional[float] = None

    def inc(self, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            if self._first_ts is None:
                self._first_ts = now
            self._count += n
            self._events.append((now, n))
            self._trim(now)

    def _trim(self, now: float) -> None:
        while self._events and self._events[0][0] < now - _RATE_WINDOW_S:
            self._events.popleft()

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def rate(self) -> float:
        """Events per second over the trailing minute.

        Young counters divide by the observed lifetime (floored at 1 s so
        a same-millisecond burst doesn't explode), not the full window —
        dividing N first-second events by 60 under-reported early rates
        60x and made fresh-boot scrapes look idle.
        """
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            if self._first_ts is None:
                return 0.0
            window = min(_RATE_WINDOW_S, max(now - self._first_ts, 1.0))
            return sum(n for _, n in self._events) / window


class SettableGauge:
    def __init__(self, initial: float = 0.0):
        self.value = initial

    def set(self, value: float) -> None:
        self.value = value


class Timer:
    def __init__(self, reservoir: int = 1024):
        self._lock = threading.Lock()
        self._values: deque = deque(maxlen=reservoir)
        self._count = 0

    def update_ms(self, elapsed_ms: float) -> None:
        with self._lock:
            self._values.append(elapsed_ms)
            self._count += 1

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.monotonic()
                return self

            def __exit__(self, *exc):
                timer.update_ms((time.monotonic() - self._t0) * 1000.0)
                return False

        return _Ctx()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._values)
            n = self._count
        if not vals:
            return {"count": n, "mean_ms": 0.0, "max_ms": 0.0,
                    "p50_ms": 0.0, "p99_ms": 0.0, "p999_ms": 0.0}
        def pct(q):
            return vals[min(int(q * (len(vals) - 1)), len(vals) - 1)]
        return {"count": n, "mean_ms": sum(vals) / len(vals),
                "max_ms": vals[-1], "p50_ms": pct(0.5), "p99_ms": pct(0.99),
                "p999_ms": pct(0.999)}


class MetricRegistry:
    """Thread-safe named-instrument registry (get-or-create semantics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._timers: Dict[str, Timer] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._settable: Dict[str, SettableGauge] = {}
        # sanitized prometheus name → (sensor name, kind): two sensors that
        # collapse to one series after clean() would silently shadow each
        # other in /metrics, so collisions fail loudly at registration.
        self._prom_names: Dict[str, tuple] = {}

    def _register_guard(self, name: str, kind: str) -> None:
        # Caller holds self._lock.
        key = _sanitize(name)
        prior = self._prom_names.get(key)
        if prior is None:
            self._prom_names[key] = (name, kind)
            return
        prior_name, prior_kind = prior
        if prior_name != name:
            raise ValueError(
                f"sensor name {name!r} collides with {prior_name!r}: both "
                f"sanitize to Prometheus series {key!r}")
        if prior_kind != kind:
            raise ValueError(
                f"sensor {name!r} already registered as a {prior_kind}, "
                f"cannot re-register as a {kind}")

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                self._register_guard(name, "counter")
                c = self._counters[name] = Counter()
            return c

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                self._register_guard(name, "timer")
                t = self._timers[name] = Timer()
            return t

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            if name not in self._gauges:
                self._register_guard(name, "gauge")
            self._gauges[name] = fn

    def settable_gauge(self, name: str, initial: float = 0.0) -> SettableGauge:
        with self._lock:
            g = self._settable.get(name)
            if g is None:
                self._register_guard(name, "settable_gauge")
                g = self._settable[name] = SettableGauge(initial)
            return g

    def names(self) -> List[str]:
        with self._lock:
            return sorted({*self._counters, *self._timers, *self._gauges,
                           *self._settable})

    # ------------------------------------------------------------- exports

    def snapshot(self) -> Dict[str, Dict]:
        """name → {type, ...values}; gauge callbacks are sampled now."""
        out: Dict[str, Dict] = {}
        # Gauges sample first: a raising callback bumps the scrape-errors
        # counter, and copying counters afterwards means the bump is
        # visible in this same snapshot rather than the next one.
        with self._lock:
            gauges = dict(self._gauges)
        gauge_records: Dict[str, Dict] = {}
        scrape_errors = 0
        for name, fn in gauges.items():
            try:
                gauge_records[name] = {"type": "gauge", "value": fn()}
            except Exception as e:   # noqa: BLE001 — one bad gauge ≠ no metrics
                gauge_records[name] = {"type": "gauge", "error": str(e)}
                scrape_errors += 1
        err_counter = self.counter(SCRAPE_ERRORS_SENSOR)
        if scrape_errors:
            err_counter.inc(scrape_errors)
        with self._lock:
            counters = dict(self._counters)
            timers = dict(self._timers)
            settable = dict(self._settable)
        for name, c in counters.items():
            out[name] = {"type": "counter", "count": c.count,
                         "one_min_rate": round(c.rate(), 6)}
        for name, t in timers.items():
            out[name] = {"type": "timer", **{k: round(v, 4)
                                             for k, v in t.stats().items()}}
        out.update(gauge_records)
        for name, g in settable.items():
            out[name] = {"type": "gauge", "value": g.value}
        return out

    def prometheus_text(self, prefix: str = "kafka_cruisecontrol") -> str:
        """Prometheus exposition format for the /metrics endpoint."""
        lines: List[str] = []

        def clean(name: str) -> str:
            return f"{prefix}_{_sanitize(name)}"

        for name, record in sorted(self.snapshot().items()):
            base = clean(name)
            if record["type"] == "counter":
                lines.append(f"# TYPE {base} counter")
                lines.append(f"{base} {record['count']}")
                lines.append(f"{base}_one_min_rate {record['one_min_rate']}")
            elif record["type"] == "timer":
                lines.append(f"# TYPE {base} summary")
                lines.append(f"{base}_count {record['count']}")
                for k in ("mean_ms", "max_ms", "p50_ms", "p99_ms",
                          "p999_ms"):
                    lines.append(f"{base}_{k} {record[k]}")
            else:
                value = record.get("value")
                if value is None:
                    continue
                lines.append(f"# TYPE {base} gauge")
                if isinstance(value, bool):
                    value = int(value)
                lines.append(f"{base} {value}")
        return "\n".join(lines) + "\n"


_GLOBAL: Optional[MetricRegistry] = None


def registry() -> MetricRegistry:
    """Process-wide registry (components grab their sensors from here)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = MetricRegistry()
    return _GLOBAL
