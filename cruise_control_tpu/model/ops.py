"""Pure tensor queries over (ClusterState, Placement).

These replace the reference's incremental load bookkeeping: where
``ClusterModel.relocateReplica``/``relocateLeadership`` (ClusterModel.java:
375-434) push load deltas up the replica->broker->host->rack tree, we recompute
aggregate views with segment-sums — O(R) work the TPU does in microseconds, and
trivially correct under any batch of simultaneous moves.

All functions are jit-safe (static shapes, no data-dependent control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.state import ClusterState, Placement


def effective_load(state: ClusterState, placement: Placement) -> jnp.ndarray:
    """f32[R, 4]: each replica's load in its current role, zeroed for padding."""
    load = jnp.where(placement.is_leader[:, None], state.leader_load, state.follower_load)
    return load * state.valid[:, None]


def broker_load(state: ClusterState, placement: Placement) -> jnp.ndarray:
    """f32[B, 4]: per-broker utilization (sum of effective replica loads)."""
    return jax.ops.segment_sum(
        effective_load(state, placement), placement.broker,
        num_segments=state.num_brokers_padded,
    )


def host_load(state: ClusterState, placement: Placement, num_hosts: int) -> jnp.ndarray:
    """f32[H, 4]: per-host utilization (brokers aggregated by host).

    Host scope matters for CPU/NW capacity checks (Resource.java: CPU+NW are
    host resources).
    """
    return jax.ops.segment_sum(broker_load(state, placement), state.host, num_segments=num_hosts)


def disk_load(state: ClusterState, placement: Placement) -> jnp.ndarray:
    """f32[B, D]: per-logdir DISK utilization for JBOD brokers."""
    flat = placement.broker * state.num_disks_per_broker + placement.disk
    sums = jax.ops.segment_sum(
        effective_load(state, placement)[:, Resource.DISK], flat,
        num_segments=state.num_brokers_padded * state.num_disks_per_broker,
    )
    return sums.reshape(state.num_brokers_padded, state.num_disks_per_broker)


def potential_leadership_load(state: ClusterState, placement: Placement) -> jnp.ndarray:
    """f32[B]: NW_OUT if a broker led *all* its replicas.

    Reference: ``ClusterModel._potentialLeadershipLoadByBrokerId`` maintained in
    ``setReplicaLoad`` (ClusterModel.java:740-764), consumed by PotentialNwOutGoal.
    """
    pot = state.leader_load[:, Resource.NW_OUT] * state.valid
    return jax.ops.segment_sum(pot, placement.broker, num_segments=state.num_brokers_padded)


def replica_counts(state: ClusterState, placement: Placement) -> jnp.ndarray:
    """i32[B]: replicas per broker."""
    return jax.ops.segment_sum(
        state.valid.astype(jnp.int32), placement.broker,
        num_segments=state.num_brokers_padded,
    )


def leader_counts(state: ClusterState, placement: Placement) -> jnp.ndarray:
    """i32[B]: leader replicas per broker."""
    return jax.ops.segment_sum(
        (state.valid & placement.is_leader).astype(jnp.int32), placement.broker,
        num_segments=state.num_brokers_padded,
    )


def topic_broker_counts(state: ClusterState, placement: Placement, num_topics: int) -> jnp.ndarray:
    """i32[T, B]: replicas of each topic on each broker (TopicReplicaDistributionGoal)."""
    b = state.num_brokers_padded
    flat = state.topic * b + placement.broker
    counts = jax.ops.segment_sum(
        state.valid.astype(jnp.int32), flat, num_segments=num_topics * b,
    )
    return counts.reshape(num_topics, b)


def topic_leader_counts(state: ClusterState, placement: Placement, num_topics: int) -> jnp.ndarray:
    """i32[T, B]: leaders of each topic on each broker (MinTopicLeadersPerBrokerGoal)."""
    b = state.num_brokers_padded
    flat = state.topic * b + placement.broker
    counts = jax.ops.segment_sum(
        (state.valid & placement.is_leader).astype(jnp.int32), flat,
        num_segments=num_topics * b,
    )
    return counts.reshape(num_topics, b)


def partition_rack_counts(state: ClusterState, placement: Placement, num_racks: int,
                          num_partitions: int) -> jnp.ndarray:
    """i32[P, K]: replicas of each partition on each rack (rack-awareness goals)."""
    rack_of_replica = state.rack[placement.broker]
    flat = state.partition * num_racks + rack_of_replica
    counts = jax.ops.segment_sum(
        state.valid.astype(jnp.int32), flat, num_segments=num_partitions * num_racks,
    )
    return counts.reshape(num_partitions, num_racks)


def partition_broker_matrix(state: ClusterState, placement: Placement,
                            num_partitions: int) -> jnp.ndarray:
    """bool[P, B]: does partition p have a replica on broker b.

    Dense P×B is too big at the 1M-replica scale — use only on small models
    (tests); goals use replica-indexed forms instead.
    """
    b = state.num_brokers_padded
    flat = state.partition * b + placement.broker
    counts = jax.ops.segment_sum(
        state.valid.astype(jnp.int32), flat, num_segments=num_partitions * b,
    )
    return (counts > 0).reshape(num_partitions, b)


def replicas_on_same_rack(state: ClusterState, placement: Placement,
                          num_racks: int, num_partitions: int) -> jnp.ndarray:
    """i32[R]: for each replica, how many *sibling* replicas of its partition
    share its rack (0 == rack-aware ok)."""
    prc = partition_rack_counts(state, placement, num_racks, num_partitions)
    rack_of_replica = state.rack[placement.broker]
    return prc[state.partition, rack_of_replica] - 1


def partition_leader_broker(state: ClusterState, placement: Placement,
                            num_partitions: int) -> jnp.ndarray:
    """i32[P]: broker index of each partition's leader (-1 if none/invalid)."""
    contrib = jnp.where(state.valid & placement.is_leader, placement.broker + 1, 0)
    got = jax.ops.segment_max(contrib, state.partition, num_segments=num_partitions)
    return got - 1


def partition_size(state: ClusterState, num_partitions: int) -> jnp.ndarray:
    """f32[P]: disk size of one replica of each partition (max over replicas)."""
    return jax.ops.segment_max(
        jnp.where(state.valid, state.leader_load[:, Resource.DISK], 0.0),
        state.partition, num_segments=num_partitions,
    )


def average_alive_utilization(state: ClusterState, placement: Placement) -> jnp.ndarray:
    """f32[4]: cluster-wide utilization / capacity over alive brokers.

    Reference: ClusterModel.load() vs aliveCapacityFor — the baseline for
    ResourceDistributionGoal's balance band.
    """
    total_load = jnp.sum(broker_load(state, placement) * state.broker_valid[:, None], axis=0)
    alive = state.alive & state.broker_valid
    total_cap = jnp.sum(state.capacity * alive[:, None], axis=0)
    return total_load / jnp.maximum(total_cap, 1e-9)


def utilization_matrix(state: ClusterState, placement: Placement) -> jnp.ndarray:
    """f32[4, B]: per-resource utilization fraction per broker
    (reference: ClusterModel.utilizationMatrix :1323-1357)."""
    load = broker_load(state, placement)
    return (load / jnp.maximum(state.capacity, 1e-9)).T
