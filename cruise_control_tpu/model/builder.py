"""Host-side mutable cluster model builder.

This is the boundary between the outside world (metadata + metric samples, or
test fixtures) and the tensor model.  It mirrors the reference ClusterModel's
mutation API — ``createBroker`` :923-940, ``createReplica`` :802-883,
``setReplicaLoad`` :740-764, ``relocateReplica`` :375-389,
``relocateLeadership`` :402-434, ``setBrokerState`` :292-331,
``createOrDeleteReplicas`` :962-1027 — but exists only to *construct* snapshots:
``freeze()`` emits the (ClusterState, Placement, ClusterMeta) triple and all
optimization happens on those tensors, never on this object graph.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from cruise_control_tpu.common.resources import Resource, NUM_RESOURCES
from cruise_control_tpu.model import cpu_model
from cruise_control_tpu.model.state import (
    BROKER_DELTA_FIELDS,
    ClusterDelta,
    ClusterMeta,
    ClusterState,
    Placement,
    REPLICA_DELTA_FIELDS,
    device_put_state,
    pack_state_arrays,
)

LoadLike = Union[Dict[Resource, float], Sequence[float], np.ndarray]


def _load_array(load: LoadLike) -> np.ndarray:
    if isinstance(load, dict):
        arr = np.zeros(NUM_RESOURCES, dtype=np.float64)
        for k, v in load.items():
            arr[int(k)] = v
        return arr
    arr = np.asarray(load, dtype=np.float64)
    if arr.shape != (NUM_RESOURCES,):
        raise ValueError(f"load must have {NUM_RESOURCES} entries, got {arr.shape}")
    return arr.copy()


@dataclass
class Replica:
    topic: str
    partition: int
    broker_id: int
    is_leader: bool
    disk: int = 0
    leader_load: np.ndarray = field(default_factory=lambda: np.zeros(NUM_RESOURCES))
    follower_load: Optional[np.ndarray] = None  # derived from leader_load if None
    offline: bool = False
    orig_broker: Optional[int] = None

    def effective_follower_load(self) -> np.ndarray:
        if self.follower_load is not None:
            return self.follower_load
        fl = self.leader_load.copy()
        fl[Resource.NW_OUT] = 0.0
        fl[Resource.CPU] = cpu_model.follower_cpu_from_leader_load(
            self.leader_load[Resource.NW_IN], self.leader_load[Resource.NW_OUT],
            self.leader_load[Resource.CPU])
        return fl


@dataclass
class Broker:
    broker_id: int
    rack: str
    host: str
    capacity: np.ndarray                      # f64[4]
    disk_capacities: np.ndarray               # f64[D>=1]
    alive: bool = True
    new_broker: bool = False
    demoted: bool = False
    disk_alive: Optional[np.ndarray] = None   # bool[D]

    def __post_init__(self):
        if self.disk_alive is None:
            self.disk_alive = np.ones(len(self.disk_capacities), dtype=bool)


class ClusterModel:
    """Mutable cluster under construction; ``freeze()`` emits tensors."""

    def __init__(self):
        self._brokers: Dict[int, Broker] = {}
        # (topic, partition) -> ordered replica list (index 0 need not be leader;
        # ``pos`` order is the Kafka replica-list order; exactly one is_leader).
        self._partitions: Dict[Tuple[str, int], List[Replica]] = {}
        self._rack_order: List[str] = []
        self._host_order: List[str] = []
        # Incrementally-maintained counts so hot paths never re-walk the
        # partition map just to size a padding bucket.
        self._num_replicas = 0
        # Monotone mutation version; stamped into ClusterMeta.extra at freeze
        # so consumers can tell which builder state a snapshot reflects.
        self._version = 0
        # --- delta journal (see enable_delta_tracking) ---
        self._track = False
        self._touched: List[Replica] = []
        self._touched_brokers: set = set()
        self._structural = False
        self._full_refreeze_reason: Optional[str] = None
        self._frozen: Optional[dict] = None   # row bookkeeping from last freeze
        self._frozen_version = -1
        self._walk_token = 0

    # ----------------------------------------------------------- counts/version

    def counts(self) -> Tuple[int, int]:
        """(num_replicas, num_brokers) — O(1), maintained incrementally."""
        return self._num_replicas, len(self._brokers)

    @property
    def version(self) -> int:
        return self._version

    # ------------------------------------------------------------ delta journal

    def enable_delta_tracking(self) -> None:
        """Start journalling mutations so :meth:`collect_delta` can emit a
        sparse :class:`ClusterDelta` instead of forcing a full re-freeze.
        Row bookkeeping is (re)established by the next :meth:`freeze`."""
        self._track = True
        self._reset_journal()

    @property
    def delta_tracking(self) -> bool:
        return self._track

    def _reset_journal(self) -> None:
        self._touched = []
        self._touched_brokers = set()
        self._structural = False
        self._full_refreeze_reason = None

    # ------------------------------------------------------------------ brokers

    def create_broker(self, rack: str, host: str, broker_id: int, capacity: LoadLike,
                      disk_capacities: Optional[Sequence[float]] = None,
                      new_broker: bool = False) -> Broker:
        if broker_id in self._brokers:
            raise ValueError(f"broker {broker_id} already exists")
        cap = _load_array(capacity)
        if disk_capacities is None:
            disks = np.array([cap[Resource.DISK]], dtype=np.float64)
        else:
            disks = np.asarray(disk_capacities, dtype=np.float64)
            cap[Resource.DISK] = disks.sum()
        b = Broker(broker_id, rack, host, cap, disks, new_broker=new_broker)
        self._brokers[broker_id] = b
        if rack not in self._rack_order:
            self._rack_order.append(rack)
        if host not in self._host_order:
            self._host_order.append(host)
        self._version += 1
        if self._track:
            # A new broker changes the broker-axis identity (and possibly the
            # disk-axis width); deltas cannot express that.
            self._full_refreeze_reason = "broker-created"
        return b

    def broker(self, broker_id: int) -> Broker:
        return self._brokers[broker_id]

    def brokers(self) -> List[Broker]:
        return list(self._brokers.values())

    def _placement_offline(self, broker_id: int, disk: int) -> bool:
        """A replica is offline when its broker or its logdir is dead."""
        b = self._brokers[broker_id]
        return (not b.alive) or disk >= len(b.disk_alive) or not bool(b.disk_alive[disk])

    def set_broker_state(self, broker_id: int, alive: bool) -> None:
        """Reference ClusterModel.setBrokerState :292-331: killing a broker marks
        its replicas offline (they must be moved off)."""
        self._brokers[broker_id].alive = alive
        self._version += 1
        if self._track:
            self._touched_brokers.add(broker_id)
        for replicas in self._partitions.values():
            for r in replicas:
                if r.broker_id == broker_id:
                    r.offline = self._placement_offline(broker_id, r.disk)
                    if self._track:
                        self._touched.append(r)

    def mark_disk_dead(self, broker_id: int, disk: int) -> None:
        """Reference ClusterModel.markDiskDead :340."""
        b = self._brokers[broker_id]
        b.disk_alive[disk] = False
        b.capacity[Resource.DISK] = b.disk_capacities[b.disk_alive].sum()
        self._version += 1
        if self._track:
            self._touched_brokers.add(broker_id)
        for replicas in self._partitions.values():
            for r in replicas:
                if r.broker_id == broker_id and r.disk == disk:
                    r.offline = True
                    if self._track:
                        self._touched.append(r)

    # ----------------------------------------------------------------- replicas

    def create_replica(self, topic: str, partition: int, broker_id: int, index: int,
                       is_leader: bool, disk: int = 0) -> Replica:
        if broker_id not in self._brokers:
            raise ValueError(f"unknown broker {broker_id}")
        key = (topic, partition)
        replicas = self._partitions.setdefault(key, [])
        if any(r.broker_id == broker_id for r in replicas):
            raise ValueError(f"partition {key} already has a replica on broker {broker_id}")
        if is_leader and any(r.is_leader for r in replicas):
            raise ValueError(f"partition {key} already has a leader")
        if index < 0:
            raise ValueError(f"replica-list index must be >= 0, got {index}")
        r = Replica(topic, partition, broker_id, is_leader,
                    disk=disk, orig_broker=broker_id,
                    offline=self._placement_offline(broker_id, disk))
        replicas.insert(min(index, len(replicas)), r)
        self._num_replicas += 1
        self._version += 1
        if self._track:
            self._structural = True
        return r

    def replica(self, topic: str, partition: int, broker_id: int) -> Replica:
        for r in self._partitions[(topic, partition)]:
            if r.broker_id == broker_id:
                return r
        raise KeyError(f"no replica of {topic}-{partition} on broker {broker_id}")

    def partition(self, topic: str, partition: int) -> List[Replica]:
        return self._partitions[(topic, partition)]

    def partitions(self) -> Dict[Tuple[str, int], List[Replica]]:
        return self._partitions

    def set_replica_load(self, topic: str, partition: int, broker_id: int,
                         load: LoadLike, follower_load: Optional[LoadLike] = None) -> None:
        """Set a replica's leader-role load; follower-role load is derived via
        the CPU model unless given explicitly (reference: setReplicaLoad
        :740-764 + MonitorUtils.populatePartitionLoad :382-447)."""
        r = self.replica(topic, partition, broker_id)
        r.leader_load = _load_array(load)
        r.follower_load = None if follower_load is None else _load_array(follower_load)
        self._version += 1
        if self._track:
            self._touched.append(r)

    def delete_replica(self, topic: str, partition: int, broker_id: int) -> None:
        replicas = self._partitions[(topic, partition)]
        r = self.replica(topic, partition, broker_id)
        if r.is_leader and len(replicas) > 1:
            raise ValueError("cannot delete the leader while followers exist")
        replicas.remove(r)
        if not replicas:
            del self._partitions[(topic, partition)]
        self._num_replicas -= 1
        self._version += 1
        if self._track:
            self._structural = True

    def relocate_replica(self, topic: str, partition: int, src_broker: int, dst_broker: int,
                         dst_disk: int = 0) -> None:
        r = self.replica(topic, partition, src_broker)
        if any(x.broker_id == dst_broker for x in self._partitions[(topic, partition)]):
            raise ValueError(f"{topic}-{partition} already on broker {dst_broker}")
        r.broker_id = dst_broker
        r.disk = dst_disk
        r.offline = self._placement_offline(dst_broker, dst_disk)
        self._version += 1
        if self._track:
            self._touched.append(r)

    def relocate_leadership(self, topic: str, partition: int, src_broker: int,
                            dst_broker: int) -> bool:
        src = self.replica(topic, partition, src_broker)
        if not src.is_leader:
            return False
        dst = self.replica(topic, partition, dst_broker)
        if dst.is_leader:
            raise ValueError("destination is already the leader")
        src.is_leader = False
        dst.is_leader = True
        self._version += 1
        if self._track:
            self._touched.append(src)
            self._touched.append(dst)
        return True

    def create_or_delete_replicas(self, topic: str, target_rf: int,
                                  broker_order: Optional[List[int]] = None) -> None:
        """Change replication factor of a topic (reference: ClusterModel.
        createOrDeleteReplicas :962-1027).  New replicas are placed round-robin
        over alive brokers not already holding the partition; deletions drop
        the last non-leader replicas."""
        order = broker_order or sorted(b.broker_id for b in self._brokers.values() if b.alive)
        cursor = 0
        for (t, p), replicas in list(self._partitions.items()):
            if t != topic:
                continue
            while len(replicas) > target_rf:
                victim = next((r for r in reversed(replicas) if not r.is_leader), None)
                if victim is None:
                    raise ValueError(
                        f"cannot reduce {t}-{p} to rf={target_rf}: only the leader remains")
                replicas.remove(victim)
                self._num_replicas -= 1
                self._version += 1
                if self._track:
                    self._structural = True
            holders = {r.broker_id for r in replicas}
            while len(replicas) < target_rf:
                for _ in range(len(order)):
                    cand = order[cursor % len(order)]
                    cursor += 1
                    if cand not in holders:
                        break
                else:
                    raise ValueError(f"not enough brokers for rf={target_rf}")
                r = Replica(t, p, cand, is_leader=False, orig_broker=cand)
                # Followers inherit the partition's follower-role load profile.
                leader = next(x for x in replicas if x.is_leader)
                r.leader_load = leader.leader_load.copy()
                replicas.append(r)
                holders.add(cand)
                self._num_replicas += 1
                self._version += 1
                if self._track:
                    self._structural = True

    # ------------------------------------------------------------------- freeze

    def freeze(self, pad_replicas_to: int = 1, pad_brokers_to: int = 1,
               ) -> Tuple[ClusterState, Placement, ClusterMeta]:
        packed, meta = self.freeze_packed(pad_replicas_to=pad_replicas_to,
                                          pad_brokers_to=pad_brokers_to)
        state, placement = device_put_state(packed)
        return state, placement, meta

    def freeze_packed(self, pad_replicas_to: int = 1, pad_brokers_to: int = 1,
                      ) -> Tuple[Dict[str, np.ndarray], ClusterMeta]:
        """Host half of :meth:`freeze`: walk the object graph into padded,
        dtype-final numpy arrays (see ``pack_state_arrays``) without touching
        the device.  ``device_put_state`` turns the result into tensors; the
        split lets the resident-model path time packing and transfer apart."""
        broker_ids = list(self._brokers.keys())
        broker_index = {b: i for i, b in enumerate(broker_ids)}
        racks = list(self._rack_order)
        hosts = list(self._host_order)
        rack_index = {r: i for i, r in enumerate(racks)}
        host_index = {h: i for i, h in enumerate(hosts)}

        topics: List[str] = []
        topic_index: Dict[str, int] = {}
        partitions: List[Tuple[int, int]] = []
        replica_rows: List[Replica] = []
        part_of_replica: List[int] = []
        pos_of_replica: List[int] = []
        for (t, p), replicas in self._partitions.items():
            if t not in topic_index:
                topic_index[t] = len(topics)
                topics.append(t)
            pid = len(partitions)
            partitions.append((topic_index[t], p))
            for pos, r in enumerate(replicas):
                replica_rows.append(r)
                part_of_replica.append(pid)
                pos_of_replica.append(pos)

        r_n = len(replica_rows)
        b_n = len(broker_ids)
        d_n = max((len(b.disk_capacities) for b in self._brokers.values()), default=1)

        leader_load = np.zeros((r_n, NUM_RESOURCES))
        follower_load = np.zeros((r_n, NUM_RESOURCES))
        assignment = np.zeros(r_n, dtype=np.int64)
        disk = np.zeros(r_n, dtype=np.int64)
        is_leader = np.zeros(r_n, dtype=bool)
        topic_arr = np.zeros(r_n, dtype=np.int64)
        orig_broker = np.zeros(r_n, dtype=np.int64)
        offline = np.zeros(r_n, dtype=bool)
        for i, r in enumerate(replica_rows):
            leader_load[i] = r.leader_load
            follower_load[i] = r.effective_follower_load()
            assignment[i] = broker_index[r.broker_id]
            disk[i] = r.disk
            is_leader[i] = r.is_leader
            topic_arr[i] = topic_index[r.topic]
            orig_broker[i] = broker_index.get(r.orig_broker, broker_index[r.broker_id])
            offline[i] = r.offline

        capacity = np.zeros((b_n, NUM_RESOURCES))
        host_arr = np.zeros(b_n, dtype=np.int64)
        rack_arr = np.zeros(b_n, dtype=np.int64)
        alive = np.zeros(b_n, dtype=bool)
        new_broker = np.zeros(b_n, dtype=bool)
        disk_capacity = np.zeros((b_n, d_n))
        disk_alive = np.zeros((b_n, d_n), dtype=bool)
        for i, bid in enumerate(broker_ids):
            b = self._brokers[bid]
            capacity[i] = b.capacity
            host_arr[i] = host_index[b.host]
            rack_arr[i] = rack_index[b.rack]
            alive[i] = b.alive
            new_broker[i] = b.new_broker
            nd = len(b.disk_capacities)
            disk_capacity[i, :nd] = b.disk_capacities
            disk_alive[i, :nd] = b.disk_alive

        packed = pack_state_arrays(
            dict(leader_load=leader_load, follower_load=follower_load,
                 partition=np.asarray(part_of_replica), topic=topic_arr,
                 pos=np.asarray(pos_of_replica), orig_broker=orig_broker,
                 offline=offline, assignment=assignment, disk=disk,
                 is_leader=is_leader, capacity=capacity, host=host_arr,
                 rack=rack_arr, alive=alive, new_broker=new_broker,
                 disk_capacity=disk_capacity, disk_alive=disk_alive),
            pad_replicas_to=pad_replicas_to, pad_brokers_to=pad_brokers_to,
        )
        meta = ClusterMeta(broker_ids=broker_ids, topics=topics, partitions=partitions,
                           racks=racks, hosts=hosts, num_replicas=r_n, num_brokers=b_n,
                           extra={"model_version": self._version})
        if self._track:
            self._note_frozen(packed, replica_rows, broker_ids, broker_index,
                              np.asarray(part_of_replica, dtype=np.int32),
                              topic_arr.astype(np.int32),
                              np.asarray(pos_of_replica, dtype=np.int32))
        return packed, meta

    def _note_frozen(self, packed: Dict[str, np.ndarray],
                     replica_rows: List[Replica],
                     broker_ids: List[int], broker_index: Dict[int, int],
                     part_arr: np.ndarray, topic_arr: np.ndarray,
                     pos_arr: np.ndarray) -> None:
        """Record the row layout of the snapshot just frozen so later
        mutations can be resolved to dense rows by :meth:`collect_delta`."""
        pad_r = packed["leader_load"].shape[0]
        r_n = len(replica_rows)

        def padded(a: np.ndarray) -> np.ndarray:
            out = np.zeros(pad_r, dtype=np.int32)
            out[:r_n] = a
            return out

        for i, r in enumerate(replica_rows):
            r._row = i
        self._frozen = dict(
            pad_r=pad_r, pad_b=packed["capacity"].shape[0],
            d_n=packed["disk_capacity"].shape[1], count=r_n,
            broker_ids=list(broker_ids), broker_index=dict(broker_index),
            partition=padded(part_arr), topic=padded(topic_arr),
            pos=padded(pos_arr),
        )
        self._frozen_version = self._version
        self._reset_journal()

    # ------------------------------------------------------------ delta collect

    def collect_delta(self, max_updates: int = 1 << 20) -> Optional[ClusterDelta]:
        """Drain the mutation journal into a :class:`ClusterDelta` against the
        last frozen snapshot, or return ``None`` when the accumulated edits
        cannot be expressed as a bounded delta (new broker, too many touched
        rows, no prior freeze) and the caller must full-freeze instead.

        On success the journal is reset and the internal row bookkeeping is
        advanced, so the returned delta must be applied (the builder now
        believes the snapshot matches its current state).
        """
        if not self._track or self._frozen is None:
            return None
        if self._full_refreeze_reason is not None:
            return None
        if self._structural:
            delta = self._collect_structural(max_updates)
        else:
            delta = self._collect_sparse(max_updates)
        if delta is not None:
            delta.from_version = self._frozen_version
            delta.to_version = self._version
            self._frozen_version = self._version
            self._reset_journal()
        return delta

    def _replica_update_rows(self, pairs: List[Tuple[int, Optional[Replica]]],
                             part_arr: np.ndarray, topic_arr: np.ndarray,
                             pos_arr: np.ndarray,
                             broker_index: Dict[int, int],
                             ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Build the replica-axis update arrays for ``(row, replica)`` pairs
        (replica ``None`` ⇒ zero the row out: it was freed by deletions).
        Field dtypes/derivations mirror freeze() exactly so a delta-applied
        snapshot stays bitwise-identical to a fresh freeze."""
        u = len(pairs)
        upd = {k: np.zeros((u,) + shp, dtype=dt)
               for k, dt, shp in REPLICA_DELTA_FIELDS}
        idx = np.zeros(u, dtype=np.int32)
        for j, (row, r) in enumerate(pairs):
            idx[j] = row
            if r is None:
                continue
            upd["leader_load"][j] = r.leader_load.astype(np.float32)
            upd["follower_load"][j] = r.effective_follower_load().astype(np.float32)
            upd["partition"][j] = part_arr[row]
            upd["topic"][j] = topic_arr[row]
            upd["pos"][j] = pos_arr[row]
            upd["orig_broker"][j] = broker_index.get(
                r.orig_broker, broker_index[r.broker_id])
            upd["offline"][j] = r.offline
            upd["valid"][j] = True
            upd["broker"][j] = broker_index[r.broker_id]
            upd["disk"][j] = r.disk
            upd["is_leader"][j] = r.is_leader
        return idx, upd

    def _broker_update_rows(self) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        fz = self._frozen
        d_n = fz["d_n"]
        rows = sorted(fz["broker_index"][bid] for bid in self._touched_brokers)
        v = len(rows)
        if not v:
            return np.zeros(0, dtype=np.int32), {}
        idx = np.asarray(rows, dtype=np.int32)
        upd = {k: np.zeros((v, d_n) if k.startswith("disk_") else
                           ((v, NUM_RESOURCES) if k == "capacity" else (v,)),
                           dtype=dt)
               for k, dt in BROKER_DELTA_FIELDS}
        inv = {i: bid for bid, i in fz["broker_index"].items()}
        for j, row in enumerate(rows):
            b = self._brokers[inv[row]]
            upd["capacity"][j] = b.capacity.astype(np.float32)
            upd["alive"][j] = b.alive
            upd["new_broker"][j] = b.new_broker
            nd = len(b.disk_capacities)
            upd["disk_capacity"][j, :nd] = b.disk_capacities.astype(np.float32)
            upd["disk_alive"][j, :nd] = b.disk_alive
        return idx, upd

    def _collect_sparse(self, max_updates: int) -> Optional[ClusterDelta]:
        """No replicas were created/deleted: every touched replica still sits
        in its frozen row, so the delta is a plain scatter."""
        fz = self._frozen
        rows: Dict[int, Replica] = {}
        for r in self._touched:
            row = getattr(r, "_row", None)
            if row is None:
                return None   # mutated replica unknown to the last freeze
            rows[row] = r
        b_idx, b_upd = self._broker_update_rows()
        if len(rows) + len(b_idx) > max_updates:
            return None
        pairs = [(row, rows[row]) for row in sorted(rows)]
        idx, upd = self._replica_update_rows(
            pairs, fz["partition"], fz["topic"], fz["pos"], fz["broker_index"])
        return ClusterDelta(replica_idx=idx, replica_updates=upd,
                            broker_idx=b_idx, broker_updates=b_upd)

    def _collect_structural(self, max_updates: int) -> Optional[ClusterDelta]:
        """Replicas were created/deleted: dense partition ids and row order
        shift.  Re-walk the partition map exactly like freeze() (list
        structure only — no per-row field packing), derive the old→new row
        permutation, and emit updates only for rows whose identity fields
        moved plus journalled load/liveness touches and freed tail rows."""
        fz = self._frozen
        pad_r = fz["pad_r"]
        broker_index = fz["broker_index"]
        self._walk_token += 1
        token = self._walk_token

        topics: List[str] = []
        topic_index: Dict[str, int] = {}
        partitions: List[Tuple[int, int]] = []
        new_rows: List[Replica] = []
        part_of: List[int] = []
        pos_of: List[int] = []
        for (t, p), replicas in self._partitions.items():
            if t not in topic_index:
                topic_index[t] = len(topics)
                topics.append(t)
            pid = len(partitions)
            partitions.append((topic_index[t], p))
            for pos, r in enumerate(replicas):
                r._wtok = token
                r._new_row = len(new_rows)
                new_rows.append(r)
                part_of.append(pid)
                pos_of.append(pos)

        new_count = len(new_rows)
        old_count = fz["count"]
        if new_count > pad_r:
            return None   # outgrew the bucket — caller re-freezes (re-buckets)

        old_row = np.fromiter((getattr(r, "_row", -1) for r in new_rows),
                              dtype=np.int64, count=new_count)
        new_part = np.asarray(part_of, dtype=np.int32)
        new_pos = np.asarray(pos_of, dtype=np.int32)
        new_topic = np.fromiter((topic_index[r.topic] for r in new_rows),
                                dtype=np.int32, count=new_count)
        g = np.clip(old_row, 0, pad_r - 1)
        changed = (old_row < 0)
        changed |= fz["partition"][g] != new_part
        changed |= fz["pos"][g] != new_pos
        changed |= fz["topic"][g] != new_topic
        changed_set = {int(i) for i in np.nonzero(changed)[0]}
        for r in self._touched:
            if getattr(r, "_wtok", 0) == token:
                changed_set.add(r._new_row)
            # touched replicas absent from the walk were deleted; their old
            # rows are handled by the permutation + freed-tail updates.
        freed = range(new_count, old_count)
        b_idx, b_upd = self._broker_update_rows()
        if len(changed_set) + len(freed) + len(b_idx) > max_updates:
            return None

        pairs: List[Tuple[int, Optional[Replica]]] = (
            [(i, new_rows[i]) for i in sorted(changed_set)]
            + [(i, None) for i in freed])
        idx, upd = self._replica_update_rows(
            pairs, new_part, new_topic, new_pos, broker_index)

        perm = np.arange(pad_r, dtype=np.int32)
        perm[:new_count] = old_row
        meta = ClusterMeta(
            broker_ids=list(fz["broker_ids"]), topics=topics,
            partitions=partitions, racks=list(self._rack_order),
            hosts=list(self._host_order), num_replicas=new_count,
            num_brokers=len(fz["broker_ids"]),
            extra={"model_version": self._version})

        # Commit the new row layout.
        for i, r in enumerate(new_rows):
            r._row = i
        def padded(a: np.ndarray) -> np.ndarray:
            out = np.zeros(pad_r, dtype=np.int32)
            out[:new_count] = a
            return out
        fz["partition"] = padded(new_part)
        fz["topic"] = padded(new_topic)
        fz["pos"] = padded(new_pos)
        fz["count"] = new_count
        return ClusterDelta(replica_idx=idx, replica_updates=upd,
                            broker_idx=b_idx, broker_updates=b_upd,
                            perm=perm, meta=meta)

    # ---------------------------------------------------------------- apply-back

    def apply_placement(self, placement: Placement, meta: ClusterMeta) -> None:
        """Mutate this model to match an optimized placement (used by tests and
        by multi-goal host orchestration when a goal runs on the builder)."""
        broker = np.asarray(placement.broker)
        disk = np.asarray(placement.disk)
        is_leader = np.asarray(placement.is_leader)
        total = sum(len(rs) for rs in self._partitions.values())
        if total != meta.num_replicas:
            raise ValueError(
                f"placement holds {meta.num_replicas} replicas but model has {total}; "
                "was the model edited after freeze()?")
        i = 0
        for (t, p), replicas in self._partitions.items():
            for r in replicas:
                r.broker_id = meta.broker_ids[int(broker[i])]
                r.disk = int(disk[i])
                r.is_leader = bool(is_leader[i])
                r.offline = self._placement_offline(r.broker_id, r.disk)
                i += 1
        self._version += 1
        if self._track:
            # Rewrites every replica; cheaper to re-freeze than to delta.
            self._full_refreeze_reason = "apply-placement"


def builder_from_snapshot(state: ClusterState, placement: Placement,
                          meta: ClusterMeta) -> ClusterModel:
    """Reconstruct a mutable ClusterModel from frozen tensors.

    Inverse of :meth:`ClusterModel.freeze` up to rack/host *ordering* (which
    is rebuilt first-seen over broker order): re-freezing the returned builder
    yields tensors bitwise-identical to re-freezing any builder that produced
    the snapshot, making it the seam for delta-equivalence fuzzing and for
    benching the resident path from generated (builder-less) clusters.
    """
    cm = ClusterModel()
    cap = np.asarray(state.capacity, dtype=np.float64)
    host = np.asarray(state.host)
    rack = np.asarray(state.rack)
    alive = np.asarray(state.alive)
    newb = np.asarray(state.new_broker)
    dcap = np.asarray(state.disk_capacity, dtype=np.float64)
    dalive = np.asarray(state.disk_alive)
    for i, bid in enumerate(meta.broker_ids):
        b = cm.create_broker(meta.racks[int(rack[i])], meta.hosts[int(host[i])],
                             int(bid), cap[i], disk_capacities=dcap[i],
                             new_broker=bool(newb[i]))
        b.alive = bool(alive[i])
        b.disk_alive = dalive[i].copy()
        # Restore the exact (possibly dead-disk-reduced) capacity vector.
        b.capacity = cap[i].copy()

    n = meta.num_replicas
    part = np.asarray(state.partition)[:n]
    pos = np.asarray(state.pos)[:n]
    offline = np.asarray(state.offline)[:n]
    orig = np.asarray(state.orig_broker)[:n]
    ll = np.asarray(state.leader_load, dtype=np.float64)[:n]
    fl = np.asarray(state.follower_load, dtype=np.float64)[:n]
    broker = np.asarray(placement.broker)[:n]
    disk = np.asarray(placement.disk)[:n]
    lead = np.asarray(placement.is_leader)[:n]
    order = np.lexsort((pos, part))
    for row in order:
        row = int(row)
        t_i, p_num = meta.partitions[int(part[row])]
        r = cm.create_replica(meta.topics[t_i], int(p_num),
                              meta.broker_ids[int(broker[row])],
                              index=int(pos[row]), is_leader=bool(lead[row]),
                              disk=int(disk[row]))
        r.leader_load = ll[row]
        # Keep the frozen follower load verbatim (the CPU-model derivation
        # would re-round through float32 differently).
        r.follower_load = fl[row]
        r.offline = bool(offline[row])
        r.orig_broker = meta.broker_ids[int(orig[row])]
    return cm
