"""Host-side mutable cluster model builder.

This is the boundary between the outside world (metadata + metric samples, or
test fixtures) and the tensor model.  It mirrors the reference ClusterModel's
mutation API — ``createBroker`` :923-940, ``createReplica`` :802-883,
``setReplicaLoad`` :740-764, ``relocateReplica`` :375-389,
``relocateLeadership`` :402-434, ``setBrokerState`` :292-331,
``createOrDeleteReplicas`` :962-1027 — but exists only to *construct* snapshots:
``freeze()`` emits the (ClusterState, Placement, ClusterMeta) triple and all
optimization happens on those tensors, never on this object graph.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from cruise_control_tpu.common.resources import Resource, NUM_RESOURCES
from cruise_control_tpu.model import cpu_model
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement, make_state

LoadLike = Union[Dict[Resource, float], Sequence[float], np.ndarray]


def _load_array(load: LoadLike) -> np.ndarray:
    if isinstance(load, dict):
        arr = np.zeros(NUM_RESOURCES, dtype=np.float64)
        for k, v in load.items():
            arr[int(k)] = v
        return arr
    arr = np.asarray(load, dtype=np.float64)
    if arr.shape != (NUM_RESOURCES,):
        raise ValueError(f"load must have {NUM_RESOURCES} entries, got {arr.shape}")
    return arr.copy()


@dataclass
class Replica:
    topic: str
    partition: int
    broker_id: int
    is_leader: bool
    disk: int = 0
    leader_load: np.ndarray = field(default_factory=lambda: np.zeros(NUM_RESOURCES))
    follower_load: Optional[np.ndarray] = None  # derived from leader_load if None
    offline: bool = False
    orig_broker: Optional[int] = None

    def effective_follower_load(self) -> np.ndarray:
        if self.follower_load is not None:
            return self.follower_load
        fl = self.leader_load.copy()
        fl[Resource.NW_OUT] = 0.0
        fl[Resource.CPU] = cpu_model.follower_cpu_from_leader_load(
            self.leader_load[Resource.NW_IN], self.leader_load[Resource.NW_OUT],
            self.leader_load[Resource.CPU])
        return fl


@dataclass
class Broker:
    broker_id: int
    rack: str
    host: str
    capacity: np.ndarray                      # f64[4]
    disk_capacities: np.ndarray               # f64[D>=1]
    alive: bool = True
    new_broker: bool = False
    demoted: bool = False
    disk_alive: Optional[np.ndarray] = None   # bool[D]

    def __post_init__(self):
        if self.disk_alive is None:
            self.disk_alive = np.ones(len(self.disk_capacities), dtype=bool)


class ClusterModel:
    """Mutable cluster under construction; ``freeze()`` emits tensors."""

    def __init__(self):
        self._brokers: Dict[int, Broker] = {}
        # (topic, partition) -> ordered replica list (index 0 need not be leader;
        # ``pos`` order is the Kafka replica-list order; exactly one is_leader).
        self._partitions: Dict[Tuple[str, int], List[Replica]] = {}
        self._rack_order: List[str] = []
        self._host_order: List[str] = []

    # ------------------------------------------------------------------ brokers

    def create_broker(self, rack: str, host: str, broker_id: int, capacity: LoadLike,
                      disk_capacities: Optional[Sequence[float]] = None,
                      new_broker: bool = False) -> Broker:
        if broker_id in self._brokers:
            raise ValueError(f"broker {broker_id} already exists")
        cap = _load_array(capacity)
        if disk_capacities is None:
            disks = np.array([cap[Resource.DISK]], dtype=np.float64)
        else:
            disks = np.asarray(disk_capacities, dtype=np.float64)
            cap[Resource.DISK] = disks.sum()
        b = Broker(broker_id, rack, host, cap, disks, new_broker=new_broker)
        self._brokers[broker_id] = b
        if rack not in self._rack_order:
            self._rack_order.append(rack)
        if host not in self._host_order:
            self._host_order.append(host)
        return b

    def broker(self, broker_id: int) -> Broker:
        return self._brokers[broker_id]

    def brokers(self) -> List[Broker]:
        return list(self._brokers.values())

    def _placement_offline(self, broker_id: int, disk: int) -> bool:
        """A replica is offline when its broker or its logdir is dead."""
        b = self._brokers[broker_id]
        return (not b.alive) or disk >= len(b.disk_alive) or not bool(b.disk_alive[disk])

    def set_broker_state(self, broker_id: int, alive: bool) -> None:
        """Reference ClusterModel.setBrokerState :292-331: killing a broker marks
        its replicas offline (they must be moved off)."""
        self._brokers[broker_id].alive = alive
        for replicas in self._partitions.values():
            for r in replicas:
                if r.broker_id == broker_id:
                    r.offline = self._placement_offline(broker_id, r.disk)

    def mark_disk_dead(self, broker_id: int, disk: int) -> None:
        """Reference ClusterModel.markDiskDead :340."""
        b = self._brokers[broker_id]
        b.disk_alive[disk] = False
        b.capacity[Resource.DISK] = b.disk_capacities[b.disk_alive].sum()
        for replicas in self._partitions.values():
            for r in replicas:
                if r.broker_id == broker_id and r.disk == disk:
                    r.offline = True

    # ----------------------------------------------------------------- replicas

    def create_replica(self, topic: str, partition: int, broker_id: int, index: int,
                       is_leader: bool, disk: int = 0) -> Replica:
        if broker_id not in self._brokers:
            raise ValueError(f"unknown broker {broker_id}")
        key = (topic, partition)
        replicas = self._partitions.setdefault(key, [])
        if any(r.broker_id == broker_id for r in replicas):
            raise ValueError(f"partition {key} already has a replica on broker {broker_id}")
        if is_leader and any(r.is_leader for r in replicas):
            raise ValueError(f"partition {key} already has a leader")
        if index < 0:
            raise ValueError(f"replica-list index must be >= 0, got {index}")
        r = Replica(topic, partition, broker_id, is_leader,
                    disk=disk, orig_broker=broker_id,
                    offline=self._placement_offline(broker_id, disk))
        replicas.insert(min(index, len(replicas)), r)
        return r

    def replica(self, topic: str, partition: int, broker_id: int) -> Replica:
        for r in self._partitions[(topic, partition)]:
            if r.broker_id == broker_id:
                return r
        raise KeyError(f"no replica of {topic}-{partition} on broker {broker_id}")

    def partition(self, topic: str, partition: int) -> List[Replica]:
        return self._partitions[(topic, partition)]

    def partitions(self) -> Dict[Tuple[str, int], List[Replica]]:
        return self._partitions

    def set_replica_load(self, topic: str, partition: int, broker_id: int,
                         load: LoadLike, follower_load: Optional[LoadLike] = None) -> None:
        """Set a replica's leader-role load; follower-role load is derived via
        the CPU model unless given explicitly (reference: setReplicaLoad
        :740-764 + MonitorUtils.populatePartitionLoad :382-447)."""
        r = self.replica(topic, partition, broker_id)
        r.leader_load = _load_array(load)
        r.follower_load = None if follower_load is None else _load_array(follower_load)

    def delete_replica(self, topic: str, partition: int, broker_id: int) -> None:
        replicas = self._partitions[(topic, partition)]
        r = self.replica(topic, partition, broker_id)
        if r.is_leader and len(replicas) > 1:
            raise ValueError("cannot delete the leader while followers exist")
        replicas.remove(r)
        if not replicas:
            del self._partitions[(topic, partition)]

    def relocate_replica(self, topic: str, partition: int, src_broker: int, dst_broker: int,
                         dst_disk: int = 0) -> None:
        r = self.replica(topic, partition, src_broker)
        if any(x.broker_id == dst_broker for x in self._partitions[(topic, partition)]):
            raise ValueError(f"{topic}-{partition} already on broker {dst_broker}")
        r.broker_id = dst_broker
        r.disk = dst_disk
        r.offline = self._placement_offline(dst_broker, dst_disk)

    def relocate_leadership(self, topic: str, partition: int, src_broker: int,
                            dst_broker: int) -> bool:
        src = self.replica(topic, partition, src_broker)
        if not src.is_leader:
            return False
        dst = self.replica(topic, partition, dst_broker)
        if dst.is_leader:
            raise ValueError("destination is already the leader")
        src.is_leader = False
        dst.is_leader = True
        return True

    def create_or_delete_replicas(self, topic: str, target_rf: int,
                                  broker_order: Optional[List[int]] = None) -> None:
        """Change replication factor of a topic (reference: ClusterModel.
        createOrDeleteReplicas :962-1027).  New replicas are placed round-robin
        over alive brokers not already holding the partition; deletions drop
        the last non-leader replicas."""
        order = broker_order or sorted(b.broker_id for b in self._brokers.values() if b.alive)
        cursor = 0
        for (t, p), replicas in list(self._partitions.items()):
            if t != topic:
                continue
            while len(replicas) > target_rf:
                victim = next((r for r in reversed(replicas) if not r.is_leader), None)
                if victim is None:
                    raise ValueError(
                        f"cannot reduce {t}-{p} to rf={target_rf}: only the leader remains")
                replicas.remove(victim)
            holders = {r.broker_id for r in replicas}
            while len(replicas) < target_rf:
                for _ in range(len(order)):
                    cand = order[cursor % len(order)]
                    cursor += 1
                    if cand not in holders:
                        break
                else:
                    raise ValueError(f"not enough brokers for rf={target_rf}")
                r = Replica(t, p, cand, is_leader=False, orig_broker=cand)
                # Followers inherit the partition's follower-role load profile.
                leader = next(x for x in replicas if x.is_leader)
                r.leader_load = leader.leader_load.copy()
                replicas.append(r)
                holders.add(cand)

    # ------------------------------------------------------------------- freeze

    def freeze(self, pad_replicas_to: int = 1, pad_brokers_to: int = 1,
               ) -> Tuple[ClusterState, Placement, ClusterMeta]:
        broker_ids = list(self._brokers.keys())
        broker_index = {b: i for i, b in enumerate(broker_ids)}
        racks = list(self._rack_order)
        hosts = list(self._host_order)
        rack_index = {r: i for i, r in enumerate(racks)}
        host_index = {h: i for i, h in enumerate(hosts)}

        topics: List[str] = []
        topic_index: Dict[str, int] = {}
        partitions: List[Tuple[int, int]] = []
        replica_rows: List[Replica] = []
        part_of_replica: List[int] = []
        pos_of_replica: List[int] = []
        for (t, p), replicas in self._partitions.items():
            if t not in topic_index:
                topic_index[t] = len(topics)
                topics.append(t)
            pid = len(partitions)
            partitions.append((topic_index[t], p))
            for pos, r in enumerate(replicas):
                replica_rows.append(r)
                part_of_replica.append(pid)
                pos_of_replica.append(pos)

        r_n = len(replica_rows)
        b_n = len(broker_ids)
        d_n = max((len(b.disk_capacities) for b in self._brokers.values()), default=1)

        leader_load = np.zeros((r_n, NUM_RESOURCES))
        follower_load = np.zeros((r_n, NUM_RESOURCES))
        assignment = np.zeros(r_n, dtype=np.int64)
        disk = np.zeros(r_n, dtype=np.int64)
        is_leader = np.zeros(r_n, dtype=bool)
        topic_arr = np.zeros(r_n, dtype=np.int64)
        orig_broker = np.zeros(r_n, dtype=np.int64)
        offline = np.zeros(r_n, dtype=bool)
        for i, r in enumerate(replica_rows):
            leader_load[i] = r.leader_load
            follower_load[i] = r.effective_follower_load()
            assignment[i] = broker_index[r.broker_id]
            disk[i] = r.disk
            is_leader[i] = r.is_leader
            topic_arr[i] = topic_index[r.topic]
            orig_broker[i] = broker_index.get(r.orig_broker, broker_index[r.broker_id])
            offline[i] = r.offline

        capacity = np.zeros((b_n, NUM_RESOURCES))
        host_arr = np.zeros(b_n, dtype=np.int64)
        rack_arr = np.zeros(b_n, dtype=np.int64)
        alive = np.zeros(b_n, dtype=bool)
        new_broker = np.zeros(b_n, dtype=bool)
        disk_capacity = np.zeros((b_n, d_n))
        disk_alive = np.zeros((b_n, d_n), dtype=bool)
        for i, bid in enumerate(broker_ids):
            b = self._brokers[bid]
            capacity[i] = b.capacity
            host_arr[i] = host_index[b.host]
            rack_arr[i] = rack_index[b.rack]
            alive[i] = b.alive
            new_broker[i] = b.new_broker
            nd = len(b.disk_capacities)
            disk_capacity[i, :nd] = b.disk_capacities
            disk_alive[i, :nd] = b.disk_alive

        state, placement = make_state(
            dict(leader_load=leader_load, follower_load=follower_load,
                 partition=np.asarray(part_of_replica), topic=topic_arr,
                 pos=np.asarray(pos_of_replica), orig_broker=orig_broker,
                 offline=offline, assignment=assignment, disk=disk,
                 is_leader=is_leader, capacity=capacity, host=host_arr,
                 rack=rack_arr, alive=alive, new_broker=new_broker,
                 disk_capacity=disk_capacity, disk_alive=disk_alive),
            pad_replicas_to=pad_replicas_to, pad_brokers_to=pad_brokers_to,
        )
        meta = ClusterMeta(broker_ids=broker_ids, topics=topics, partitions=partitions,
                           racks=racks, hosts=hosts, num_replicas=r_n, num_brokers=b_n)
        return state, placement, meta

    # ---------------------------------------------------------------- apply-back

    def apply_placement(self, placement: Placement, meta: ClusterMeta) -> None:
        """Mutate this model to match an optimized placement (used by tests and
        by multi-goal host orchestration when a goal runs on the builder)."""
        broker = np.asarray(placement.broker)
        disk = np.asarray(placement.disk)
        is_leader = np.asarray(placement.is_leader)
        total = sum(len(rs) for rs in self._partitions.values())
        if total != meta.num_replicas:
            raise ValueError(
                f"placement holds {meta.num_replicas} replicas but model has {total}; "
                "was the model edited after freeze()?")
        i = 0
        for (t, p), replicas in self._partitions.items():
            for r in replicas:
                r.broker_id = meta.broker_ids[int(broker[i])]
                r.disk = int(disk[i])
                r.is_leader = bool(is_leader[i])
                r.offline = self._placement_offline(r.broker_id, r.disk)
                i += 1
