"""Device-resident cluster model: keep frozen tensors on-device across
requests and scatter-apply builder deltas instead of re-freezing.

Every propose/what-if request used to pay a full O(cluster) host pack plus a
host→device transfer (``builder.freeze``) before the solver even started.
The :class:`ResidentModelService` pins the last (ClusterState, Placement,
ClusterMeta) triple, keyed by its compilesvc shape bucket, and on the next
request asks the builder for a :class:`~cruise_control_tpu.model.state.
ClusterDelta` — a sparse edit script applied into the *donated* device
buffers by a stable-shaped scatter kernel.  A full freeze happens only when
the delta contract cannot hold:

- no resident entry yet, or a different builder object (monitor rebuilt);
- the shape bucket changed (cluster outgrew / shrank past a pad boundary);
- the builder journalled an inexpressible edit (new broker, apply_placement);
- the delta overflowed ``max_delta_slots`` touched rows;
- ``max_delta_chain`` consecutive applies since the last full freeze (bounds
  drift from float scatter reordering — none observed, but cheap insurance);
- an explicit :meth:`invalidate` (solver device failover, config reload).

Sensors: ``Model.full-freezes``, ``Model.delta-applies``,
``Model.resident-invalidations``.  Spans: ``model.freeze`` (host pack),
``model.transfer`` (host→device), ``model.delta_apply`` — so ``/trace``
proves where the milliseconds went.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from cruise_control_tpu.common.metrics import registry as _metric_registry
from cruise_control_tpu.compilesvc.buckets import geometric_bucket
from cruise_control_tpu.model.builder import ClusterModel
from cruise_control_tpu.model.state import (
    BROKER_DELTA_FIELDS,
    ClusterDelta,
    ClusterMeta,
    ClusterState,
    Placement,
    apply_deltas,
    device_put_state,
    empty_delta,
)
from cruise_control_tpu.obsvc.memory import (SUBSYS_RESIDENT, measure_bytes,
                                             memory_ledger)
from cruise_control_tpu.obsvc.tracer import tracer as _tracer

LOG = logging.getLogger(__name__)

FULL_FREEZE_SENSOR = "Model.full-freezes"
DELTA_APPLY_SENSOR = "Model.delta-applies"
INVALIDATION_SENSOR = "Model.resident-invalidations"

# Update-slot padding ladder floor: deltas are padded up to a geometric slot
# bucket so the scatter executable's shape stays stable across requests.
DELTA_SLOT_FLOOR = 64
DELTA_SLOT_GROWTH = 2.0


class ResidentModelService:
    """Owns the device-resident (state, placement, meta) triple.

    All access is serialized by :attr:`lock`; the facade holds it across the
    monitor's builder update + snapshot so delta collection never races a
    concurrent request's apply.
    """

    def __init__(self, enabled: bool = True, max_delta_slots: int = 8192,
                 max_delta_chain: int = 512,
                 slot_floor: int = DELTA_SLOT_FLOOR,
                 slot_growth: float = DELTA_SLOT_GROWTH,
                 pin_wait_s: float = 0.5):
        self.enabled = bool(enabled)
        self.max_delta_slots = int(max_delta_slots)
        self.max_delta_chain = int(max_delta_chain)
        # How long a delta apply waits for pinned solves to drain before
        # falling back to a (never-donating) full freeze.  Short by default:
        # the stall only happens under concurrent solves — boot warmup /
        # precompute overlapping a request — and there a full freeze is
        # cheaper than serializing behind a cold compile.
        self.pin_wait_s = float(pin_wait_s)
        self.slot_floor = int(slot_floor)
        self.slot_growth = float(slot_growth)
        self.lock = threading.RLock()
        # Requests "pin" the tensors they received while their solve is in
        # flight; a delta apply donates (and thereby deletes) the resident
        # buffers, so it waits for the pin count to drain first.
        self._cond = threading.Condition(self.lock)
        self._pins = 0
        self._entry: Optional[dict] = None
        self._invalidation_reasons: Dict[str, int] = {}
        # Materialize the counters at construction so /metrics (and the
        # sensor-drift guard) see them before the first request.
        reg = _metric_registry()
        self._full_freezes = reg.counter(FULL_FREEZE_SENSOR)
        self._delta_applies = reg.counter(DELTA_APPLY_SENSOR)
        self._invalidations = reg.counter(INVALIDATION_SENSOR)

    # ------------------------------------------------------------------ public

    def delta_slots(self, n: int) -> int:
        """Pad an update count to its geometric slot bucket (capped at
        ``max_delta_slots`` — collect already refused anything larger)."""
        return min(geometric_bucket(max(n, 1), self.slot_floor,
                                    self.slot_growth),
                   max(self.max_delta_slots, self.slot_floor))

    def invalidate(self, reason: str) -> None:
        """Drop the resident entry (e.g. after a device failure the buffers
        may be corrupt or unreachable; after failover they live on the wrong
        backend).  The next snapshot will full-freeze."""
        with self.lock:
            if self._entry is None:
                return
            memory_ledger().post(SUBSYS_RESIDENT,
                                 self._entry.get("nbytes", 0), kind="free")
            self._entry = None
            self._invalidations.inc()
            self._invalidation_reasons[reason] = (
                self._invalidation_reasons.get(reason, 0) + 1)
            LOG.info("resident model invalidated: %s", reason)

    def snapshot(self, builder_or_fn,
                 pad_fn: Callable[[int, int], Tuple[int, int]],
                 pin: bool = False,
                 ) -> Tuple[ClusterState, Placement, ClusterMeta]:
        """Return device tensors for the builder's current state — via delta
        apply into the resident buffers when possible, via full freeze
        otherwise.  ``pad_fn`` maps true (replicas, brokers) counts to the
        padded bucket (``compile_service().pad_targets``).

        ``builder_or_fn`` is a ClusterModel or a zero-arg callable returning
        one; callables run under :attr:`lock` so monitor-side builder updates
        cannot race a concurrent request's delta collection.  With
        ``pin=True`` the returned tensors are pinned against donation until
        the caller invokes :meth:`release` (wrap the solve in try/finally).
        """
        with self.lock:
            builder = builder_or_fn() if callable(builder_or_fn) \
                else builder_or_fn
            n_r, n_b = builder.counts()
            bucket = pad_fn(n_r, n_b)
            e = self._entry
            delta: Optional[ClusterDelta] = None
            if (self.enabled and e is not None and e["builder"] is builder
                    and e["bucket"] == bucket and builder.delta_tracking):
                if builder.version == e["version"]:
                    delta = empty_delta(e["version"], e["version"])
                elif e["chain"] < self.max_delta_chain:
                    delta = builder.collect_delta(
                        max_updates=self.max_delta_slots)
            if delta is not None:
                if delta.is_empty and builder.version == e["version"]:
                    out = e["state"], e["placement"], e["meta"]
                elif self._wait_unpinned(self.pin_wait_s):
                    out = self._apply(e, builder, delta)
                else:
                    # A pin leaked or a solve is wedged; a full freeze is
                    # always safe (it never donates the old buffers).
                    LOG.warning("resident pins did not drain; falling back "
                                "to full freeze")
                    out = self._full_freeze(builder, bucket)
            else:
                out = self._full_freeze(builder, bucket)
            if pin:
                self._pins += 1
                memory_ledger().post(SUBSYS_RESIDENT, 0, kind="pin")
            return out

    def release(self) -> None:
        """Drop a ``pin=True`` snapshot's pin; lets pending deltas donate."""
        with self._cond:
            if self._pins > 0:
                memory_ledger().post(SUBSYS_RESIDENT, 0, kind="release")
            self._pins = max(0, self._pins - 1)
            self._cond.notify_all()

    def stats(self) -> dict:
        with self.lock:
            e = self._entry
            return {
                "enabled": self.enabled,
                "resident": e is not None,
                "bucket": list(e["bucket"]) if e else None,
                "deltaChain": e["chain"] if e else 0,
                "modelVersion": e["version"] if e else None,
                "fullFreezes": int(self._full_freezes.count),
                "deltaApplies": int(self._delta_applies.count),
                "invalidations": int(self._invalidations.count),
                "invalidationReasons": dict(self._invalidation_reasons),
            }

    def warm_scatter(self, pad_r: int, pad_b: int, num_disks: int = 1) -> None:
        """Compile the delta-apply executables for a shape bucket at boot:
        run both kernels (plain scatter and perm+scatter) once over zeroed
        tensors with a floor-sized no-op delta."""
        import jax.numpy as jnp  # local: keep module import light
        from cruise_control_tpu.common.resources import NUM_RESOURCES

        def zeros():
            state = ClusterState(
                leader_load=jnp.zeros((pad_r, NUM_RESOURCES), jnp.float32),
                follower_load=jnp.zeros((pad_r, NUM_RESOURCES), jnp.float32),
                partition=jnp.zeros(pad_r, jnp.int32),
                topic=jnp.zeros(pad_r, jnp.int32),
                pos=jnp.zeros(pad_r, jnp.int32),
                orig_broker=jnp.zeros(pad_r, jnp.int32),
                offline=jnp.zeros(pad_r, bool),
                valid=jnp.zeros(pad_r, bool),
                capacity=jnp.zeros((pad_b, NUM_RESOURCES), jnp.float32),
                host=jnp.zeros(pad_b, jnp.int32),
                rack=jnp.zeros(pad_b, jnp.int32),
                alive=jnp.zeros(pad_b, bool),
                new_broker=jnp.zeros(pad_b, bool),
                broker_valid=jnp.zeros(pad_b, bool),
                disk_capacity=jnp.zeros((pad_b, num_disks), jnp.float32),
                disk_alive=jnp.zeros((pad_b, num_disks), bool),
            )
            placement = Placement(broker=jnp.zeros(pad_r, jnp.int32),
                                  disk=jnp.zeros(pad_r, jnp.int32),
                                  is_leader=jnp.zeros(pad_r, bool))
            return state, placement

        slots = self.delta_slots(1)
        for perm in (None, np.arange(pad_r, dtype=np.int32)):
            st, pl = zeros()
            d = empty_delta()
            d.perm = perm
            st, pl = apply_deltas(st, pl, d, slots, 1)
            st.valid.block_until_ready()
        # The broker-axis-only kernel (liveness flips / capacity edits ride a
        # tiny dedicated scatter, not the replica slot ladder): warm it at
        # the same broker-slot width _apply will use for this bucket.
        b_slots = max(1, min(self.slot_floor, pad_b))
        st, pl = zeros()
        d = empty_delta()
        d.broker_idx = np.zeros(1, dtype=np.int32)
        shapes = {"capacity": (1, NUM_RESOURCES),
                  "disk_capacity": (1, num_disks), "disk_alive": (1, num_disks)}
        d.broker_updates = {
            name: np.zeros(shapes.get(name, (1,)), dtype)
            for name, dtype in BROKER_DELTA_FIELDS}
        st, pl = apply_deltas(st, pl, d, slots, b_slots)
        st.valid.block_until_ready()

    # ----------------------------------------------------------------- private

    def _wait_unpinned(self, timeout: float) -> bool:
        """Wait for pinned solves to drain (donation deletes the buffers they
        are using).  Condition shares :attr:`lock`, so waiting releases it
        and pinned requests can finish and call :meth:`release`."""
        deadline = time.monotonic() + timeout
        while self._pins > 0:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self._cond.wait(timeout=min(remaining, 1.0))
        return True

    def _apply(self, entry: dict, builder: ClusterModel, delta: ClusterDelta,
               ) -> Tuple[ClusterState, Placement, ClusterMeta]:
        slots = self.delta_slots(int(delta.replica_idx.shape[0]))
        b_slots = max(1, min(self.slot_floor,
                             entry["state"].num_brokers_padded))
        with _tracer().span("model.delta_apply", updates=delta.num_updates,
                            structural=delta.perm is not None):
            state, placement = apply_deltas(
                entry["state"], entry["placement"], delta,
                pad_replica_updates_to=slots,
                pad_broker_updates_to=b_slots)
        meta = delta.meta if delta.meta is not None else entry["meta"]
        entry.update(state=state, placement=placement, meta=meta,
                     version=builder.version, chain=entry["chain"] + 1)
        self._delta_applies.inc()
        # Donation: apply_deltas donated (deleted) the old buffers and
        # produced same-shaped replacements — net zero live bytes; the
        # ledger counts the event without moving the subsystem total.
        memory_ledger().post(SUBSYS_RESIDENT, entry.get("nbytes", 0),
                             kind="donate")
        return state, placement, meta

    def _full_freeze(self, builder: ClusterModel, bucket: Tuple[int, int],
                     ) -> Tuple[ClusterState, Placement, ClusterMeta]:
        n_r, n_b = builder.counts()
        if self.enabled and not builder.delta_tracking:
            builder.enable_delta_tracking()
        with _tracer().span("model.freeze", replicas=n_r, brokers=n_b):
            packed, meta = builder.freeze_packed(pad_replicas_to=bucket[0],
                                                 pad_brokers_to=bucket[1])
        with _tracer().span("model.transfer"):
            state, placement = device_put_state(packed)
            state.valid.block_until_ready()
        self._full_freezes.inc()
        if self.enabled:
            nbytes = measure_bytes((state, placement))
            if self._entry is not None:
                # Replacing the pool entry: the old buffers are unreferenced
                # once in-flight pinned solves drain.
                memory_ledger().post(SUBSYS_RESIDENT,
                                     self._entry.get("nbytes", 0),
                                     kind="free")
            memory_ledger().post(SUBSYS_RESIDENT, nbytes, kind="alloc")
            self._entry = dict(builder=builder, bucket=bucket, state=state,
                               placement=placement, meta=meta,
                               version=builder.version, chain=0,
                               nbytes=nbytes)
        return state, placement, meta
