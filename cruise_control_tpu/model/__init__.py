from cruise_control_tpu.model.state import ClusterState, Placement, ClusterMeta
from cruise_control_tpu.model.builder import ClusterModel, Broker, Replica
from cruise_control_tpu.model import ops
from cruise_control_tpu.model.stats import ClusterModelStats, compute_stats
from cruise_control_tpu.model.sanity import sanity_check
