from cruise_control_tpu.model.state import (
    ClusterState, Placement, ClusterMeta, ClusterDelta, apply_deltas)
from cruise_control_tpu.model.builder import (
    ClusterModel, Broker, Replica, builder_from_snapshot)
from cruise_control_tpu.model import ops
from cruise_control_tpu.model.stats import ClusterModelStats, compute_stats
from cruise_control_tpu.model.sanity import sanity_check
