"""CPU estimation models.

Reference: ``model/ModelUtils.java:61-133`` (static-weight model) and
``model/LinearRegressionModelParameters.java`` (trainable linear model).

The static model splits a broker's measured CPU across its partitions in
proportion to weighted byte rates (leader bytes-in 0.7, leader bytes-out 0.15,
follower bytes-in 0.15 by default — MonitorConfig.java:243-261).  The trainable
model fits CPU ~ [leader_bytes_in, leader_bytes_out, follower_bytes_in] by
least squares; here that's one ``jnp.linalg.lstsq`` over the accumulated
training matrix instead of the reference's hand-rolled normal equations.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

CPU_WEIGHT_LEADER_BYTES_IN = 0.7
CPU_WEIGHT_LEADER_BYTES_OUT = 0.15
CPU_WEIGHT_FOLLOWER_BYTES_IN = 0.15


@dataclass
class CpuModelParams:
    leader_bytes_in_weight: float = CPU_WEIGHT_LEADER_BYTES_IN
    leader_bytes_out_weight: float = CPU_WEIGHT_LEADER_BYTES_OUT
    follower_bytes_in_weight: float = CPU_WEIGHT_FOLLOWER_BYTES_IN
    # When fitted, the linear model overrides the static split.
    coefficients: Optional[np.ndarray] = None  # [3]: leader_in, leader_out, follower_in


DEFAULT_PARAMS = CpuModelParams()


def follower_cpu_from_leader_load(bytes_in: float, bytes_out: float, leader_cpu: float,
                                  params: CpuModelParams = DEFAULT_PARAMS) -> float:
    """CPU a replica would use as follower, from its leader-role load
    (reference: ModelUtils.getFollowerCpuUtilFromLeaderLoad :61-78)."""
    if params.coefficients is not None:
        return float(params.coefficients[2] * bytes_in)
    if bytes_in == 0.0 and bytes_out == 0.0:
        return 0.0
    denom = (params.leader_bytes_in_weight * bytes_in
             + params.leader_bytes_out_weight * bytes_out)
    if denom <= 0.0:
        return 0.0
    return leader_cpu * (params.follower_bytes_in_weight * bytes_in) / denom


def follower_cpu_from_leader_load_vec(bytes_in: np.ndarray, bytes_out: np.ndarray,
                                      leader_cpu: np.ndarray,
                                      params: CpuModelParams = DEFAULT_PARAMS) -> np.ndarray:
    """Vectorized form used when packing snapshots."""
    if params.coefficients is not None:
        return params.coefficients[2] * bytes_in
    denom = (params.leader_bytes_in_weight * bytes_in
             + params.leader_bytes_out_weight * bytes_out)
    out = leader_cpu * (params.follower_bytes_in_weight * bytes_in) / np.maximum(denom, 1e-12)
    return np.where((bytes_in == 0.0) & (bytes_out == 0.0), 0.0, out)


ALLOWED_METRIC_ERROR_FACTOR = 1.1
UNSTABLE_METRIC_THROUGHPUT_THRESHOLD = 10.0


def estimate_leader_cpu_util_per_core(broker_cpu_util: float,
                                      broker_leader_bytes_in: float,
                                      broker_leader_bytes_out: float,
                                      broker_follower_bytes_in: float,
                                      partition_bytes_in: float,
                                      partition_bytes_out: float,
                                      params: CpuModelParams = DEFAULT_PARAMS) -> Optional[float]:
    """Split broker CPU to one leader partition (ModelUtils.estimateLeaderCpuUtilPerCore :84-133).

    Returns None when partition rates exceed broker rates beyond metric noise
    (inconsistent sample — caller drops the sample, as the reference does).
    """
    if params.coefficients is not None:
        c = params.coefficients
        return float(c[0] * partition_bytes_in + c[1] * partition_bytes_out)
    if broker_leader_bytes_in == 0 or broker_leader_bytes_out == 0:
        return 0.0
    if (broker_leader_bytes_in * ALLOWED_METRIC_ERROR_FACTOR < partition_bytes_in
            and broker_leader_bytes_in > UNSTABLE_METRIC_THROUGHPUT_THRESHOLD):
        return None
    if (broker_leader_bytes_out * ALLOWED_METRIC_ERROR_FACTOR < partition_bytes_out
            and broker_leader_bytes_out > UNSTABLE_METRIC_THROUGHPUT_THRESHOLD):
        return None
    li = params.leader_bytes_in_weight * broker_leader_bytes_in
    lo = params.leader_bytes_out_weight * broker_leader_bytes_out
    fi = params.follower_bytes_in_weight * broker_follower_bytes_in
    total = li + lo + fi
    if total <= 0:
        return 0.0
    leader_contrib = (li * min(1.0, partition_bytes_in / broker_leader_bytes_in)
                      + lo * min(1.0, partition_bytes_out / broker_leader_bytes_out))
    return (leader_contrib / total) * broker_cpu_util


@dataclass
class LinearRegressionCpuModel:
    """Trainable CPU model (reference: LinearRegressionModelParameters.java:1-376).

    Accumulates (leader_bytes_in, leader_bytes_out, follower_bytes_in, cpu)
    training rows from broker metric samples and fits by least squares.
    """

    min_samples: int = 100
    _rows: list = field(default_factory=list)

    def add_sample(self, leader_bytes_in: float, leader_bytes_out: float,
                   follower_bytes_in: float, cpu_util: float) -> None:
        self._rows.append((leader_bytes_in, leader_bytes_out, follower_bytes_in, cpu_util))

    @property
    def num_samples(self) -> int:
        return len(self._rows)

    def trained(self) -> bool:
        return self.num_samples >= self.min_samples

    def fit(self) -> Optional[np.ndarray]:
        if not self.trained():
            return None
        data = np.asarray(self._rows, dtype=np.float64)
        x, y = data[:, :3], data[:, 3]
        coef, *_ = np.linalg.lstsq(x, y, rcond=None)
        return coef

    def training_completeness(self) -> float:
        return min(1.0, self.num_samples / self.min_samples)
