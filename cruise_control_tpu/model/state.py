"""Frozen structure-of-arrays cluster model.

The reference keeps a mutable object graph (``model/ClusterModel.java:48-1388``:
racks -> hosts -> brokers -> disks -> replicas, each owning a windowed ``Load``)
and goals mutate it replica-by-replica.  Its own ``utilizationMatrix``
(ClusterModel.java:1323-1357) already shows the model collapses to matrices —
here that collapse is the primary representation:

- ``ClusterState``  — immutable per-replica / per-broker tensors (the "what is").
- ``Placement``     — the three mutable arrays the optimizer actually changes:
  replica->broker assignment, replica->disk assignment, and leadership.
- ``ClusterMeta``   — static host-side identity info (names, id maps, sizes);
  never traced.

Every array is padded to a static size so jitted solvers never recompile when
brokers die or replicas appear; ``valid`` / ``broker_valid`` masks gate padding.

Load semantics: the reference stores a replica's *current-role* load and
transfers NW_OUT fully plus a CPU fraction on leadership moves
(``ClusterModel.relocateLeadership`` :402-434).  We instead store both potential
roles per replica (``leader_load`` / ``follower_load``); the effective load is
selected by the leadership mask, which makes leadership transfer a pure mask
flip instead of an in-place load mutation.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES


@flax.struct.dataclass
class Placement:
    """The optimizer-mutable part of the cluster: where replicas sit and who leads.

    Shapes: ``broker``/``disk``/``is_leader`` are [R]; padded entries hold
    broker 0 / disk 0 / False and are masked out by ``ClusterState.valid``.
    """

    broker: jnp.ndarray    # i32[R] dense broker index
    disk: jnp.ndarray      # i32[R] disk index within broker (0 if non-JBOD)
    is_leader: jnp.ndarray  # bool[R]


@flax.struct.dataclass
class ClusterState:
    """Immutable cluster tensors (padded, static-shaped)."""

    # --- replica axis [R] ---
    leader_load: jnp.ndarray    # f32[R, 4] load if this replica leads
    follower_load: jnp.ndarray  # f32[R, 4] load if it follows (NW_OUT=0, reduced CPU)
    partition: jnp.ndarray      # i32[R] dense partition id in [0, P)
    topic: jnp.ndarray          # i32[R] dense topic id in [0, T)
    pos: jnp.ndarray            # i32[R] index in the partition's replica list (0 = preferred leader)
    orig_broker: jnp.ndarray    # i32[R] broker at snapshot time (immigrant tracking)
    offline: jnp.ndarray        # bool[R] replica currently on a dead broker/disk
    valid: jnp.ndarray          # bool[R] padding mask

    # --- broker axis [B] ---
    capacity: jnp.ndarray       # f32[B, 4]; dead brokers get 0 effective capacity via masks
    host: jnp.ndarray           # i32[B] dense host id in [0, H)
    rack: jnp.ndarray           # i32[B] dense rack id in [0, K)
    alive: jnp.ndarray          # bool[B]
    new_broker: jnp.ndarray     # bool[B] recently-added broker (add_broker scenarios)
    broker_valid: jnp.ndarray   # bool[B] padding mask

    # --- disk axis [B, D] (D = max logdirs per broker; 1 when non-JBOD) ---
    disk_capacity: jnp.ndarray  # f32[B, D]
    disk_alive: jnp.ndarray     # bool[B, D]

    @property
    def num_replicas_padded(self) -> int:
        return self.leader_load.shape[0]

    @property
    def num_brokers_padded(self) -> int:
        return self.capacity.shape[0]

    @property
    def num_disks_per_broker(self) -> int:
        return self.disk_capacity.shape[1]


class ClusterMeta:
    """Static, host-side identity info for a snapshot. Never traced.

    Maps dense indices used in ``ClusterState`` back to external identities
    (Kafka broker ids, topic names, rack/host names, topic-partitions).
    """

    def __init__(
        self,
        broker_ids: List[int],
        topics: List[str],
        partitions: List[Tuple[int, int]],   # dense pid -> (dense topic id, partition number)
        racks: List[str],
        hosts: List[str],
        num_replicas: int,
        num_brokers: int,
        extra: Optional[Dict[str, Any]] = None,
    ):
        self.broker_ids = broker_ids          # dense broker idx -> Kafka broker id
        self.topics = topics                  # dense topic idx -> topic name
        self.partitions = partitions          # dense pid -> (topic idx, partition)
        self.racks = racks
        self.hosts = hosts
        self.num_replicas = num_replicas      # true (unpadded) counts
        self.num_brokers = num_brokers
        self.extra = extra or {}
        self.broker_index = {b: i for i, b in enumerate(broker_ids)}
        self.topic_index = {t: i for i, t in enumerate(topics)}
        self.partition_index = {tp: i for i, tp in enumerate(partitions)}

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    @property
    def num_topics(self) -> int:
        return len(self.topics)

    @property
    def num_racks(self) -> int:
        return len(self.racks)

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    def tp_name(self, pid: int) -> str:
        t, p = self.partitions[pid]
        return f"{self.topics[t]}-{p}"


def _pad_to(n: int, multiple: int) -> int:
    if multiple <= 1:
        return max(n, 1)
    return max(((n + multiple - 1) // multiple) * multiple, multiple)


def pack_state_arrays(
    arrays: Dict[str, np.ndarray],
    pad_replicas_to: int = 1,
    pad_brokers_to: int = 1,
) -> Dict[str, np.ndarray]:
    """Host-side half of :func:`make_state`: pad and coerce the unpadded
    per-replica / per-broker numpy arrays to their final device dtypes.

    Split out so the resident-model path can time (and span) the pure host
    packing work separately from the host→device transfer."""
    r = arrays["leader_load"].shape[0]
    b = arrays["capacity"].shape[0]
    rp = _pad_to(r, pad_replicas_to)
    bp = _pad_to(b, pad_brokers_to)

    def padr(x: np.ndarray, fill=0) -> np.ndarray:
        if x.shape[0] == rp:
            return x
        pad = [(0, rp - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, pad, constant_values=fill)

    def padb(x: np.ndarray, fill=0) -> np.ndarray:
        if x.shape[0] == bp:
            return x
        pad = [(0, bp - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, pad, constant_values=fill)

    return dict(
        leader_load=padr(arrays["leader_load"].astype(np.float32)),
        follower_load=padr(arrays["follower_load"].astype(np.float32)),
        partition=padr(arrays["partition"].astype(np.int32)),
        topic=padr(arrays["topic"].astype(np.int32)),
        pos=padr(arrays["pos"].astype(np.int32)),
        orig_broker=padr(arrays["orig_broker"].astype(np.int32)),
        offline=padr(arrays.get("offline", np.zeros(r, dtype=bool)).astype(bool)),
        valid=padr(np.ones(r, dtype=bool), False),
        capacity=padb(arrays["capacity"].astype(np.float32)),
        host=padb(arrays["host"].astype(np.int32)),
        rack=padb(arrays["rack"].astype(np.int32)),
        alive=padb(arrays.get("alive", np.ones(b, dtype=bool)), False),
        new_broker=padb(arrays.get("new_broker", np.zeros(b, dtype=bool)), False),
        broker_valid=padb(np.ones(b, dtype=bool), False),
        disk_capacity=padb(arrays["disk_capacity"].astype(np.float32)),
        disk_alive=padb(arrays["disk_alive"].astype(bool), False),
        assignment=padr(arrays["assignment"].astype(np.int32)),
        disk=padr(arrays.get("disk", np.zeros(r, dtype=np.int32)).astype(np.int32)),
        is_leader=padr(arrays["is_leader"].astype(bool)),
    )


def device_put_state(packed: Dict[str, np.ndarray]) -> Tuple[ClusterState, Placement]:
    """Device half of :func:`make_state`: ship packed host arrays to the
    accelerator as (ClusterState, Placement)."""
    state = ClusterState(
        leader_load=jnp.asarray(packed["leader_load"]),
        follower_load=jnp.asarray(packed["follower_load"]),
        partition=jnp.asarray(packed["partition"]),
        topic=jnp.asarray(packed["topic"]),
        pos=jnp.asarray(packed["pos"]),
        orig_broker=jnp.asarray(packed["orig_broker"]),
        offline=jnp.asarray(packed["offline"]),
        valid=jnp.asarray(packed["valid"]),
        capacity=jnp.asarray(packed["capacity"]),
        host=jnp.asarray(packed["host"]),
        rack=jnp.asarray(packed["rack"]),
        alive=jnp.asarray(packed["alive"]),
        new_broker=jnp.asarray(packed["new_broker"]),
        broker_valid=jnp.asarray(packed["broker_valid"]),
        disk_capacity=jnp.asarray(packed["disk_capacity"]),
        disk_alive=jnp.asarray(packed["disk_alive"]),
    )
    placement = Placement(
        broker=jnp.asarray(packed["assignment"]),
        disk=jnp.asarray(packed["disk"]),
        is_leader=jnp.asarray(packed["is_leader"]),
    )
    return state, placement


def make_state(
    arrays: Dict[str, np.ndarray],
    pad_replicas_to: int = 1,
    pad_brokers_to: int = 1,
) -> Tuple[ClusterState, Placement]:
    """Pack host numpy arrays into (ClusterState, Placement) with padding.

    ``arrays`` holds unpadded per-replica and per-broker arrays keyed by the
    field names of ClusterState/Placement.  Padding multiples let callers keep
    jit caches warm across snapshots of slightly different size (pad replicas
    to e.g. 8192, brokers to 128 → recompiles only on size-class change).
    """
    return device_put_state(
        pack_state_arrays(arrays, pad_replicas_to, pad_brokers_to))


# --------------------------------------------------------------------- deltas

# Replica-axis fields a delta may rewrite, with the per-row shape/dtype each
# update array must carry.  ``broker``/``disk``/``is_leader`` live on
# Placement; everything else on ClusterState.
REPLICA_DELTA_FIELDS: Tuple[Tuple[str, Any, Tuple[int, ...]], ...] = (
    ("leader_load", np.float32, (NUM_RESOURCES,)),
    ("follower_load", np.float32, (NUM_RESOURCES,)),
    ("partition", np.int32, ()),
    ("topic", np.int32, ()),
    ("pos", np.int32, ()),
    ("orig_broker", np.int32, ()),
    ("offline", np.bool_, ()),
    ("valid", np.bool_, ()),
    ("broker", np.int32, ()),
    ("disk", np.int32, ()),
    ("is_leader", np.bool_, ()),
)

BROKER_DELTA_FIELDS: Tuple[Tuple[str, Any], ...] = (
    ("capacity", np.float32),
    ("alive", np.bool_),
    ("new_broker", np.bool_),
    ("disk_capacity", np.float32),
    ("disk_alive", np.bool_),
)


@dataclasses.dataclass
class ClusterDelta:
    """A sparse host-side edit script against a frozen snapshot.

    ``replica_idx``/``broker_idx`` name the rows to rewrite; the update dicts
    carry one array per rewritten field (same dtypes as the frozen tensors).
    ``perm`` (when set) is a full row permutation applied *before* the
    scatter: ``new_row i ← old_row perm[i]`` — it carries surviving rows to
    their new positions after replica creation/deletion shifted the dense
    partition ids; fresh and freed rows are always also in ``replica_idx`` so
    their post-gather content is fully overwritten.  ``meta`` replaces the
    snapshot's ClusterMeta when the partition table changed.
    """

    replica_idx: np.ndarray                  # i32[U]
    replica_updates: Dict[str, np.ndarray]   # REPLICA_DELTA_FIELDS arrays, [U,...]
    broker_idx: np.ndarray                   # i32[V]
    broker_updates: Dict[str, np.ndarray]    # BROKER_DELTA_FIELDS arrays, [V,...]
    perm: Optional[np.ndarray] = None        # i32[R_pad]
    meta: Optional["ClusterMeta"] = None
    from_version: int = 0
    to_version: int = 0

    @property
    def num_updates(self) -> int:
        return int(self.replica_idx.shape[0]) + int(self.broker_idx.shape[0])

    @property
    def is_empty(self) -> bool:
        return self.num_updates == 0 and self.perm is None


def empty_delta(from_version: int = 0, to_version: int = 0) -> ClusterDelta:
    z = np.zeros(0, dtype=np.int32)
    return ClusterDelta(
        replica_idx=z,
        replica_updates={k: np.zeros((0,) + shp, dtype=dt)
                         for k, dt, shp in REPLICA_DELTA_FIELDS},
        broker_idx=z.copy(),
        broker_updates={},
        from_version=from_version, to_version=to_version)


def _scatter_body(state: ClusterState, placement: Placement, r_idx, r_upd,
                  b_idx, b_upd) -> Tuple[ClusterState, Placement]:
    """Shared scatter tail of both delta kernels.  Padding slots carry an
    out-of-range index, so ``mode="drop"`` makes them no-ops — the executable
    shape depends only on the (bucketed) slot counts, never on how many real
    updates a particular delta carries."""
    sr = lambda arr, key: arr.at[r_idx].set(r_upd[key], mode="drop")
    state = state.replace(
        leader_load=sr(state.leader_load, "leader_load"),
        follower_load=sr(state.follower_load, "follower_load"),
        partition=sr(state.partition, "partition"),
        topic=sr(state.topic, "topic"),
        pos=sr(state.pos, "pos"),
        orig_broker=sr(state.orig_broker, "orig_broker"),
        offline=sr(state.offline, "offline"),
        valid=sr(state.valid, "valid"),
    )
    if b_upd:
        sb = lambda arr, key: arr.at[b_idx].set(b_upd[key], mode="drop")
        state = state.replace(
            capacity=sb(state.capacity, "capacity"),
            alive=sb(state.alive, "alive"),
            new_broker=sb(state.new_broker, "new_broker"),
            disk_capacity=sb(state.disk_capacity, "disk_capacity"),
            disk_alive=sb(state.disk_alive, "disk_alive"),
        )
    placement = placement.replace(
        broker=sr(placement.broker, "broker"),
        disk=sr(placement.disk, "disk"),
        is_leader=sr(placement.is_leader, "is_leader"),
    )
    return state, placement


@partial(jax.jit, donate_argnums=(0, 1))
def _apply_delta_scatter(state, placement, r_idx, r_upd, b_idx, b_upd):
    return _scatter_body(state, placement, r_idx, r_upd, b_idx, b_upd)


@partial(jax.jit, donate_argnums=(0,))
def _apply_broker_delta_scatter(state, b_idx, b_upd):
    """Broker-axis-only scatter: liveness flips, capacity edits, logdir
    failures.  These deltas touch none of the replica-axis tensors, so they
    get a dedicated tiny kernel — no replica-slot padding buffers, no
    placement donation, and a shape family keyed only by the broker slot
    bucket instead of riding the replica slot ladder."""
    sb = lambda arr, key: arr.at[b_idx].set(b_upd[key], mode="drop")
    return state.replace(
        capacity=sb(state.capacity, "capacity"),
        alive=sb(state.alive, "alive"),
        new_broker=sb(state.new_broker, "new_broker"),
        disk_capacity=sb(state.disk_capacity, "disk_capacity"),
        disk_alive=sb(state.disk_alive, "disk_alive"),
    )


@partial(jax.jit, donate_argnums=(0, 1))
def _apply_delta_perm_scatter(state, placement, perm, r_idx, r_upd, b_idx,
                              b_upd):
    # Gather surviving rows to their new positions first.  ``perm`` entries
    # for fresh rows are negative: the clip makes the gather well-defined and
    # the subsequent scatter (which always covers fresh rows) overwrites the
    # junk it fetched.
    cl = jnp.clip(perm, 0, state.leader_load.shape[0] - 1)
    g = lambda x: jnp.take(x, cl, axis=0)
    state = state.replace(
        leader_load=g(state.leader_load), follower_load=g(state.follower_load),
        partition=g(state.partition), topic=g(state.topic), pos=g(state.pos),
        orig_broker=g(state.orig_broker), offline=g(state.offline),
        valid=g(state.valid))
    placement = placement.replace(
        broker=g(placement.broker), disk=g(placement.disk),
        is_leader=g(placement.is_leader))
    return _scatter_body(state, placement, r_idx, r_upd, b_idx, b_upd)


def _pad_updates(idx: np.ndarray, upd: Dict[str, np.ndarray], slots: int,
                 sentinel: int) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    n = idx.shape[0]
    slots = max(slots, n, 1)
    out_idx = np.full(slots, sentinel, dtype=np.int32)
    out_idx[:n] = idx
    out = {}
    for k, v in upd.items():
        buf = np.zeros((slots,) + v.shape[1:], dtype=v.dtype)
        buf[:n] = v
        out[k] = jnp.asarray(buf)
    return jnp.asarray(out_idx), out


def apply_deltas(
    state: ClusterState,
    placement: Placement,
    delta: ClusterDelta,
    pad_replica_updates_to: int = 1,
    pad_broker_updates_to: int = 1,
) -> Tuple[ClusterState, Placement]:
    """Scatter-apply a :class:`ClusterDelta` into **donated** device buffers.

    The inputs ``state``/``placement`` are consumed (XLA may reuse their
    memory); callers must drop every reference to them afterwards.  Update
    arrays are padded up to the requested slot counts so repeated applies at
    the same (R_pad, B_pad, slot) bucket hit one compiled executable.
    """
    rp = state.num_replicas_padded
    bp = state.num_brokers_padded
    if (delta.perm is None and delta.replica_idx.shape[0] == 0
            and delta.broker_updates):
        # Broker-only delta (liveness/capacity edits): skip the replica-slot
        # ladder entirely — the placement is untouched and returned as-is.
        b_idx, b_upd = _pad_updates(delta.broker_idx, delta.broker_updates,
                                    pad_broker_updates_to, bp)
        return _apply_broker_delta_scatter(state, b_idx, b_upd), placement
    r_idx, r_upd = _pad_updates(delta.replica_idx, delta.replica_updates,
                                pad_replica_updates_to, rp)
    b_idx, b_upd = _pad_updates(delta.broker_idx, delta.broker_updates,
                                pad_broker_updates_to, bp)
    if delta.perm is not None:
        perm = jnp.asarray(delta.perm.astype(np.int32))
        return _apply_delta_perm_scatter(state, placement, perm, r_idx, r_upd,
                                         b_idx, b_upd)
    return _apply_delta_scatter(state, placement, r_idx, r_upd, b_idx, b_upd)
