"""Frozen structure-of-arrays cluster model.

The reference keeps a mutable object graph (``model/ClusterModel.java:48-1388``:
racks -> hosts -> brokers -> disks -> replicas, each owning a windowed ``Load``)
and goals mutate it replica-by-replica.  Its own ``utilizationMatrix``
(ClusterModel.java:1323-1357) already shows the model collapses to matrices —
here that collapse is the primary representation:

- ``ClusterState``  — immutable per-replica / per-broker tensors (the "what is").
- ``Placement``     — the three mutable arrays the optimizer actually changes:
  replica->broker assignment, replica->disk assignment, and leadership.
- ``ClusterMeta``   — static host-side identity info (names, id maps, sizes);
  never traced.

Every array is padded to a static size so jitted solvers never recompile when
brokers die or replicas appear; ``valid`` / ``broker_valid`` masks gate padding.

Load semantics: the reference stores a replica's *current-role* load and
transfers NW_OUT fully plus a CPU fraction on leadership moves
(``ClusterModel.relocateLeadership`` :402-434).  We instead store both potential
roles per replica (``leader_load`` / ``follower_load``); the effective load is
selected by the leadership mask, which makes leadership transfer a pure mask
flip instead of an in-place load mutation.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import flax.struct
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES


@flax.struct.dataclass
class Placement:
    """The optimizer-mutable part of the cluster: where replicas sit and who leads.

    Shapes: ``broker``/``disk``/``is_leader`` are [R]; padded entries hold
    broker 0 / disk 0 / False and are masked out by ``ClusterState.valid``.
    """

    broker: jnp.ndarray    # i32[R] dense broker index
    disk: jnp.ndarray      # i32[R] disk index within broker (0 if non-JBOD)
    is_leader: jnp.ndarray  # bool[R]


@flax.struct.dataclass
class ClusterState:
    """Immutable cluster tensors (padded, static-shaped)."""

    # --- replica axis [R] ---
    leader_load: jnp.ndarray    # f32[R, 4] load if this replica leads
    follower_load: jnp.ndarray  # f32[R, 4] load if it follows (NW_OUT=0, reduced CPU)
    partition: jnp.ndarray      # i32[R] dense partition id in [0, P)
    topic: jnp.ndarray          # i32[R] dense topic id in [0, T)
    pos: jnp.ndarray            # i32[R] index in the partition's replica list (0 = preferred leader)
    orig_broker: jnp.ndarray    # i32[R] broker at snapshot time (immigrant tracking)
    offline: jnp.ndarray        # bool[R] replica currently on a dead broker/disk
    valid: jnp.ndarray          # bool[R] padding mask

    # --- broker axis [B] ---
    capacity: jnp.ndarray       # f32[B, 4]; dead brokers get 0 effective capacity via masks
    host: jnp.ndarray           # i32[B] dense host id in [0, H)
    rack: jnp.ndarray           # i32[B] dense rack id in [0, K)
    alive: jnp.ndarray          # bool[B]
    new_broker: jnp.ndarray     # bool[B] recently-added broker (add_broker scenarios)
    broker_valid: jnp.ndarray   # bool[B] padding mask

    # --- disk axis [B, D] (D = max logdirs per broker; 1 when non-JBOD) ---
    disk_capacity: jnp.ndarray  # f32[B, D]
    disk_alive: jnp.ndarray     # bool[B, D]

    @property
    def num_replicas_padded(self) -> int:
        return self.leader_load.shape[0]

    @property
    def num_brokers_padded(self) -> int:
        return self.capacity.shape[0]

    @property
    def num_disks_per_broker(self) -> int:
        return self.disk_capacity.shape[1]


class ClusterMeta:
    """Static, host-side identity info for a snapshot. Never traced.

    Maps dense indices used in ``ClusterState`` back to external identities
    (Kafka broker ids, topic names, rack/host names, topic-partitions).
    """

    def __init__(
        self,
        broker_ids: List[int],
        topics: List[str],
        partitions: List[Tuple[int, int]],   # dense pid -> (dense topic id, partition number)
        racks: List[str],
        hosts: List[str],
        num_replicas: int,
        num_brokers: int,
        extra: Optional[Dict[str, Any]] = None,
    ):
        self.broker_ids = broker_ids          # dense broker idx -> Kafka broker id
        self.topics = topics                  # dense topic idx -> topic name
        self.partitions = partitions          # dense pid -> (topic idx, partition)
        self.racks = racks
        self.hosts = hosts
        self.num_replicas = num_replicas      # true (unpadded) counts
        self.num_brokers = num_brokers
        self.extra = extra or {}
        self.broker_index = {b: i for i, b in enumerate(broker_ids)}
        self.topic_index = {t: i for i, t in enumerate(topics)}
        self.partition_index = {tp: i for i, tp in enumerate(partitions)}

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    @property
    def num_topics(self) -> int:
        return len(self.topics)

    @property
    def num_racks(self) -> int:
        return len(self.racks)

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    def tp_name(self, pid: int) -> str:
        t, p = self.partitions[pid]
        return f"{self.topics[t]}-{p}"


def _pad_to(n: int, multiple: int) -> int:
    if multiple <= 1:
        return max(n, 1)
    return max(((n + multiple - 1) // multiple) * multiple, multiple)


def make_state(
    arrays: Dict[str, np.ndarray],
    pad_replicas_to: int = 1,
    pad_brokers_to: int = 1,
) -> Tuple[ClusterState, Placement]:
    """Pack host numpy arrays into (ClusterState, Placement) with padding.

    ``arrays`` holds unpadded per-replica and per-broker arrays keyed by the
    field names of ClusterState/Placement.  Padding multiples let callers keep
    jit caches warm across snapshots of slightly different size (pad replicas
    to e.g. 8192, brokers to 128 → recompiles only on size-class change).
    """
    r = arrays["leader_load"].shape[0]
    b = arrays["capacity"].shape[0]
    rp = _pad_to(r, pad_replicas_to)
    bp = _pad_to(b, pad_brokers_to)

    def padr(x: np.ndarray, fill=0) -> np.ndarray:
        if x.shape[0] == rp:
            return x
        pad = [(0, rp - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, pad, constant_values=fill)

    def padb(x: np.ndarray, fill=0) -> np.ndarray:
        if x.shape[0] == bp:
            return x
        pad = [(0, bp - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, pad, constant_values=fill)

    valid = padr(np.ones(r, dtype=bool), False)
    broker_valid = padb(np.ones(b, dtype=bool), False)

    state = ClusterState(
        leader_load=jnp.asarray(padr(arrays["leader_load"].astype(np.float32))),
        follower_load=jnp.asarray(padr(arrays["follower_load"].astype(np.float32))),
        partition=jnp.asarray(padr(arrays["partition"].astype(np.int32))),
        topic=jnp.asarray(padr(arrays["topic"].astype(np.int32))),
        pos=jnp.asarray(padr(arrays["pos"].astype(np.int32))),
        orig_broker=jnp.asarray(padr(arrays["orig_broker"].astype(np.int32))),
        offline=jnp.asarray(padr(arrays.get("offline", np.zeros(r, dtype=bool)).astype(bool))),
        valid=jnp.asarray(valid),
        capacity=jnp.asarray(padb(arrays["capacity"].astype(np.float32))),
        host=jnp.asarray(padb(arrays["host"].astype(np.int32))),
        rack=jnp.asarray(padb(arrays["rack"].astype(np.int32))),
        alive=jnp.asarray(padb(arrays.get("alive", np.ones(b, dtype=bool)), False)),
        new_broker=jnp.asarray(padb(arrays.get("new_broker", np.zeros(b, dtype=bool)), False)),
        broker_valid=jnp.asarray(broker_valid),
        disk_capacity=jnp.asarray(padb(arrays["disk_capacity"].astype(np.float32))),
        disk_alive=jnp.asarray(padb(arrays["disk_alive"].astype(bool), False)),
    )
    placement = Placement(
        broker=jnp.asarray(padr(arrays["assignment"].astype(np.int32))),
        disk=jnp.asarray(padr(arrays.get("disk", np.zeros(r, dtype=np.int32)).astype(np.int32))),
        is_leader=jnp.asarray(padr(arrays["is_leader"].astype(bool))),
    )
    return state, placement
