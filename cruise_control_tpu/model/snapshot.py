"""Cluster snapshot serialization.

The host↔solver boundary format (SURVEY.md §5: {replica loads f32[R,4],
assignment i32[R], leader mask, rack ids, capacities, masks}).  Two codecs:

- JSON — human-readable, used by the ``tpucc propose`` CLI and tests; schema
  mirrors what the reference's ``load`` endpoint emits (brokers + partitions
  with per-resource loads).
- NPZ  — zero-copy numpy bundle for large snapshots (1M replicas packs in
  ~100 MB and loads in milliseconds).
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.model.builder import ClusterModel
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement, make_state

_RES_KEYS = ("cpu", "networkInbound", "networkOutbound", "disk")


def model_to_json_dict(cm: ClusterModel) -> Dict:
    brokers = []
    for b in cm.brokers():
        brokers.append({
            "brokerId": b.broker_id,
            "rack": b.rack,
            "host": b.host,
            "alive": b.alive,
            "newBroker": b.new_broker,
            "capacity": {k: float(b.capacity[i]) for i, k in enumerate(_RES_KEYS)},
            "diskCapacities": [float(x) for x in b.disk_capacities],
            "diskAlive": [bool(x) for x in b.disk_alive],
        })
    partitions = []
    for (topic, part), replicas in cm.partitions().items():
        partitions.append({
            "topic": topic,
            "partition": part,
            "replicas": [{
                "brokerId": r.broker_id,
                "isLeader": r.is_leader,
                "disk": r.disk,
                "load": {k: float(r.leader_load[i]) for i, k in enumerate(_RES_KEYS)},
                "followerLoad": (None if r.follower_load is None else
                                 {k: float(r.follower_load[i])
                                  for i, k in enumerate(_RES_KEYS)}),
            } for r in replicas],
        })
    return {"version": 1, "brokers": brokers, "partitions": partitions}


def model_from_json_dict(doc: Dict) -> ClusterModel:
    cm = ClusterModel()
    for b in doc["brokers"]:
        cap = {Resource.from_name(k): v for k, v in b["capacity"].items()}
        disks = b.get("diskCapacities")
        cm.create_broker(rack=b["rack"], host=b.get("host", f"h{b['brokerId']}"),
                         broker_id=b["brokerId"], capacity=cap,
                         disk_capacities=disks if disks and len(disks) > 1 else None,
                         new_broker=b.get("newBroker", False))
    for p in doc["partitions"]:
        for i, r in enumerate(p["replicas"]):
            cm.create_replica(p["topic"], p["partition"], broker_id=r["brokerId"],
                              index=i, is_leader=r["isLeader"], disk=r.get("disk", 0))
            load = [r["load"][k] for k in _RES_KEYS]
            fl = r.get("followerLoad")
            cm.set_replica_load(p["topic"], p["partition"], r["brokerId"], load,
                                follower_load=None if fl is None
                                else [fl[k] for k in _RES_KEYS])
    # Dead brokers: applied after replicas exist so offline flags propagate.
    for b in doc["brokers"]:
        if not b.get("alive", True):
            cm.set_broker_state(b["brokerId"], alive=False)
        for d, ok in enumerate(b.get("diskAlive", [])):
            if not ok:
                cm.mark_disk_dead(b["brokerId"], d)
    return cm


def save_json(cm: ClusterModel, path: str) -> None:
    with open(path, "w") as f:
        json.dump(model_to_json_dict(cm), f)


def load_json(path: str) -> ClusterModel:
    with open(path) as f:
        return model_from_json_dict(json.load(f))


# ------------------------------------------------------------------ NPZ codec


def save_npz(path: str, state: ClusterState, placement: Placement,
             meta: ClusterMeta) -> None:
    np.savez_compressed(
        path,
        leader_load=np.asarray(state.leader_load),
        follower_load=np.asarray(state.follower_load),
        partition=np.asarray(state.partition),
        topic=np.asarray(state.topic),
        pos=np.asarray(state.pos),
        orig_broker=np.asarray(state.orig_broker),
        offline=np.asarray(state.offline),
        valid=np.asarray(state.valid),
        capacity=np.asarray(state.capacity),
        host=np.asarray(state.host),
        rack=np.asarray(state.rack),
        alive=np.asarray(state.alive),
        new_broker=np.asarray(state.new_broker),
        broker_valid=np.asarray(state.broker_valid),
        disk_capacity=np.asarray(state.disk_capacity),
        disk_alive=np.asarray(state.disk_alive),
        assignment=np.asarray(placement.broker),
        disk=np.asarray(placement.disk),
        is_leader=np.asarray(placement.is_leader),
        meta_broker_ids=np.asarray(meta.broker_ids),
        meta_topics=np.asarray(meta.topics),
        meta_partitions=np.asarray(meta.partitions),
        meta_racks=np.asarray(meta.racks),
        meta_hosts=np.asarray(meta.hosts),
        meta_counts=np.asarray([meta.num_replicas, meta.num_brokers]),
    )


def load_npz(path: str) -> Tuple[ClusterState, Placement, ClusterMeta]:
    z = np.load(path, allow_pickle=False)
    n_r, n_b = (int(x) for x in z["meta_counts"])
    arrays = {k: z[k][:n_r] if z[k].shape[:1] == z["valid"].shape else z[k]
              for k in ("leader_load", "follower_load", "partition", "topic", "pos",
                        "orig_broker", "offline", "assignment", "disk", "is_leader")}
    for k in ("capacity", "host", "rack", "alive", "new_broker",
              "disk_capacity", "disk_alive"):
        arrays[k] = z[k][:n_b]
    # Trim replica-axis arrays to the true count (they were saved padded).
    for k in ("leader_load", "follower_load", "partition", "topic", "pos",
              "orig_broker", "offline", "assignment", "disk", "is_leader"):
        arrays[k] = np.asarray(arrays[k])[:n_r]
    state, placement = make_state(arrays)
    mp = z["meta_partitions"]
    meta = ClusterMeta(
        broker_ids=[int(x) for x in z["meta_broker_ids"]],
        topics=[str(x) for x in z["meta_topics"]],
        partitions=[(int(a), int(b)) for a, b in mp],
        racks=[str(x) for x in z["meta_racks"]],
        hosts=[str(x) for x in z["meta_hosts"]],
        num_replicas=n_r, num_brokers=n_b,
    )
    return state, placement, meta
