"""Vectorized ClusterModelStats (reference: model/ClusterModelStats.java:29-496).

Per-resource avg/max/min/stdev over alive brokers, balanced-broker counts
against the balance band, and replica/leader/topic-replica count statistics.
These feed goal comparators (is the model better after optimization?) and the
REST responses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.resources import Resource, NUM_RESOURCES
from cruise_control_tpu.model import ops
from cruise_control_tpu.model.state import ClusterState, Placement


@dataclass
class ClusterModelStats:
    """Host-side summary; produced by compute_stats()."""

    avg_util: np.ndarray       # f32[4] mean broker utilization (absolute)
    max_util: np.ndarray       # f32[4]
    min_util: np.ndarray       # f32[4]
    std_util: np.ndarray       # f32[4]
    num_balanced_brokers: np.ndarray  # i32[4] brokers inside the balance band
    avg_replicas: float
    max_replicas: int
    min_replicas: int
    std_replicas: float
    num_brokers: int
    num_replicas: int
    num_leaders: int
    num_unbalanced_brokers: np.ndarray  # i32[4]

    def cv(self) -> np.ndarray:
        """Coefficient of variation per resource — scale-free balance measure."""
        return self.std_util / np.maximum(self.avg_util, 1e-9)

    def to_dict(self) -> Dict:
        return {
            "statistics": {
                "AVG": {r.resource: float(self.avg_util[r]) for r in Resource}
                | {"replicas": self.avg_replicas},
                "MAX": {r.resource: float(self.max_util[r]) for r in Resource}
                | {"replicas": self.max_replicas},
                "MIN": {r.resource: float(self.min_util[r]) for r in Resource}
                | {"replicas": self.min_replicas},
                "STD": {r.resource: float(self.std_util[r]) for r in Resource}
                | {"replicas": self.std_replicas},
            },
            "numBalancedBrokers": {r.resource: int(self.num_balanced_brokers[r]) for r in Resource},
            "numBrokers": self.num_brokers,
            "numReplicas": self.num_replicas,
            "numLeaders": self.num_leaders,
        }


def _stats_arrays(state: ClusterState, placement: Placement, balance_threshold: jnp.ndarray):
    load = ops.broker_load(state, placement)          # [B,4]
    alive = state.alive & state.broker_valid          # [B]
    n = jnp.maximum(jnp.sum(alive), 1)

    masked = jnp.where(alive[:, None], load, 0.0)
    avg = jnp.sum(masked, axis=0) / n
    mx = jnp.max(jnp.where(alive[:, None], load, -jnp.inf), axis=0)
    mn = jnp.min(jnp.where(alive[:, None], load, jnp.inf), axis=0)
    var = jnp.sum(jnp.where(alive[:, None], (load - avg) ** 2, 0.0), axis=0) / n
    std = jnp.sqrt(var)

    # Balance band per reference ResourceDistributionGoal.initGoalState :236-263:
    # [avg * (2 - T), avg * T], computed on utilization percentages; equivalently
    # compare absolute load against avg_util_fraction * capacity bounds.
    avg_frac = ops.average_alive_utilization(state, placement)      # [4]
    upper = avg_frac[None, :] * balance_threshold[None, :] * state.capacity
    lower = avg_frac[None, :] * (2.0 - balance_threshold[None, :]) * state.capacity
    in_band = (load <= upper) & (load >= lower)
    balanced = jnp.sum(in_band & alive[:, None], axis=0)

    rc = ops.replica_counts(state, placement)
    rc_alive = jnp.where(alive, rc, 0)
    avg_rc = jnp.sum(rc_alive) / n
    mx_rc = jnp.max(jnp.where(alive, rc, -1))
    mn_rc = jnp.min(jnp.where(alive, rc, jnp.iinfo(jnp.int32).max))
    std_rc = jnp.sqrt(jnp.sum(jnp.where(alive, (rc - avg_rc) ** 2, 0.0)) / n)

    num_leaders = jnp.sum((state.valid & placement.is_leader).astype(jnp.int32))
    num_replicas = jnp.sum(state.valid.astype(jnp.int32))
    return avg, mx, mn, std, balanced, avg_rc, mx_rc, mn_rc, std_rc, n, num_replicas, num_leaders


_stats_jit = jax.jit(_stats_arrays)


def compute_stats(state: ClusterState, placement: Placement,
                  balance_threshold: np.ndarray | None = None) -> ClusterModelStats:
    if balance_threshold is None:
        balance_threshold = np.full(NUM_RESOURCES, 1.1, dtype=np.float32)
    (avg, mx, mn, std, balanced, avg_rc, mx_rc, mn_rc, std_rc, n,
     num_replicas, num_leaders) = jax.device_get(
        _stats_jit(state, placement, jnp.asarray(balance_threshold, dtype=jnp.float32)))
    return ClusterModelStats(
        avg_util=np.asarray(avg), max_util=np.asarray(mx), min_util=np.asarray(mn),
        std_util=np.asarray(std), num_balanced_brokers=np.asarray(balanced),
        avg_replicas=float(avg_rc), max_replicas=int(mx_rc), min_replicas=int(mn_rc),
        std_replicas=float(std_rc), num_brokers=int(n),
        num_replicas=int(num_replicas), num_leaders=int(num_leaders),
        num_unbalanced_brokers=np.asarray(n - balanced, dtype=np.int64),
    )
