"""Vectorized model invariants (reference: ClusterModel.sanityCheck :1137-1287).

The reference walks the object tree asserting load sums are consistent
replica -> broker -> host -> rack -> cluster; with segment-sum aggregation that
consistency holds by construction, so the checks that remain meaningful are the
structural ones.  Used after every solve and heavily in tests.
"""

from __future__ import annotations

from typing import List

import numpy as np

from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement


def sanity_check(state: ClusterState, placement: Placement, meta: ClusterMeta,
                 allow_offline: bool = False) -> List[str]:
    """Return a list of violated-invariant descriptions (empty == healthy)."""
    problems: List[str] = []
    valid = np.asarray(state.valid)
    bvalid = np.asarray(state.broker_valid)
    alive = np.asarray(state.alive)
    broker = np.asarray(placement.broker)
    disk = np.asarray(placement.disk)
    is_leader = np.asarray(placement.is_leader)
    partition = np.asarray(state.partition)

    r = valid.sum()
    if r != meta.num_replicas:
        problems.append(f"valid replica count {r} != meta.num_replicas {meta.num_replicas}")
    if bvalid.sum() != meta.num_brokers:
        problems.append(f"valid broker count {bvalid.sum()} != meta.num_brokers {meta.num_brokers}")

    # Replicas sit on valid brokers.
    vb = broker[valid]
    if vb.size and (vb.min() < 0 or vb.max() >= len(bvalid) or not bvalid[vb].all()):
        problems.append("replica assigned to invalid broker index")
        return problems

    # Exactly one leader per partition.
    leaders_per_p = np.bincount(partition[valid & is_leader], minlength=meta.num_partitions)
    missing = np.where(leaders_per_p == 0)[0]
    multi = np.where(leaders_per_p > 1)[0]
    if missing.size:
        problems.append(f"{missing.size} partitions without a leader, e.g. {meta.tp_name(int(missing[0]))}")
    if multi.size:
        problems.append(f"{multi.size} partitions with multiple leaders, e.g. {meta.tp_name(int(multi[0]))}")

    # No two replicas of one partition on the same broker.
    pb = partition[valid].astype(np.int64) * len(bvalid) + broker[valid]
    uniq, counts = np.unique(pb, return_counts=True)
    if (counts > 1).any():
        pid = int(uniq[counts > 1][0] // len(bvalid))
        problems.append(f"partition {meta.tp_name(pid)} has >1 replica on one broker")

    # Replicas on dead brokers / dead disks must be flagged offline.
    if not allow_offline:
        dead_broker = ~alive[np.clip(broker, 0, len(alive) - 1)]
        disk_alive = np.asarray(state.disk_alive)
        dead_disk = ~disk_alive[np.clip(broker, 0, len(alive) - 1),
                                np.clip(disk, 0, state.num_disks_per_broker - 1)]
        bad = valid & (dead_broker | dead_disk)
        if bad.any():
            problems.append(f"{bad.sum()} replicas placed on dead brokers/disks")

    # Disk index bounds.
    if valid.any() and (disk[valid].min() < 0 or disk[valid].max() >= state.num_disks_per_broker):
        problems.append("replica disk index out of range")

    # Loads must be non-negative and finite.
    ll = np.asarray(state.leader_load)[valid]
    fl = np.asarray(state.follower_load)[valid]
    if not (np.isfinite(ll).all() and np.isfinite(fl).all()):
        problems.append("non-finite replica load")
    elif (ll < -1e-6).any() or (fl < -1e-6).any():
        problems.append("negative replica load")

    return problems
