"""Random cluster generator (vectorized).

Port of the reference's parameterized random model generator
``cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/model/
RandomCluster.java`` (:55 generate, :104-121 populate) with the property set
from ``common/TestConstants.java`` (BASE_PROPERTIES: 10 racks / 40 brokers /
50001 replicas / 3000 topics / RF 3, resource means, UNIFORM / LINEAR /
EXPONENTIAL distributions).  Unlike the reference's per-replica object
construction, everything here is numpy so BASELINE configs #4-#5
(2.6K brokers / 1M replicas) generate in seconds.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.model import cpu_model
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement, make_state

TYPICAL_CPU_CAPACITY = 100.0
LARGE_BROKER_CAPACITY = 300_000.0
MEDIUM_BROKER_CAPACITY = 200_000.0


class Distribution(enum.Enum):
    UNIFORM = "uniform"
    LINEAR = "linear"
    EXPONENTIAL = "exponential"


@dataclass
class ClusterProperties:
    """Reference: TestConstants.BASE_PROPERTIES."""

    num_racks: int = 10
    num_brokers: int = 40
    num_dead_brokers: int = 0
    num_brokers_with_bad_disk: int = 0
    num_replicas: int = 50_001
    num_topics: int = 3_000
    min_replication: int = 3
    max_replication: int = 3
    mean_cpu: float = 0.01       # utilization fraction of capacity
    mean_disk: float = 100.0
    mean_nw_in: float = 100.0
    mean_nw_out: float = 100.0
    num_disks: int = 1
    distribution: Distribution = Distribution.UNIFORM
    seed: int = 3140             # TestConstants.SEED_BASE
    # ---- fuzzsvc extensions (defaults reproduce the reference layout) ----
    # 0.0 = reference round-robin racks; > 0 skews broker counts across
    # racks exponentially (rack 0 largest), so rack-aware goals face
    # heterogeneous domains instead of perfectly even ones.
    rack_skew: float = 0.0
    # 1 = homogeneous capacity; k > 1 assigns brokers round-robin to k
    # capacity tiers spanning 0.5x..1.5x of the reference capacity.
    capacity_tiers: int = 1
    # Explicit fault sets for deterministic scenario replay.  When given
    # they take precedence over the sampled num_dead_brokers /
    # num_brokers_with_bad_disk counts; dead_disk_ids works at any
    # num_disks (the sampled path needs num_disks > 1).
    dead_broker_ids: Optional[Tuple[int, ...]] = None
    dead_disk_ids: Optional[Tuple[Tuple[int, int], ...]] = None


def _apportion(weights: np.ndarray, total: int, min_each: int = 0) -> np.ndarray:
    """Integer counts summing to ``total``, proportional to ``weights``
    (largest-remainder), each at least ``min_each`` when feasible."""
    n = weights.shape[0]
    min_each = min(min_each, total // n) if n else 0
    spread = total - min_each * n
    share = weights / weights.sum() * spread
    counts = np.floor(share).astype(np.int64)
    remainder = spread - int(counts.sum())
    if remainder > 0:
        order = np.argsort(-(share - counts), kind="stable")
        counts[order[:remainder]] += 1
    return counts + min_each


def _sample(rng: np.random.Generator, dist: Distribution, mean: float,
            n: int) -> np.ndarray:
    if dist is Distribution.UNIFORM:
        return rng.uniform(0.0, 2.0 * mean, n)
    if dist is Distribution.LINEAR:
        # Triangular ramp: density increasing linearly with value.
        return 2.0 * mean * np.sqrt(rng.uniform(0.0, 1.0, n))
    return rng.exponential(mean, n)


def generate(props: Optional[ClusterProperties] = None,
             pad_replicas_to: int = 1, pad_brokers_to: int = 1,
             ) -> Tuple[ClusterState, Placement, ClusterMeta]:
    """Build a random (state, placement, meta) snapshot."""
    p = props or ClusterProperties()
    rng = np.random.default_rng(p.seed)

    # ---- topics / partitions: popularity-weighted partition counts.
    rf = rng.integers(p.min_replication, p.max_replication + 1, p.num_topics)
    popularity = rng.exponential(1.0, p.num_topics) + 1e-3
    weights = popularity / popularity.sum()
    # partitions per topic so that sum(partitions * rf) ≈ num_replicas.
    target = np.maximum((weights * p.num_replicas / rf).astype(np.int64), 1)
    num_partitions_per_topic = target
    pid_topic = np.repeat(np.arange(p.num_topics), num_partitions_per_topic)
    num_partitions = pid_topic.shape[0]
    part_rf = rf[pid_topic]                              # [P]
    r_n = int(part_rf.sum())

    # ---- replica rows: partition / topic / pos.
    part_of_replica = np.repeat(np.arange(num_partitions), part_rf)
    offsets = np.concatenate([[0], np.cumsum(part_rf)])[:-1]
    pos = np.arange(r_n) - offsets[part_of_replica]
    topic_of_replica = pid_topic[part_of_replica]

    # ---- placement: RF distinct brokers per partition (re-roll collisions).
    max_rf = int(part_rf.max())
    picks = rng.integers(0, p.num_brokers, (num_partitions, max_rf))
    for _ in range(64):
        dup = np.zeros((num_partitions, max_rf), dtype=bool)
        for j in range(1, max_rf):
            dup[:, j] = (picks[:, :j] == picks[:, j:j + 1]).any(axis=1)
        n_dup = int(dup.sum())
        if n_dup == 0:
            break
        picks[dup] = rng.integers(0, p.num_brokers, n_dup)
    slot = pos  # replica's column in picks
    assignment = picks[part_of_replica, slot]
    is_leader = pos == 0

    # ---- loads.
    cpu_cap = TYPICAL_CPU_CAPACITY
    leader_load = np.zeros((r_n, NUM_RESOURCES))
    leader_load[:, Resource.CPU] = _sample(rng, p.distribution,
                                           p.mean_cpu * cpu_cap, r_n)
    leader_load[:, Resource.DISK] = _sample(rng, p.distribution, p.mean_disk, r_n)
    leader_load[:, Resource.NW_IN] = _sample(rng, p.distribution, p.mean_nw_in, r_n)
    leader_load[:, Resource.NW_OUT] = _sample(rng, p.distribution, p.mean_nw_out, r_n)
    # Per-partition identical disk/NW_IN across replicas (same data replicated).
    first_row = offsets[part_of_replica]
    for res in (Resource.DISK, Resource.NW_IN, Resource.NW_OUT, Resource.CPU):
        leader_load[:, res] = leader_load[first_row, res]

    follower_load = leader_load.copy()
    follower_load[:, Resource.NW_OUT] = 0.0
    follower_load[:, Resource.CPU] = cpu_model.follower_cpu_from_leader_load_vec(
        leader_load[:, Resource.NW_IN], leader_load[:, Resource.NW_OUT],
        leader_load[:, Resource.CPU])

    # ---- brokers: racks (round-robin, or skewed per rack_skew), one host
    # per broker, capacity homogeneous or tiered per capacity_tiers.
    capacity = np.tile(np.array([
        TYPICAL_CPU_CAPACITY, LARGE_BROKER_CAPACITY,
        MEDIUM_BROKER_CAPACITY, LARGE_BROKER_CAPACITY]), (p.num_brokers, 1))
    if p.rack_skew > 0.0:
        w = np.exp(-p.rack_skew * np.arange(p.num_racks)
                   / max(p.num_racks - 1, 1))
        counts = _apportion(w, p.num_brokers, min_each=1)
        rack = np.repeat(np.arange(p.num_racks), counts)
    else:
        rack = np.arange(p.num_brokers) % p.num_racks
    tier_mult = np.ones(p.num_brokers)
    if p.capacity_tiers > 1:
        tier = np.arange(p.num_brokers) % p.capacity_tiers
        tier_mult = 0.5 + tier / (p.capacity_tiers - 1)
        capacity = capacity * tier_mult[:, None]
    host = np.arange(p.num_brokers)
    alive = np.ones(p.num_brokers, dtype=bool)
    if p.dead_broker_ids is not None:
        alive[list(p.dead_broker_ids)] = False
    elif p.num_dead_brokers > 0:
        dead = rng.choice(p.num_brokers, p.num_dead_brokers, replace=False)
        alive[dead] = False

    d_n = max(p.num_disks, 1)
    disk_capacity = (np.full((p.num_brokers, d_n), LARGE_BROKER_CAPACITY / d_n)
                     * tier_mult[:, None])
    disk_alive = np.ones((p.num_brokers, d_n), dtype=bool)
    if p.dead_disk_ids is not None:
        for b, d in p.dead_disk_ids:
            disk_alive[int(b), int(d)] = False
    elif p.num_brokers_with_bad_disk > 0 and d_n > 1:
        bad = rng.choice(np.nonzero(alive)[0],
                         min(p.num_brokers_with_bad_disk, int(alive.sum())),
                         replace=False)
        disk_alive[bad, 0] = False
    disk = (rng.integers(0, d_n, r_n) if d_n > 1
            else np.zeros(r_n, dtype=np.int64))

    offline = ~alive[assignment] | ~disk_alive[assignment, disk]

    state, placement = make_state(
        dict(leader_load=leader_load, follower_load=follower_load,
             partition=part_of_replica, topic=topic_of_replica, pos=pos,
             orig_broker=assignment, offline=offline, assignment=assignment,
             disk=disk, is_leader=is_leader, capacity=capacity, host=host,
             rack=rack, alive=alive,
             new_broker=np.zeros(p.num_brokers, dtype=bool),
             disk_capacity=disk_capacity, disk_alive=disk_alive),
        pad_replicas_to=pad_replicas_to, pad_brokers_to=pad_brokers_to,
    )
    first_of_topic = np.searchsorted(pid_topic, np.arange(p.num_topics), side="left")
    pnum = np.arange(num_partitions) - first_of_topic[pid_topic]
    meta = ClusterMeta(
        broker_ids=list(range(p.num_brokers)),
        topics=[f"topic{t}" for t in range(p.num_topics)],
        partitions=list(zip(pid_topic.tolist(), pnum.tolist())),
        racks=[str(k) for k in range(p.num_racks)],
        hosts=[f"h{i}" for i in range(p.num_brokers)],
        num_replicas=r_n, num_brokers=p.num_brokers,
    )
    return state, placement, meta
