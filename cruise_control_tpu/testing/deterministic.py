"""Deterministic test clusters.

Port of the reference fixture generator
``cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/common/
DeterministicCluster.java`` (and the constants it pulls from
``TestConstants.java:40-135``).  These hand-built models drive the analyzer
parity tests (reference: ``analyzer/DeterministicClusterTest.java``) and are
BASELINE config #1.

Loads are given as (cpu, nw_in, nw_out, disk) per the reference's
``getAggregatedMetricValues`` argument order.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.builder import ClusterModel

TYPICAL_CPU_CAPACITY = 100.0
LARGE_BROKER_CAPACITY = 300_000.0
MEDIUM_BROKER_CAPACITY = 200_000.0
SMALL_BROKER_CAPACITY = 10.0

BROKER_CAPACITY = {
    Resource.CPU: TYPICAL_CPU_CAPACITY,
    Resource.NW_IN: LARGE_BROKER_CAPACITY,
    Resource.NW_OUT: MEDIUM_BROKER_CAPACITY,
    Resource.DISK: LARGE_BROKER_CAPACITY,
}
# Two logdirs per broker, half the disk capacity each (TestConstants.DISK_CAPACITY).
JBOD_DISK_CAPACITIES = [LARGE_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2]

# Broker id -> rack id maps (DeterministicCluster.RACK_BY_BROKER{,2,3}).
RACK_BY_BROKER = {0: 0, 1: 0, 2: 1}
RACK_BY_BROKER2 = {0: 0, 1: 1, 2: 1}
RACK_BY_BROKER3 = {0: 0, 1: 1, 2: 1, 3: 1}

T1, T2 = "T1", "T2"


def load(cpu: float, nw_in: float, nw_out: float, disk: float) -> np.ndarray:
    return np.array([cpu, nw_in, nw_out, disk], dtype=np.float64)


def homogeneous_cluster(rack_by_broker: Dict[int, int],
                        capacity: Optional[Dict[Resource, float]] = None,
                        jbod: bool = False) -> ClusterModel:
    """DeterministicCluster.getHomogeneousCluster: one host per broker."""
    capacity = capacity or BROKER_CAPACITY
    cm = ClusterModel()
    for broker_id, rack in sorted(rack_by_broker.items()):
        cm.create_broker(rack=str(rack), host=f"h{broker_id}", broker_id=broker_id,
                         capacity=dict(capacity),
                         disk_capacities=JBOD_DISK_CAPACITIES if jbod else None)
    return cm


def unbalanced() -> ClusterModel:
    """Two racks, three brokers, two partitions (1 replica each), all on broker 0."""
    cm = homogeneous_cluster(RACK_BY_BROKER)
    half = load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic in (T1, T2):
        cm.create_replica(topic, 0, broker_id=0, index=0, is_leader=True)
        cm.set_replica_load(topic, 0, 0, half)
    return cm


def unbalanced2() -> ClusterModel:
    """unbalanced() + four more 1-replica partitions (broker 1 gets one, broker 0 three)."""
    cm = unbalanced()
    half = load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic, part, broker in ((T1, 1, 1), (T2, 1, 0), (T1, 2, 0), (T2, 2, 0)):
        cm.create_replica(topic, part, broker_id=broker, index=0, is_leader=True)
        cm.set_replica_load(topic, part, broker, half)
    return cm


def unbalanced3() -> ClusterModel:
    """Two racks, three brokers, two partitions × two replicas; leaders at index 1."""
    cm = homogeneous_cluster(RACK_BY_BROKER)
    half = load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic in (T1, T2):
        cm.create_replica(topic, 0, broker_id=1, index=0, is_leader=False)
        cm.create_replica(topic, 0, broker_id=0, index=1, is_leader=True)
        cm.set_replica_load(topic, 0, 0, half)
        cm.set_replica_load(topic, 0, 1, half)
    return cm


def unbalanced_with_a_follower() -> ClusterModel:
    """unbalanced() + a follower of T1-0 on broker 2."""
    cm = unbalanced()
    cm.create_replica(T1, 0, broker_id=2, index=1, is_leader=False)
    cm.set_replica_load(T1, 0, 2, load(TYPICAL_CPU_CAPACITY / 8, LARGE_BROKER_CAPACITY / 2,
                                       0.0, LARGE_BROKER_CAPACITY / 2))
    return cm


def _create_unbalanced(topics, num_partitions: int) -> ClusterModel:
    """DeterministicCluster.createUnbalanced: 2 brokers / 2 racks / 2 disks each."""
    cm = homogeneous_cluster({0: 0, 1: 1}, jbod=True)
    for topic in topics:
        for i in range(num_partitions):
            broker_id = 1 if i > 3 else 0
            logdir = 0 if i % 4 < 2 else 1
            cm.create_replica(topic, i, broker_id=broker_id, index=0, is_leader=True,
                              disk=logdir)
            cm.set_replica_load(topic, i, broker_id, load(
                TYPICAL_CPU_CAPACITY / 5 + TYPICAL_CPU_CAPACITY / 50 * (i / 2.0 - 1.5),
                LARGE_BROKER_CAPACITY / 5 + LARGE_BROKER_CAPACITY / 50 * (i / 2.0 - 1.5),
                MEDIUM_BROKER_CAPACITY / 5 + MEDIUM_BROKER_CAPACITY / 50 * (i / 2.0 - 1.5),
                LARGE_BROKER_CAPACITY / 5 + LARGE_BROKER_CAPACITY / 50 * (i / 2.0 - 1.5)))
    return cm


def unbalanced4() -> ClusterModel:
    """Two JBOD brokers on two racks; one topic × 8 single-replica partitions."""
    return _create_unbalanced((T1,), 8)


def unbalanced5() -> ClusterModel:
    """unbalanced4 shape with two topics × 14 partitions."""
    return _create_unbalanced((T1, T2), 14)


def swap_only_balanceable() -> ClusterModel:
    """Two brokers where NO single replica move can stay inside the NW_IN
    balance band — the hot broker's lightest replica still overshoots the cold
    broker's upper bound — but one swap balances both exactly.

    b0 holds NW_IN loads {10, 8} (util 18/20), b1 holds {4, 2} (util 6/20);
    avg util 0.6, band [10.8, 13.2].  Moving 8 → b1 gives 14 > 13.2 (reject);
    swapping 10 ↔ 4 gives 12 / 12 (in band).  Exercises the solver's swap
    phase (reference mechanism: ResourceDistributionGoal.java:543-725).
    """
    capacity = {Resource.CPU: TYPICAL_CPU_CAPACITY, Resource.NW_IN: 20.0,
                Resource.NW_OUT: MEDIUM_BROKER_CAPACITY,
                Resource.DISK: LARGE_BROKER_CAPACITY}
    cm = homogeneous_cluster({0: 0, 1: 1}, capacity=capacity)
    nw_in = {(T1, 0): (0, 10.0), (T1, 1): (0, 8.0),
             (T2, 0): (1, 4.0), (T2, 1): (1, 2.0)}
    for (topic, part), (broker, value) in nw_in.items():
        cm.create_replica(topic, part, broker_id=broker, index=0, is_leader=True)
        cm.set_replica_load(topic, part, broker, load(1.0, value, 0.0, 1.0))
    return cm


def rack_aware_satisfiable() -> ClusterModel:
    """Two racks, three brokers, one partition × 2 replicas on brokers 0,1 (same rack)."""
    cm = homogeneous_cluster(RACK_BY_BROKER)
    cm.create_replica(T1, 0, broker_id=0, index=0, is_leader=True)
    cm.create_replica(T1, 0, broker_id=1, index=1, is_leader=False)
    cm.set_replica_load(T1, 0, 0, load(40.0, 100.0, 130.0, 75.0))
    cm.set_replica_load(T1, 0, 1, load(5.0, 100.0, 0.0, 75.0))
    return cm


def rack_aware_satisfiable2() -> ClusterModel:
    """Replicas on brokers 0,2 with RACK_BY_BROKER2 (already rack-aware)."""
    cm = homogeneous_cluster(RACK_BY_BROKER2)
    cm.create_replica(T1, 0, broker_id=0, index=0, is_leader=True)
    cm.create_replica(T1, 0, broker_id=2, index=1, is_leader=False)
    cm.set_replica_load(T1, 0, 0, load(40.0, 100.0, 130.0, 75.0))
    cm.set_replica_load(T1, 0, 2, load(5.0, 100.0, 0.0, 75.0))
    return cm


def rack_aware_unsatisfiable() -> ClusterModel:
    """rack_aware_satisfiable + a third replica: 3 replicas, only 2 racks."""
    cm = rack_aware_satisfiable()
    cm.create_replica(T1, 0, broker_id=2, index=2, is_leader=False)
    cm.set_replica_load(T1, 0, 2, load(60.0, 100.0, 130.0, 75.0))
    return cm


# ---------------------------------------------------------------- deck models
# (DeterministicCluster.smallClusterModel / mediumClusterModel — the models
# DeterministicClusterTest.java:137-199 sweeps across balance percentages,
# capacity thresholds and broker capacities.)

TOPIC_A, TOPIC_B, TOPIC_C, TOPIC_D = "A", "B", "C", "D"
# TestConstants.TOPIC_MUST_HAVE_LEADER_REPLICAS_ON_BROKERS
TOPIC_L = "must_have_leader_replica_on_broker_topic"
TOPIC0, TOPIC1 = "topic0", "topic1"

# TestConstants.java:36-42 sweep values.
ZERO_BALANCE_PERCENTAGE = 1.00
LOW_BALANCE_PERCENTAGE = 1.05
MEDIUM_BALANCE_PERCENTAGE = 1.25
HIGH_BALANCE_PERCENTAGE = 1.65
HIGH_CAPACITY_THRESHOLD = 0.9
MEDIUM_CAPACITY_THRESHOLD = 0.8
LOW_CAPACITY_THRESHOLD = 0.7


def small_cluster_model(capacity: Optional[Dict[Resource, float]] = None) -> ClusterModel:
    """DeterministicCluster.smallClusterModel:678-714 — 3 brokers / 2 racks,
    5 partitions x RF2 over topics T1, T2."""
    cm = homogeneous_cluster(RACK_BY_BROKER, capacity=capacity)
    deck = [
        # (topic, partition, leader broker, leader load, follower broker, follower load)
        (T1, 0, 0, (20.0, 100.0, 130.0, 75.0), 2, (5.0, 100.0, 0.0, 75.0)),
        (T1, 1, 1, (15.0, 90.0, 110.0, 55.0), 0, (4.5, 90.0, 0.0, 55.0)),
        (T2, 0, 1, (5.0, 5.0, 6.0, 5.0), 2, (4.0, 5.0, 0.0, 5.0)),
        (T2, 1, 0, (25.0, 25.0, 45.0, 55.0), 2, (10.5, 25.0, 0.0, 55.0)),
        (T2, 2, 0, (20.0, 45.0, 120.0, 95.0), 1, (8.0, 45.0, 0.0, 95.0)),
    ]
    for topic, part, lb, lload, fb, fload in deck:
        cm.create_replica(topic, part, broker_id=lb, index=0, is_leader=True)
        cm.create_replica(topic, part, broker_id=fb, index=1, is_leader=False)
        cm.set_replica_load(topic, part, lb, load(*lload))
        cm.set_replica_load(topic, part, fb, load(*fload))
    return cm


def medium_cluster_model(capacity: Optional[Dict[Resource, float]] = None) -> ClusterModel:
    """DeterministicCluster.mediumClusterModel:799-842 — 3 brokers / 2 racks,
    6 partitions x RF2 over topics A, B, C, D."""
    cm = homogeneous_cluster(RACK_BY_BROKER, capacity=capacity)
    deck = [
        (TOPIC_A, 0, 1, (5.0, 4.0, 10.0, 10.0), 0, (5.0, 5.0, 0.0, 4.0)),
        (TOPIC_A, 1, 0, (5.0, 3.0, 10.0, 8.0), 2, (3.0, 4.0, 0.0, 6.0)),
        (TOPIC_A, 2, 0, (5.0, 2.0, 10.0, 6.0), 2, (4.0, 5.0, 0.0, 3.0)),
        (TOPIC_B, 0, 1, (5.0, 4.0, 10.0, 7.0), 2, (2.0, 2.0, 0.0, 5.0)),
        (TOPIC_C, 0, 2, (1.0, 8.0, 10.0, 4.0), 1, (5.0, 6.0, 0.0, 4.0)),
        (TOPIC_D, 0, 1, (5.0, 5.0, 10.0, 6.0), 2, (2.0, 8.0, 0.0, 7.0)),
    ]
    for topic, part, lb, lload, fb, fload in deck:
        cm.create_replica(topic, part, broker_id=lb, index=0, is_leader=True)
        cm.create_replica(topic, part, broker_id=fb, index=1, is_leader=False)
        cm.set_replica_load(topic, part, lb, load(*lload))
        cm.set_replica_load(topic, part, fb, load(*fload))
    return cm


# ------------------------------------------------- min-topic-leaders fixtures
# (DeterministicCluster.minLeaderReplicaPerBroker*:300-545; the goal must fix
# them with leadership moves where possible and replica moves where not.)

_HALF = (TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
         MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)


def _leader_topic_cluster(assignments) -> ClusterModel:
    """assignments: iterable of (topic, partition, [(broker, is_leader), ...])."""
    cm = homogeneous_cluster(RACK_BY_BROKER2)
    for topic, part, replicas in assignments:
        for idx, (broker, is_leader) in enumerate(replicas):
            cm.create_replica(topic, part, broker_id=broker, index=idx,
                              is_leader=is_leader)
            cm.set_replica_load(topic, part, broker, load(*_HALF))
    return cm


def min_leader_satisfiable() -> ClusterModel:
    """B0: P0_l, P1_l; B1: P2_l, P0_f; B2: P2_f, P1_f (:347-380)."""
    return _leader_topic_cluster([
        (TOPIC_L, 0, [(0, True), (1, False)]),
        (TOPIC_L, 1, [(0, True), (2, False)]),
        (TOPIC_L, 2, [(1, True), (2, False)]),
    ])


def min_leader_satisfiable2() -> ClusterModel:
    """B0 leads everything; B1/B2 hold followers (:392-430)."""
    return _leader_topic_cluster([
        (TOPIC_L, 0, [(0, True), (2, False)]),
        (TOPIC_L, 1, [(0, True), (1, False)]),
        (TOPIC_L, 2, [(0, True), (2, False)]),
    ])


def min_leader_satisfiable3() -> ClusterModel:
    """Four brokers (B0 EMPTY), 16 partitions x RF2; min 4 leaders/broker
    forces replica MOVES onto B0 — promotions alone cannot reach it
    (:496-545)."""
    cm = ClusterModel()
    for broker_id, rack in sorted(RACK_BY_BROKER3.items()):
        cm.create_broker(rack=str(rack), host=f"h{broker_id}", broker_id=broker_id,
                         capacity=dict(BROKER_CAPACITY))
    placement = {i: (1, 3) for i in range(4)}        # leader B1, follower B3
    placement.update({i: (2, 1) for i in range(4, 10)})   # leader B2, follower B1
    placement.update({i: (3, 2) for i in range(10, 16)})  # leader B3, follower B2
    for part, (lb, fb) in placement.items():
        cm.create_replica(TOPIC_L, part, broker_id=lb, index=0, is_leader=True)
        cm.create_replica(TOPIC_L, part, broker_id=fb, index=1, is_leader=False)
        cm.set_replica_load(TOPIC_L, part, lb, load(*_HALF))
        cm.set_replica_load(TOPIC_L, part, fb, load(*_HALF))
    return cm


def min_leader_satisfiable4() -> ClusterModel:
    """Two topics x 3 partitions, all leaders on B0, all followers on B1,
    B2 empty (:439-492) — needs both promotions and replica moves."""
    return _leader_topic_cluster([
        (topic, part, [(0, True), (1, False)])
        for topic in (TOPIC0, TOPIC1) for part in range(3)
    ])


def min_leader_unsatisfiable() -> ClusterModel:
    """Two leader replicas, three brokers: pigeonhole failure (:314-334,
    DeterministicClusterTest.java:229-232 expects OptimizationFailureException)."""
    return _leader_topic_cluster([
        (TOPIC_L, 0, [(0, True), (2, False)]),
        (TOPIC_L, 1, [(0, True), (1, False)]),
    ])
