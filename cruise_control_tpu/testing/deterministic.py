"""Deterministic test clusters.

Port of the reference fixture generator
``cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/common/
DeterministicCluster.java`` (and the constants it pulls from
``TestConstants.java:40-135``).  These hand-built models drive the analyzer
parity tests (reference: ``analyzer/DeterministicClusterTest.java``) and are
BASELINE config #1.

Loads are given as (cpu, nw_in, nw_out, disk) per the reference's
``getAggregatedMetricValues`` argument order.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.builder import ClusterModel

TYPICAL_CPU_CAPACITY = 100.0
LARGE_BROKER_CAPACITY = 300_000.0
MEDIUM_BROKER_CAPACITY = 200_000.0
SMALL_BROKER_CAPACITY = 10.0

BROKER_CAPACITY = {
    Resource.CPU: TYPICAL_CPU_CAPACITY,
    Resource.NW_IN: LARGE_BROKER_CAPACITY,
    Resource.NW_OUT: MEDIUM_BROKER_CAPACITY,
    Resource.DISK: LARGE_BROKER_CAPACITY,
}
# Two logdirs per broker, half the disk capacity each (TestConstants.DISK_CAPACITY).
JBOD_DISK_CAPACITIES = [LARGE_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2]

# Broker id -> rack id maps (DeterministicCluster.RACK_BY_BROKER{,2,3}).
RACK_BY_BROKER = {0: 0, 1: 0, 2: 1}
RACK_BY_BROKER2 = {0: 0, 1: 1, 2: 1}
RACK_BY_BROKER3 = {0: 0, 1: 1, 2: 1, 3: 1}

T1, T2 = "T1", "T2"


def load(cpu: float, nw_in: float, nw_out: float, disk: float) -> np.ndarray:
    return np.array([cpu, nw_in, nw_out, disk], dtype=np.float64)


def homogeneous_cluster(rack_by_broker: Dict[int, int],
                        capacity: Optional[Dict[Resource, float]] = None,
                        jbod: bool = False) -> ClusterModel:
    """DeterministicCluster.getHomogeneousCluster: one host per broker."""
    capacity = capacity or BROKER_CAPACITY
    cm = ClusterModel()
    for broker_id, rack in sorted(rack_by_broker.items()):
        cm.create_broker(rack=str(rack), host=f"h{broker_id}", broker_id=broker_id,
                         capacity=dict(capacity),
                         disk_capacities=JBOD_DISK_CAPACITIES if jbod else None)
    return cm


def unbalanced() -> ClusterModel:
    """Two racks, three brokers, two partitions (1 replica each), all on broker 0."""
    cm = homogeneous_cluster(RACK_BY_BROKER)
    half = load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic in (T1, T2):
        cm.create_replica(topic, 0, broker_id=0, index=0, is_leader=True)
        cm.set_replica_load(topic, 0, 0, half)
    return cm


def unbalanced2() -> ClusterModel:
    """unbalanced() + four more 1-replica partitions (broker 1 gets one, broker 0 three)."""
    cm = unbalanced()
    half = load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic, part, broker in ((T1, 1, 1), (T2, 1, 0), (T1, 2, 0), (T2, 2, 0)):
        cm.create_replica(topic, part, broker_id=broker, index=0, is_leader=True)
        cm.set_replica_load(topic, part, broker, half)
    return cm


def unbalanced3() -> ClusterModel:
    """Two racks, three brokers, two partitions × two replicas; leaders at index 1."""
    cm = homogeneous_cluster(RACK_BY_BROKER)
    half = load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic in (T1, T2):
        cm.create_replica(topic, 0, broker_id=1, index=0, is_leader=False)
        cm.create_replica(topic, 0, broker_id=0, index=1, is_leader=True)
        cm.set_replica_load(topic, 0, 0, half)
        cm.set_replica_load(topic, 0, 1, half)
    return cm


def unbalanced_with_a_follower() -> ClusterModel:
    """unbalanced() + a follower of T1-0 on broker 2."""
    cm = unbalanced()
    cm.create_replica(T1, 0, broker_id=2, index=1, is_leader=False)
    cm.set_replica_load(T1, 0, 2, load(TYPICAL_CPU_CAPACITY / 8, LARGE_BROKER_CAPACITY / 2,
                                       0.0, LARGE_BROKER_CAPACITY / 2))
    return cm


def _create_unbalanced(topics, num_partitions: int) -> ClusterModel:
    """DeterministicCluster.createUnbalanced: 2 brokers / 2 racks / 2 disks each."""
    cm = homogeneous_cluster({0: 0, 1: 1}, jbod=True)
    for topic in topics:
        for i in range(num_partitions):
            broker_id = 1 if i > 3 else 0
            logdir = 0 if i % 4 < 2 else 1
            cm.create_replica(topic, i, broker_id=broker_id, index=0, is_leader=True,
                              disk=logdir)
            cm.set_replica_load(topic, i, broker_id, load(
                TYPICAL_CPU_CAPACITY / 5 + TYPICAL_CPU_CAPACITY / 50 * (i / 2.0 - 1.5),
                LARGE_BROKER_CAPACITY / 5 + LARGE_BROKER_CAPACITY / 50 * (i / 2.0 - 1.5),
                MEDIUM_BROKER_CAPACITY / 5 + MEDIUM_BROKER_CAPACITY / 50 * (i / 2.0 - 1.5),
                LARGE_BROKER_CAPACITY / 5 + LARGE_BROKER_CAPACITY / 50 * (i / 2.0 - 1.5)))
    return cm


def unbalanced4() -> ClusterModel:
    """Two JBOD brokers on two racks; one topic × 8 single-replica partitions."""
    return _create_unbalanced((T1,), 8)


def unbalanced5() -> ClusterModel:
    """unbalanced4 shape with two topics × 14 partitions."""
    return _create_unbalanced((T1, T2), 14)


def swap_only_balanceable() -> ClusterModel:
    """Two brokers where NO single replica move can stay inside the NW_IN
    balance band — the hot broker's lightest replica still overshoots the cold
    broker's upper bound — but one swap balances both exactly.

    b0 holds NW_IN loads {10, 8} (util 18/20), b1 holds {4, 2} (util 6/20);
    avg util 0.6, band [10.8, 13.2].  Moving 8 → b1 gives 14 > 13.2 (reject);
    swapping 10 ↔ 4 gives 12 / 12 (in band).  Exercises the solver's swap
    phase (reference mechanism: ResourceDistributionGoal.java:543-725).
    """
    capacity = {Resource.CPU: TYPICAL_CPU_CAPACITY, Resource.NW_IN: 20.0,
                Resource.NW_OUT: MEDIUM_BROKER_CAPACITY,
                Resource.DISK: LARGE_BROKER_CAPACITY}
    cm = homogeneous_cluster({0: 0, 1: 1}, capacity=capacity)
    nw_in = {(T1, 0): (0, 10.0), (T1, 1): (0, 8.0),
             (T2, 0): (1, 4.0), (T2, 1): (1, 2.0)}
    for (topic, part), (broker, value) in nw_in.items():
        cm.create_replica(topic, part, broker_id=broker, index=0, is_leader=True)
        cm.set_replica_load(topic, part, broker, load(1.0, value, 0.0, 1.0))
    return cm


def rack_aware_satisfiable() -> ClusterModel:
    """Two racks, three brokers, one partition × 2 replicas on brokers 0,1 (same rack)."""
    cm = homogeneous_cluster(RACK_BY_BROKER)
    cm.create_replica(T1, 0, broker_id=0, index=0, is_leader=True)
    cm.create_replica(T1, 0, broker_id=1, index=1, is_leader=False)
    cm.set_replica_load(T1, 0, 0, load(40.0, 100.0, 130.0, 75.0))
    cm.set_replica_load(T1, 0, 1, load(5.0, 100.0, 0.0, 75.0))
    return cm


def rack_aware_satisfiable2() -> ClusterModel:
    """Replicas on brokers 0,2 with RACK_BY_BROKER2 (already rack-aware)."""
    cm = homogeneous_cluster(RACK_BY_BROKER2)
    cm.create_replica(T1, 0, broker_id=0, index=0, is_leader=True)
    cm.create_replica(T1, 0, broker_id=2, index=1, is_leader=False)
    cm.set_replica_load(T1, 0, 0, load(40.0, 100.0, 130.0, 75.0))
    cm.set_replica_load(T1, 0, 2, load(5.0, 100.0, 0.0, 75.0))
    return cm


def rack_aware_unsatisfiable() -> ClusterModel:
    """rack_aware_satisfiable + a third replica: 3 replicas, only 2 racks."""
    cm = rack_aware_satisfiable()
    cm.create_replica(T1, 0, broker_id=2, index=2, is_leader=False)
    cm.set_replica_load(T1, 0, 2, load(60.0, 100.0, 130.0, 75.0))
    return cm
