"""Optimization verifier — the cross-implementation parity oracle.

Port of ``cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/
analyzer/OptimizationVerifier.java`` (:1-345): run a goal list by priority
over a model, then verify postconditions.  The reference's Verification enums
map to the checks here:

- GOAL_VIOLATION  → every hard goal satisfied; soft goals did not regress.
- NEW_BROKERS     → (add-broker runs) original brokers keep only original replicas.
- DEAD_BROKERS    → no replica remains on a dead broker / dead disk.
- REGRESSION      → per-goal stats comparator says "not worse" (AbstractGoal:108-117).
- Load invariants → broker loads equal the segment-sums of replica loads
                    (the ClusterModel.sanityCheck analog, vectorized).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.context import (
    build_context,
    compute_aggregates,
    currently_offline,
)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, OptimizerResult
from cruise_control_tpu.analyzer.options import OptimizationOptions
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement


@dataclass
class VerificationFailure(AssertionError):
    check: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.check}] {self.detail}"


@dataclass
class VerifyReport:
    result: OptimizerResult
    failures: List[VerificationFailure] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.failures


def verify_placement(
    state: ClusterState,
    placement: Placement,
    meta: ClusterMeta,
    final: Placement,
    goal_names: Sequence[str] = (),
    constraint: Optional[BalancingConstraint] = None,
    options: Optional[OptimizationOptions] = None,
    verifications: Sequence[str] = ("GOAL_VIOLATION", "DEAD_BROKERS", "REGRESSION"),
    goal_infos: Sequence = (),
) -> List[VerificationFailure]:
    """Postcondition checks over an arbitrary ``final`` placement.

    The standalone oracle behind :func:`execute_goals_for`: callers that
    already hold a solved (or deliberately broken) placement — the fuzz
    harness, what-if lanes, failure-path tests — verify it directly without
    re-running the optimizer.  Every violated check is reported (the list
    accumulates; nothing short-circuits), so a multi-way breakage names all
    of its causes at once.  ``goal_infos`` feeds the REGRESSION comparator
    and may be empty when no per-goal stats exist.
    """
    constraint = constraint or BalancingConstraint()
    options = options or OptimizationOptions()
    failures: List[VerificationFailure] = []
    gctx = build_context(state, placement, meta, constraint, options)
    agg = compute_aggregates(gctx, final)

    if "GOAL_VIOLATION" in verifications:
        from cruise_control_tpu.analyzer.goals.registry import goal_by_name
        for name in goal_names:
            goal = goal_by_name(name)
            if goal.is_hard:
                n = int(np.sum(np.asarray(goal.violated_brokers(gctx, final, agg))))
                if n:
                    failures.append(VerificationFailure(
                        "GOAL_VIOLATION", f"hard goal {name} violated on {n} brokers"))

    if "DEAD_BROKERS" in verifications:
        stranded = int(np.sum(np.asarray(currently_offline(gctx, final))))
        if stranded:
            failures.append(VerificationFailure(
                "DEAD_BROKERS", f"{stranded} replicas still on dead brokers/disks"))

    if "REGRESSION" in verifications:
        for info in goal_infos:
            if info.rounds > 0 and info.metric_after > info.metric_before * (1 + 1e-5):
                failures.append(VerificationFailure(
                    "REGRESSION",
                    f"{info.goal_name} metric worsened "
                    f"{info.metric_before:.6g} -> {info.metric_after:.6g}"))

    if "NEW_BROKERS" in verifications and bool(np.asarray(state.new_broker).any()):
        # Replicas may only move TO new brokers; old brokers keep originals.
        # Vacuous without new brokers (OptimizationVerifier.java:188 gates on
        # !clusterModel.newBrokers().isEmpty()).
        new_broker = np.asarray(state.new_broker)
        moved = (np.asarray(final.broker) != np.asarray(state.orig_broker))
        moved &= np.asarray(state.valid)
        bad = moved & ~new_broker[np.asarray(final.broker)]
        offline = np.asarray(currently_offline(gctx, placement))
        bad &= ~offline  # offline replicas may go anywhere alive
        n_bad = int(bad.sum())
        if n_bad:
            failures.append(VerificationFailure(
                "NEW_BROKERS", f"{n_bad} healthy replicas moved to non-new brokers"))

    # Load-consistency invariant (ClusterModel.sanityCheck analog): the jax
    # segment-sum per-broker loads must match an independent numpy recompute
    # from the final placement — catches drift in the solver's incremental
    # scatter updates and in the aggregation kernels.
    from cruise_control_tpu.model import ops
    bl = np.asarray(ops.broker_load(state, final))
    eff = np.where(np.asarray(final.is_leader)[:, None],
                   np.asarray(state.leader_load), np.asarray(state.follower_load))
    eff = eff * np.asarray(state.valid)[:, None]
    expect = np.zeros_like(bl)
    np.add.at(expect, np.asarray(final.broker), eff)
    if not np.allclose(bl, expect, rtol=1e-4, atol=1e-3):
        failures.append(VerificationFailure(
            "LOAD_CONSISTENCY", "per-broker loads != numpy recompute from placement"))

    return failures


def execute_goals_for(
    state: ClusterState,
    placement: Placement,
    meta: ClusterMeta,
    goal_names: Sequence[str],
    constraint: Optional[BalancingConstraint] = None,
    options: Optional[OptimizationOptions] = None,
    verifications: Sequence[str] = ("GOAL_VIOLATION", "DEAD_BROKERS", "REGRESSION"),
) -> VerifyReport:
    """Run goals and verify (reference: OptimizationVerifier.executeGoalsFor)."""
    constraint = constraint or BalancingConstraint()
    options = options or OptimizationOptions()
    optimizer = GoalOptimizer(constraint=constraint, goal_names=list(goal_names))
    result = optimizer.optimizations(state, placement, meta, options=options)
    report = VerifyReport(result=result)
    report.failures.extend(verify_placement(
        state, placement, meta, result.final_placement,
        goal_names=goal_names, constraint=constraint, options=options,
        verifications=verifications, goal_infos=result.goal_infos))
    return report
