"""Fake GSS ticket validator for SPNEGO tests.

Stands in for a deployment's GSSAPI-backed validator behind
``webserver.auth.spnego.validator.class`` (see
``servlet/security.SpnegoSecurityProvider``).  Accepts tokens of the form
``b"principal:<name>"`` and returns ``<name>``; everything else raises.
"""

from __future__ import annotations


class FakeGssValidator:
    def __call__(self, token: bytes):
        if token.startswith(b"principal:"):
            return token[len(b"principal:"):].decode("utf-8")
        raise ValueError("bad ticket")
