"""Versioned binary wire format for raw metrics.

Reference: ``cruise-control-metrics-reporter/.../metric/MetricSerde.java`` +
``BrokerMetric/TopicMetric/PartitionMetric.toBuffer`` — each record is
[version u8][wire-type u8][time i64][broker i32][scope payload][value f64],
where the scope payload is empty for broker metrics, a length-prefixed UTF-8
topic for topic metrics, and topic + partition i32 for partition metrics.
Readers accept any version ≤ theirs (UnknownVersionException otherwise) and
skip type ids newer than their inventory — the rolling-upgrade contract the
reference encodes per-type via ``supportedVersionSince``.
"""

from __future__ import annotations

import struct
from typing import Optional

from cruise_control_tpu.common.exceptions import CruiseControlError
from cruise_control_tpu.monitor.samples import (
    CruiseControlMetric,
    RawMetricScope,
    RawMetricType,
    raw_type_by_id,
)

METRIC_VERSION = 5

_HEAD = struct.Struct(">BBqi")      # version, type id, time_ms, broker_id
_F64 = struct.Struct(">d")
_I32 = struct.Struct(">i")
_U16 = struct.Struct(">H")


class UnknownVersionError(CruiseControlError):
    pass


def serialize_metric(m: CruiseControlMetric) -> bytes:
    out = bytearray(_HEAD.pack(METRIC_VERSION, m.raw_type.wire_id,
                               int(m.time_ms), m.broker_id))
    scope = m.raw_type.scope
    if scope is not RawMetricScope.BROKER:
        topic = (m.topic or "").encode("utf-8")
        out += _U16.pack(len(topic))
        out += topic
        if scope is RawMetricScope.PARTITION:
            out += _I32.pack(m.partition if m.partition is not None else -1)
    out += _F64.pack(m.value)
    return bytes(out)


def deserialize_metric(buf: bytes) -> Optional[CruiseControlMetric]:
    """None when the record's type id is newer than this reader's inventory
    (forward-compatible skip); raises on a newer VERSION byte."""
    version, wire_id, time_ms, broker_id = _HEAD.unpack_from(buf, 0)
    if version > METRIC_VERSION:
        raise UnknownVersionError(
            f"metric version {version} > supported {METRIC_VERSION}")
    try:
        raw_type = raw_type_by_id(wire_id)
    except KeyError:
        return None
    off = _HEAD.size
    topic = None
    partition = None
    if raw_type.scope is not RawMetricScope.BROKER:
        (tlen,) = _U16.unpack_from(buf, off)
        off += _U16.size
        topic = buf[off:off + tlen].decode("utf-8")
        off += tlen
        if raw_type.scope is RawMetricScope.PARTITION:
            (partition,) = _I32.unpack_from(buf, off)
            off += _I32.size
    (value,) = _F64.unpack_from(buf, off)
    return CruiseControlMetric(raw_type=raw_type, time_ms=float(time_ms),
                               broker_id=broker_id, topic=topic,
                               partition=partition, value=value)
