"""Broker-side metrics-reporter agent.

Reference: ``CruiseControlMetricsReporter.java:61-392`` — a per-broker agent
that snapshots the broker's metric registry every reporting interval,
converts it to typed raw metrics (``YammerMetricProcessor``/
``MetricsUtils``), serializes them and publishes to the metrics topic.  Here
the registry is a ``BrokerMetricsSource`` SPI (a real deployment adapts its
metrics system; the demo source derives a full 63-type payload from the
in-process fake cluster), and publishing goes through the ``Transport`` SPI
partitioned by broker id.
"""

from __future__ import annotations

import threading
from typing import Iterable, List, Protocol

from cruise_control_tpu.monitor.samples import (
    CruiseControlMetric,
    RawMetricScope,
    RawMetricType,
)
from cruise_control_tpu.reporter.serde import serialize_metric
from cruise_control_tpu.reporter.transport import Transport


class BrokerMetricsSource(Protocol):
    """Adapts a broker's local metric registry to typed raw metrics."""

    def collect(self, broker_id: int, time_ms: float) -> Iterable[CruiseControlMetric]: ...


class MetricsReporter:
    """One broker's reporting loop (start()/stop(); report_once() for tests
    and for in-process demo clusters driven by the task runner's clock)."""

    def __init__(self, broker_id: int, source: BrokerMetricsSource,
                 transport: Transport, reporting_interval_ms: float = 60_000.0,
                 clock=None):
        import time as _time
        self.broker_id = broker_id
        self.source = source
        self.transport = transport
        self.interval_ms = reporting_interval_ms
        self._clock = clock or (lambda: _time.time() * 1000.0)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.records_reported = 0

    def report_once(self, time_ms: float | None = None) -> int:
        now = self._clock() if time_ms is None else time_ms
        n = 0
        for metric in self.source.collect(self.broker_id, now):
            self.transport.append(self.broker_id % self.transport.num_partitions,
                                  serialize_metric(metric))
            n += 1
        self.records_reported += n
        return n

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_ms / 1000.0):
                self.report_once()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"metrics-reporter-{self.broker_id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class DemoBrokerMetricsSource:
    """Derives the full 63-type payload from the in-process fake cluster
    (plays the role of YammerMetricProcessor over a real broker registry)."""

    def __init__(self, metadata_backend, mean_bytes_in: float | None = None,
                 mean_bytes_out: float | None = None,
                 mean_size: float | None = None,
                 cpu_per_leader: float | None = None, seed: int | None = None):
        from cruise_control_tpu.monitor import sampler as _s
        self.backend = metadata_backend
        self.mean_bytes_in = _s.DEMO_MEAN_BYTES_IN if mean_bytes_in is None else mean_bytes_in
        self.mean_bytes_out = _s.DEMO_MEAN_BYTES_OUT if mean_bytes_out is None else mean_bytes_out
        self.mean_size = _s.DEMO_MEAN_SIZE if mean_size is None else mean_size
        self.cpu_per_leader = _s.DEMO_CPU_PER_LEADER if cpu_per_leader is None else cpu_per_leader
        self.seed = _s.DEMO_SEED if seed is None else seed

    def collect(self, broker_id: int, time_ms: float) -> List[CruiseControlMetric]:
        from cruise_control_tpu.monitor.sampler import synthetic_jitter
        meta = self.backend.fetch()
        out: List[CruiseControlMetric] = []

        def emit(t, value, topic=None, partition=None):
            out.append(CruiseControlMetric(raw_type=t, time_ms=time_ms,
                                           broker_id=broker_id, topic=topic,
                                           partition=partition, value=value))

        led = [p for p in meta.partitions if p.leader == broker_id]
        followed = [p for p in meta.partitions
                    if broker_id in p.replicas and p.leader != broker_id]
        by_topic = {}
        for p in led:
            by_topic.setdefault(p.topic, []).append(p)

        def jitter(key):
            return synthetic_jitter(key, self.seed)

        total_in = total_out = 0.0
        for topic, parts in by_topic.items():
            t_in = sum(self.mean_bytes_in * jitter((t.topic, t.partition))
                       for t in parts)
            t_out = sum(self.mean_bytes_out * jitter((t.topic, t.partition))
                        for t in parts)
            total_in += t_in
            total_out += t_out
            emit(RawMetricType.TOPIC_BYTES_IN, t_in, topic=topic)
            emit(RawMetricType.TOPIC_BYTES_OUT, t_out, topic=topic)
            emit(RawMetricType.TOPIC_REPLICATION_BYTES_IN, t_in * 0.5, topic=topic)
            emit(RawMetricType.TOPIC_REPLICATION_BYTES_OUT, t_out * 0.5, topic=topic)
            emit(RawMetricType.TOPIC_PRODUCE_REQUEST_RATE, len(parts) * 5.0, topic=topic)
            emit(RawMetricType.TOPIC_FETCH_REQUEST_RATE, len(parts) * 8.0, topic=topic)
            emit(RawMetricType.TOPIC_MESSAGES_IN_PER_SEC, t_in / 100.0, topic=topic)

        for p in led + followed:
            emit(RawMetricType.PARTITION_SIZE,
                 self.mean_size * jitter((p.topic, p.partition)),
                 topic=p.topic, partition=p.partition)

        repl_in = self.mean_bytes_in * len(followed)
        emit(RawMetricType.ALL_TOPIC_BYTES_IN, total_in)
        emit(RawMetricType.ALL_TOPIC_BYTES_OUT, total_out)
        emit(RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN, repl_in)
        emit(RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT, repl_in)
        emit(RawMetricType.BROKER_CPU_UTIL, self.cpu_per_leader * max(len(led), 1))
        emit(RawMetricType.ALL_TOPIC_PRODUCE_REQUEST_RATE, len(led) * 5.0)
        emit(RawMetricType.ALL_TOPIC_FETCH_REQUEST_RATE, len(led) * 8.0)
        emit(RawMetricType.ALL_TOPIC_MESSAGES_IN_PER_SEC, total_in / 100.0)

        # The remaining broker-health gauges: emit every type in the
        # inventory so the wire carries the reporter's complete schema.
        emitted = {m.raw_type for m in out}
        for t in RawMetricType:
            if t in emitted or t.scope is not RawMetricScope.BROKER:
                continue
            base = 10.0 if "QUEUE" in t.name else 1.0
            emit(t, base * jitter((t.name,)))
        return out
