from cruise_control_tpu.reporter.agent import (
    BrokerMetricsSource,
    DemoBrokerMetricsSource,
    MetricsReporter,
)
from cruise_control_tpu.reporter.serde import (
    METRIC_VERSION,
    UnknownVersionError,
    deserialize_metric,
    serialize_metric,
)
from cruise_control_tpu.reporter.transport import (
    FileTransport,
    InProcessTransport,
    SocketTransport,
    TransportServer,
    Transport,
)

__all__ = [
    "BrokerMetricsSource", "DemoBrokerMetricsSource", "MetricsReporter",
    "METRIC_VERSION", "UnknownVersionError", "deserialize_metric",
    "serialize_metric", "FileTransport", "InProcessTransport",
    "SocketTransport", "TransportServer", "Transport",
]
