"""Pluggable transport between reporter agents and the monitor.

Reference: the reporter publishes serialized metrics to the
``__CruiseControlMetrics`` Kafka topic (CruiseControlMetricsReporter.java:
producer setup :160-180, send :340-360) and samplers consume it partitioned.
Here the transport is an SPI with the same shape — append records to a
numbered partition, poll a partition range since an offset — so the
in-process demo, a file-backed queue, or a real message bus all fit behind
the fetch fan-out's partition assignor.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import List, Protocol, Sequence, Tuple

_LEN = struct.Struct(">I")


class Transport(Protocol):
    @property
    def num_partitions(self) -> int: ...

    def append(self, partition: int, record: bytes) -> None: ...

    def poll(self, partition: int, offset: int,
             max_records: int = 10_000) -> Tuple[List[bytes], int]:
        """(records, next_offset) from ``offset`` onward."""
        ...


class InProcessTransport:
    """Partitioned in-memory log (the demo/test bus)."""

    def __init__(self, num_partitions: int = 8):
        self._parts: List[List[bytes]] = [[] for _ in range(num_partitions)]
        self._lock = threading.Lock()

    def record_count(self, partition: int) -> int:
        with self._lock:
            return len(self._parts[partition % len(self._parts)])

    def truncate_tail(self, partition: int, keep_records: int) -> None:
        """Drop everything but the newest ``keep_records`` (retention — the
        role Kafka topic retention plays for the reference's metrics/sample
        topics).  Invalidates outstanding poll offsets for the partition, so
        only offset-free consumers (replay-from-zero stores) may use it."""
        with self._lock:
            log = self._parts[partition % len(self._parts)]
            del log[:-keep_records]

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def append(self, partition: int, record: bytes) -> None:
        with self._lock:
            self._parts[partition % len(self._parts)].append(record)

    def poll(self, partition: int, offset: int,
             max_records: int = 10_000) -> Tuple[List[bytes], int]:
        with self._lock:
            log = self._parts[partition % len(self._parts)]
            out = log[offset:offset + max_records]
            return list(out), offset + len(out)


class FileTransport:
    """Partitioned length-prefixed segment files (durable demo bus)."""

    def __init__(self, directory: str, num_partitions: int = 8):
        self._dir = directory
        self._n = num_partitions
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()

    @property
    def num_partitions(self) -> int:
        return self._n

    def _path(self, partition: int) -> str:
        return os.path.join(self._dir, f"metrics-{partition % self._n}.log")

    def append(self, partition: int, record: bytes) -> None:
        with self._lock, open(self._path(partition), "ab") as f:
            f.write(_LEN.pack(len(record)))
            f.write(record)

    def record_count(self, partition: int) -> int:
        n = 0
        offset = 0
        while True:
            records, offset = self.poll(partition, offset)
            if not records:
                return n
            n += len(records)

    def truncate_tail(self, partition: int, keep_records: int) -> None:
        """Rewrite the segment keeping the newest ``keep_records`` (see
        InProcessTransport.truncate_tail for the offset-invalidation
        contract)."""
        tail: List[bytes] = []
        offset = 0
        while True:
            records, offset = self.poll(partition, offset)
            if not records:
                break
            tail.extend(records)
            tail = tail[-keep_records:]
        path = self._path(partition)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                for rec in tail:
                    f.write(_LEN.pack(len(rec)))
                    f.write(rec)
            os.replace(tmp, path)

    def poll(self, partition: int, offset: int,
             max_records: int = 10_000) -> Tuple[List[bytes], int]:
        """``offset`` is a BYTE offset for the file transport."""
        path = self._path(partition)
        if not os.path.exists(path):
            return [], offset
        out: List[bytes] = []
        with self._lock, open(path, "rb") as f:
            f.seek(offset)
            pos = offset
            while len(out) < max_records:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    break
                (n,) = _LEN.unpack(head)
                rec = f.read(n)
                if len(rec) < n:   # torn tail write — re-read next poll
                    break
                out.append(rec)
                pos = f.tell()
            return out, pos
