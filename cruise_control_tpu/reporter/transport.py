"""Pluggable transport between reporter agents and the monitor.

Reference: the reporter publishes serialized metrics to the
``__CruiseControlMetrics`` Kafka topic (CruiseControlMetricsReporter.java:
producer setup :160-180, send :340-360) and samplers consume it partitioned.
Here the transport is an SPI with the same shape — append records to a
numbered partition, poll a partition range since an offset — so the
in-process demo, a file-backed queue, or a real message bus all fit behind
the fetch fan-out's partition assignor.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import List, Protocol, Sequence, Tuple

_LEN = struct.Struct(">I")


class Transport(Protocol):
    @property
    def num_partitions(self) -> int: ...

    def append(self, partition: int, record: bytes) -> None: ...

    def poll(self, partition: int, offset: int,
             max_records: int = 10_000) -> Tuple[List[bytes], int]:
        """(records, next_offset) from ``offset`` onward."""
        ...


class InProcessTransport:
    """Partitioned in-memory log (the demo/test bus)."""

    def __init__(self, num_partitions: int = 8):
        self._parts: List[List[bytes]] = [[] for _ in range(num_partitions)]
        self._lock = threading.Lock()

    def record_count(self, partition: int) -> int:
        with self._lock:
            return len(self._parts[partition % len(self._parts)])

    def truncate_tail(self, partition: int, keep_records: int) -> None:
        """Drop everything but the newest ``keep_records`` (retention — the
        role Kafka topic retention plays for the reference's metrics/sample
        topics).  Invalidates outstanding poll offsets for the partition, so
        only offset-free consumers (replay-from-zero stores) may use it."""
        with self._lock:
            log = self._parts[partition % len(self._parts)]
            del log[:-keep_records]

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def append(self, partition: int, record: bytes) -> None:
        with self._lock:
            self._parts[partition % len(self._parts)].append(record)

    def poll(self, partition: int, offset: int,
             max_records: int = 10_000) -> Tuple[List[bytes], int]:
        with self._lock:
            log = self._parts[partition % len(self._parts)]
            out = log[offset:offset + max_records]
            return list(out), offset + len(out)


class FileTransport:
    """Partitioned length-prefixed segment files (durable demo bus)."""

    def __init__(self, directory: str, num_partitions: int = 8):
        self._dir = directory
        self._n = num_partitions
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()

    @property
    def num_partitions(self) -> int:
        return self._n

    def _path(self, partition: int) -> str:
        return os.path.join(self._dir, f"metrics-{partition % self._n}.log")

    def append(self, partition: int, record: bytes) -> None:
        with self._lock, open(self._path(partition), "ab") as f:
            f.write(_LEN.pack(len(record)))
            f.write(record)

    def record_count(self, partition: int) -> int:
        n = 0
        offset = 0
        while True:
            records, offset = self.poll(partition, offset)
            if not records:
                return n
            n += len(records)

    def truncate_tail(self, partition: int, keep_records: int) -> None:
        """Rewrite the segment keeping the newest ``keep_records`` (see
        InProcessTransport.truncate_tail for the offset-invalidation
        contract)."""
        tail: List[bytes] = []
        offset = 0
        while True:
            records, offset = self.poll(partition, offset)
            if not records:
                break
            tail.extend(records)
            tail = tail[-keep_records:]
        path = self._path(partition)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                for rec in tail:
                    f.write(_LEN.pack(len(rec)))
                    f.write(rec)
            os.replace(tmp, path)

    def poll(self, partition: int, offset: int,
             max_records: int = 10_000) -> Tuple[List[bytes], int]:
        """``offset`` is a BYTE offset for the file transport."""
        path = self._path(partition)
        if not os.path.exists(path):
            return [], offset
        out: List[bytes] = []
        with self._lock, open(path, "rb") as f:
            f.seek(offset)
            pos = offset
            while len(out) < max_records:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    break
                (n,) = _LEN.unpack(head)
                rec = f.read(n)
                if len(rec) < n:   # torn tail write — re-read next poll
                    break
                out.append(rec)
                pos = f.tell()
            return out, pos


#: Upper bound on one request frame (metrics records are KB-scale; the
#: base64 of the largest sane record is far below this).  Bounding the
#: readline keeps one misbehaving peer from buffering an unbounded line into
#: service memory.
MAX_FRAME_BYTES = 4 * 1024 * 1024


class TransportServer:
    """Expose a Transport on a TCP listener — the bus's broker side.

    The reference's metrics bus is a Kafka topic: broker-side reporter
    plugins PRODUCE over the network and the service's samplers CONSUME
    partitioned — inheriting Kafka's SASL/SSL/ACLs.  This server gives any
    local Transport (file-backed for durability, in-process for tests) that
    network face: newline-delimited JSON frames with base64 payloads, ops
    ``meta`` / ``append`` / ``poll``.  Thread-per-connection is plenty at
    control-plane rates.

    Security (the role Kafka's listener security plays): ``auth_secret``
    requires every connection's FIRST frame to be
    ``{"op": "auth", "token": <secret>}`` — anything else is rejected and
    the connection closed, so an unauthenticated peer can neither forge
    metrics nor read workload data.  ``ssl_certfile``/``ssl_keyfile`` wrap
    the listener in TLS (same PEM config shape as the web server), which
    also protects the token in transit.  Plaintext + no secret is demo-only:
    bind it to loopback.
    """

    #: Bound on the per-connection TLS handshake; a peer that connects and
    #: goes silent is dropped after this instead of pinning its thread.
    HANDSHAKE_TIMEOUT_S = 15.0

    def __init__(self, transport: Transport, host: str = "127.0.0.1",
                 port: int = 0, auth_secret: str | None = None,
                 ssl_certfile: str | None = None,
                 ssl_keyfile: str | None = None):
        import socketserver

        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def setup(self):
                # TLS is wrapped HERE, in the per-connection thread — never
                # on the listening socket, where one stalled peer's handshake
                # would block the accept loop (and every other agent) until
                # it went away.
                if outer._ssl_ctx is not None:
                    self.request.settimeout(outer.HANDSHAKE_TIMEOUT_S)
                    self.request = outer._ssl_ctx.wrap_socket(
                        self.request, server_side=True)
                    self.request.settimeout(None)
                super().setup()

            def handle(self):
                import base64
                import hmac
                import json
                authed = outer.auth_secret is None
                if not authed:
                    # An unauthenticated peer gets HANDSHAKE_TIMEOUT_S to
                    # present its auth frame; without the deadline, a client
                    # that connects and goes silent pins this handler thread
                    # forever (same DoS shape the TLS setup already guards).
                    self.connection.settimeout(outer.HANDSHAKE_TIMEOUT_S)
                while True:
                    try:
                        line = self.rfile.readline(MAX_FRAME_BYTES)
                    except OSError:
                        # Pre-auth deadline expired (or the socket died):
                        # drop the peer.
                        return
                    if not line:
                        return
                    if len(line) >= MAX_FRAME_BYTES and \
                            not line.endswith(b"\n"):
                        # Oversized frame: answer once, then drop the peer —
                        # the rest of the line would have to be drained
                        # (unbounded) to resync the stream.
                        self._reply({"ok": False, "error":
                                     "frame exceeds MAX_FRAME_BYTES"})
                        return
                    if not authed:
                        # The auth gate sits OUTSIDE the per-frame error
                        # handling: any pre-auth frame that is not a valid
                        # auth op — wrong token, other op, or unparseable
                        # garbage — gets one error frame and a disconnect.
                        # (Inside it, malformed lines would loop as per-frame
                        # errors, letting an unauthenticated peer pin this
                        # thread forever.)
                        try:
                            req = json.loads(line)
                            ok_auth = (isinstance(req, dict)
                                       and req.get("op") == "auth"
                                       and hmac.compare_digest(
                                           str(req.get("token", "")),
                                           outer.auth_secret))
                        except ValueError:
                            ok_auth = False
                        if not ok_auth:
                            self._reply({"ok": False,
                                         "error": "authentication required"})
                            return
                        authed = True
                        # Authenticated peers are long-lived publishers;
                        # clear the handshake deadline.
                        self.connection.settimeout(None)
                        self._reply({"ok": True})
                        continue
                    try:
                        req = json.loads(line)
                        op = req.get("op")
                        if op == "meta":
                            resp = {"ok": True, "num_partitions":
                                    outer.transport.num_partitions}
                        elif op == "append":
                            outer.transport.append(
                                int(req["p"]),
                                base64.b64decode(req["rec"]))
                            resp = {"ok": True}
                        elif op == "poll":
                            recs, nxt = outer.transport.poll(
                                int(req["p"]), int(req["off"]),
                                int(req.get("max", 10_000)))
                            resp = {"ok": True, "next": nxt,
                                    "recs": [base64.b64encode(r).decode()
                                             for r in recs]}
                        elif op == "auth":
                            resp = {"ok": True}      # idempotent re-auth
                        else:
                            resp = {"ok": False,
                                    "error": f"unknown op {op!r}"}
                    except Exception as e:   # noqa: BLE001 — report per frame
                        resp = {"ok": False,
                                "error": f"{type(e).__name__}: {e}"}
                    self._reply(resp)

            def _reply(self, resp) -> None:
                import json
                self.wfile.write((json.dumps(resp) + "\n").encode())
                self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

            def handle_error(self, request, client_address):
                # Failed TLS handshakes / timeouts from scanners and broken
                # peers are expected noise — one log line, not a traceback.
                import logging
                import sys
                logging.getLogger(__name__).warning(
                    "transport connection from %s failed: %s",
                    client_address, sys.exc_info()[1])

        self.transport = transport
        self.auth_secret = auth_secret
        self._ssl_ctx = None
        if ssl_certfile:
            from cruise_control_tpu.utils.netsec import server_ssl_context
            self._ssl_ctx = server_ssl_context(ssl_certfile, ssl_keyfile)
        self._server = Server((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="metrics-transport")
        self._thread.start()

    def stop(self) -> None:
        # BaseServer.shutdown() blocks on an event only serve_forever sets —
        # a built-but-never-started server must not hang the caller.
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()


class SocketTransport:
    """Transport client over TCP — the role the Kafka producer/consumer
    clients play for the reference's ``__CruiseControlMetrics`` topic.
    Reporter agents on remote brokers publish through this; the service's
    consuming samplers can equally read a remote bus.  One connection,
    reconnected on failure; calls are serialized (each agent/fetcher owns
    its own instance)."""

    def __init__(self, address: str, timeout_s: float = 10.0,
                 auth_secret: str | None = None,
                 ssl_enable: bool = False,
                 ssl_cafile: str | None = None):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._timeout = timeout_s
        self._auth_secret = auth_secret
        self._ssl_enable = ssl_enable or bool(ssl_cafile)
        self._ssl_cafile = ssl_cafile
        self._sock = None
        self._rfile = None
        self._lock = threading.Lock()
        self._num_partitions: int | None = None

    def _connect_locked(self):
        import json
        import socket
        sock = socket.create_connection(self._addr, timeout=self._timeout)
        if self._ssl_enable:
            from cruise_control_tpu.utils.netsec import client_ssl_context
            sock = client_ssl_context(self._ssl_cafile).wrap_socket(sock)
        self._sock = sock
        self._rfile = sock.makefile("rb")
        if self._auth_secret is not None:
            # Authenticate the fresh connection before replaying the caller's
            # request (TransportServer requires auth as the first frame).
            sock.sendall((json.dumps(
                {"op": "auth", "token": self._auth_secret}) + "\n").encode())
            line = self._rfile.readline()
            try:
                accepted = bool(line) and json.loads(line).get("ok")
            except ValueError as e:
                # A garbled auth reply is a CONNECTION problem (proxy junk,
                # mid-frame disconnect) — surface it as such so _request's
                # reconnect-and-retry path handles it, instead of a raw
                # JSONDecodeError escaping to the caller.
                raise ConnectionError(
                    f"malformed transport auth reply: {e}") from None
            if not accepted:
                raise ConnectionError("transport authentication rejected")

    def _request(self, req: dict, idempotent: bool = True) -> dict:
        import json

        with self._lock:
            for attempt in (0, 1):
                sent = False
                try:
                    if self._sock is None:
                        self._connect_locked()
                    self._sock.sendall((json.dumps(req) + "\n").encode())
                    sent = True
                    line = self._rfile.readline()
                    if not line:
                        raise ConnectionError("transport peer closed")
                    try:
                        resp = json.loads(line)
                    except ValueError as e:
                        # Same contract as the auth reply: a response that is
                        # not JSON means the stream is broken, not the request.
                        raise ConnectionError(
                            f"malformed transport reply: {e}") from None
                    if not resp.get("ok"):
                        raise RuntimeError(
                            f"transport error: {resp.get('error')}")
                    return resp
                except (OSError, ConnectionError):
                    self._close_locked()
                    # A lost RESPONSE may mean the server already applied
                    # the request; blind resend would double-apply appends
                    # (at-least-once → duplicate metrics).  Retry only
                    # idempotent ops, or failures from before the send.
                    if attempt or (sent and not idempotent):
                        raise
        raise AssertionError("unreachable")

    def _close_locked(self) -> None:
        for f in (self._rfile, self._sock):
            try:
                if f is not None:
                    f.close()
            except OSError:
                pass
        self._sock = self._rfile = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    @property
    def num_partitions(self) -> int:
        if self._num_partitions is None:
            self._num_partitions = int(self._request(
                {"op": "meta"})["num_partitions"])
        return self._num_partitions

    def append(self, partition: int, record: bytes) -> None:
        import base64
        self._request({"op": "append", "p": int(partition),
                       "rec": base64.b64encode(record).decode()},
                      idempotent=False)

    def poll(self, partition: int, offset: int,
             max_records: int = 10_000) -> Tuple[List[bytes], int]:
        import base64
        resp = self._request({"op": "poll", "p": int(partition),
                              "off": int(offset), "max": int(max_records)})
        return ([base64.b64decode(r) for r in resp["recs"]],
                int(resp["next"]))
