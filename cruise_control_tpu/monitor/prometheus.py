"""Prometheus-backed metric sampler.

Reference: ``monitor/sampling/prometheus/PrometheusMetricSampler.java:54-289``
(+ ``DefaultPrometheusQuerySupplier``, ``PrometheusAdapter``): for every raw
metric type, run a PromQL range query, map each series back to a broker /
topic / partition via its labels, average the series values over the window,
and hand the typed batch to the metrics processor.

The HTTP layer is injectable (``query_fn``) so deployments plug their client
and tests feed canned series; the default uses stdlib urllib against
``<endpoint>/api/v1/query_range``.
"""

from __future__ import annotations

import json
import logging
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cruise_control_tpu.common.exceptions import CruiseControlError
from cruise_control_tpu.monitor.samples import CruiseControlMetric, RawMetricScope, RawMetricType
from cruise_control_tpu.monitor.sampler import (
    CruiseControlMetricsProcessor,
    SamplerResult,
)

LOG = logging.getLogger(__name__)


class InvalidPrometheusResultError(CruiseControlError):
    """Series whose labels cannot be mapped to this cluster — skipped."""


@dataclass
class PrometheusSeries:
    labels: Dict[str, str]
    values: List[Tuple[float, float]]     # (time_s, value)


def default_query_map() -> Dict[RawMetricType, str]:
    """RawMetricType → PromQL (DefaultPrometheusQuerySupplier.java:22-120,
    node-exporter + JMX-exporter naming)."""
    q: Dict[RawMetricType, str] = {
        RawMetricType.BROKER_CPU_UTIL:
            "1 - avg by (instance) (irate(node_cpu_seconds_total{mode='idle'}[1m]))",
        RawMetricType.ALL_TOPIC_BYTES_IN:
            "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_BytesInPerSec[1m]))",
        RawMetricType.ALL_TOPIC_BYTES_OUT:
            "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_BytesOutPerSec[1m]))",
        RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN:
            "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_ReplicationBytesInPerSec[1m]))",
        RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT:
            "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_ReplicationBytesOutPerSec[1m]))",
        RawMetricType.ALL_TOPIC_FETCH_REQUEST_RATE:
            "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_TotalFetchRequestsPerSec[1m]))",
        RawMetricType.ALL_TOPIC_PRODUCE_REQUEST_RATE:
            "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_TotalProduceRequestsPerSec[1m]))",
        RawMetricType.ALL_TOPIC_MESSAGES_IN_PER_SEC:
            "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_MessagesInPerSec[1m]))",
        RawMetricType.TOPIC_BYTES_IN:
            "sum by (instance, topic) (irate(kafka_server_BrokerTopicMetrics_BytesInPerSec{topic!=''}[1m]))",
        RawMetricType.TOPIC_BYTES_OUT:
            "sum by (instance, topic) (irate(kafka_server_BrokerTopicMetrics_BytesOutPerSec{topic!=''}[1m]))",
        RawMetricType.PARTITION_SIZE:
            "sum by (instance, topic, partition) (kafka_log_Log_Size)",
    }
    return q


class PrometheusMetricSampler:
    """MetricSampler SPI impl querying a Prometheus server."""

    def __init__(self, endpoint: Optional[str] = None,
                 query_map: Optional[Dict[RawMetricType, str]] = None,
                 query_fn: Optional[Callable[[str, float, float], List[PrometheusSeries]]] = None,
                 resolution_step_ms: float = 60_000.0,
                 processor: Optional[CruiseControlMetricsProcessor] = None):
        if not endpoint and query_fn is None:
            # Fail at construction (startup), not at the first sampling tick.
            raise ValueError(
                "PrometheusMetricSampler needs a prometheus.server.endpoint "
                "or an injected query_fn")
        self.endpoint = endpoint
        self.query_map = query_map or default_query_map()
        self.step_ms = resolution_step_ms
        self.processor = processor or CruiseControlMetricsProcessor()
        self._query_fn = query_fn or self._http_query

    # ---------------------------------------------------------- http adapter

    def _http_query(self, promql: str, start_ms: float,
                    end_ms: float) -> List[PrometheusSeries]:
        """PrometheusAdapter.queryMetric — /api/v1/query_range."""
        params = urllib.parse.urlencode({
            "query": promql,
            "start": start_ms / 1000.0,
            "end": end_ms / 1000.0,
            "step": max(self.step_ms / 1000.0, 1.0),
        })
        url = f"{self.endpoint}/api/v1/query_range?{params}"
        with urllib.request.urlopen(url, timeout=30) as resp:
            payload = json.load(resp)
        if payload.get("status") != "success":
            raise CruiseControlError(f"prometheus query failed: {payload}")
        out = []
        for series in payload["data"]["result"]:
            values = [(float(t), float(v)) for t, v in series.get("values", [])]
            out.append(PrometheusSeries(labels=series.get("metric", {}),
                                        values=values))
        return out

    # ------------------------------------------------------------- mapping

    @staticmethod
    def _host_of(labels: Dict[str, str]) -> str:
        instance = labels.get("instance", "")
        return instance.split(":", 1)[0]

    def _broker_for(self, labels: Dict[str, str], host_map: Dict[str, int]) -> int:
        host = self._host_of(labels)
        if host not in host_map:
            raise InvalidPrometheusResultError(f"unknown instance host {host!r}")
        return host_map[host]

    def _series_value(self, series: PrometheusSeries) -> float:
        if not series.values:
            raise InvalidPrometheusResultError("empty series")
        return sum(v for _, v in series.values) / len(series.values)

    def get_samples(self, metadata, start_ms: float, end_ms: float) -> SamplerResult:
        host_map = {b.host: b.broker_id for b in metadata.brokers}
        raw: List[CruiseControlMetric] = []
        skipped = 0
        for raw_type, promql in self.query_map.items():
            try:
                results = self._query_fn(promql, start_ms, end_ms)
            except CruiseControlError:
                raise
            except Exception as e:
                raise CruiseControlError(
                    f"could not query prometheus for {raw_type.name}: {e}") from e
            for series in results:
                try:
                    broker_id = self._broker_for(series.labels, host_map)
                    value = self._series_value(series)
                    topic = series.labels.get("topic")
                    partition = series.labels.get("partition")
                    if raw_type.scope is not RawMetricScope.BROKER and not topic:
                        raise InvalidPrometheusResultError("missing topic label")
                    raw.append(CruiseControlMetric(
                        raw_type=raw_type, time_ms=end_ms, broker_id=broker_id,
                        topic=topic,
                        partition=int(partition) if partition is not None else None,
                        value=value))
                except InvalidPrometheusResultError:
                    # Frequent and legitimate (e.g. a shared Prometheus server
                    # carrying other clusters' series) — trace-level skip.
                    skipped += 1
        LOG.debug("prometheus sampler: %d metrics, %d series skipped",
                  len(raw), skipped)
        if not raw:
            return SamplerResult()
        return self.processor.process(metadata, raw, end_ms)
