"""Monitor layer: samples → windowed aggregates → cluster model snapshots.

TPU-native replacement for the reference monitor
(``monitor/LoadMonitor.java``, ``monitor/sampling/**`` and the core
``MetricSampleAggregator`` framework): ring buffers become dense
``f32[E, W, M]`` arrays with count/validity planes, extrapolations become
vectorized masks, and the output is the frozen SoA snapshot the analyzer
consumes directly.
"""

from cruise_control_tpu.monitor.metric_def import MetricDef, ValueComputingStrategy
from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions,
    Extrapolation,
    MetricSampleAggregator,
    MetricSampleCompleteness,
)
from cruise_control_tpu.monitor.load_monitor import (
    LoadMonitor,
    ModelCompletenessRequirements,
)

__all__ = [
    "MetricDef",
    "ValueComputingStrategy",
    "MetricSampleAggregator",
    "AggregationOptions",
    "MetricSampleCompleteness",
    "Extrapolation",
    "LoadMonitor",
    "ModelCompletenessRequirements",
]
