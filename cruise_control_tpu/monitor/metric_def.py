"""Metric definitions.

Reference: core ``metricdef/MetricDef.java:30-157`` (name→id registry, per-
metric value-computing strategy, resource grouping) and the Kafka-typed
``monitor/metricdefinition/KafkaMetricDef.java:42-298`` (the ~50 model
metrics with AVG/MAX/LATEST strategies, COMMON vs BROKER_ONLY scope, and the
resource↔metric-id mapping that ``Load.expectedUtilizationFor`` uses).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.common.resources import Resource


class ValueComputingStrategy(enum.Enum):
    """How windowed samples collapse to one value (MetricDef.java)."""

    AVG = "avg"
    MAX = "max"
    LATEST = "latest"


class DefScope(enum.Enum):
    COMMON = "common"          # partitions and brokers
    BROKER_ONLY = "broker"     # broker entities only


@dataclass(frozen=True)
class MetricInfo:
    name: str
    metric_id: int
    strategy: ValueComputingStrategy
    scope: DefScope
    group: Optional[Resource]      # resource this metric contributes to
    to_predict: bool = False       # used by the CPU linear model


class MetricDef:
    """Immutable metric registry (core MetricDef semantics)."""

    def __init__(self, infos: Sequence[MetricInfo]):
        self._infos = list(infos)
        self._by_name = {m.name: m for m in infos}
        assert [m.metric_id for m in infos] == list(range(len(infos)))

    def metric_info(self, name: str) -> MetricInfo:
        return self._by_name[name]

    def metric_id(self, name: str) -> int:
        return self._by_name[name].metric_id

    def all_metric_infos(self) -> List[MetricInfo]:
        return list(self._infos)

    @property
    def size(self) -> int:
        return len(self._infos)

    def strategy_vector(self) -> np.ndarray:
        """i8[M]: 0=AVG 1=MAX 2=LATEST — drives vectorized window collapse."""
        order = [ValueComputingStrategy.AVG, ValueComputingStrategy.MAX,
                 ValueComputingStrategy.LATEST]
        return np.array([order.index(m.strategy) for m in self._infos], dtype=np.int8)

    def resource_metric_ids(self, resource: Resource) -> List[int]:
        return [m.metric_id for m in self._infos if m.group == resource]

    def resource_matrix(self) -> np.ndarray:
        """f32[4, M]: selector matrix — resource utilization = matrix @ values
        (a metric contributes to at most one resource)."""
        mat = np.zeros((4, self.size), dtype=np.float32)
        for m in self._infos:
            if m.group is not None:
                mat[int(m.group), m.metric_id] = 1.0
        return mat


def _common(name: str, strategy: ValueComputingStrategy,
            group: Optional[Resource], predict: bool = False) -> Tuple:
    return (name, strategy, DefScope.COMMON, group, predict)


def _broker(name: str, strategy: ValueComputingStrategy = ValueComputingStrategy.AVG,
            group: Optional[Resource] = None) -> Tuple:
    return (name, strategy, DefScope.BROKER_ONLY, group, False)


# KafkaMetricDef.java:44-101 — COMMON metrics first (shared id space for
# partition entities), then BROKER_ONLY.
_A, _M, _L = (ValueComputingStrategy.AVG, ValueComputingStrategy.MAX,
              ValueComputingStrategy.LATEST)
_DEFS = [
    _common("CPU_USAGE", _A, Resource.CPU, True),
    _common("DISK_USAGE", _L, Resource.DISK),
    _common("LEADER_BYTES_IN", _A, Resource.NW_IN),
    _common("LEADER_BYTES_OUT", _A, Resource.NW_OUT),
    _common("PRODUCE_RATE", _A, None),
    _common("FETCH_RATE", _A, None),
    _common("MESSAGE_IN_RATE", _A, None),
    _common("REPLICATION_BYTES_IN_RATE", _A, Resource.NW_IN),
    _common("REPLICATION_BYTES_OUT_RATE", _A, Resource.NW_OUT),
    _broker("BROKER_PRODUCE_REQUEST_RATE"),
    _broker("BROKER_CONSUMER_FETCH_REQUEST_RATE"),
    _broker("BROKER_FOLLOWER_FETCH_REQUEST_RATE"),
    _broker("BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT"),
    _broker("BROKER_REQUEST_QUEUE_SIZE"),
    _broker("BROKER_RESPONSE_QUEUE_SIZE"),
    _broker("BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX", _M),
    _broker("BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN"),
    _broker("BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX", _M),
    _broker("BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN"),
    _broker("BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX", _M),
    _broker("BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN"),
    _broker("BROKER_PRODUCE_TOTAL_TIME_MS_MAX", _M),
    _broker("BROKER_PRODUCE_TOTAL_TIME_MS_MEAN"),
    _broker("BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX", _M),
    _broker("BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN"),
    _broker("BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX", _M),
    _broker("BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN"),
    _broker("BROKER_PRODUCE_LOCAL_TIME_MS_MAX", _M),
    _broker("BROKER_PRODUCE_LOCAL_TIME_MS_MEAN"),
    _broker("BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX", _M),
    _broker("BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN"),
    _broker("BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX", _M),
    _broker("BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN"),
    _broker("BROKER_LOG_FLUSH_RATE"),
    _broker("BROKER_LOG_FLUSH_TIME_MS_MAX", _M),
    _broker("BROKER_LOG_FLUSH_TIME_MS_MEAN"),
]


def _build(defs) -> MetricDef:
    infos = [MetricInfo(name=n, metric_id=i, strategy=s, scope=sc, group=g,
                        to_predict=p)
             for i, (n, s, sc, g, p) in enumerate(defs)]
    return MetricDef(infos)


# Partition entities use only the COMMON prefix; broker entities use all.
COMMON_METRIC_DEF = _build([d for d in _DEFS if d[2] is DefScope.COMMON])
BROKER_METRIC_DEF = _build(_DEFS)

CPU_USAGE = COMMON_METRIC_DEF.metric_id("CPU_USAGE")
DISK_USAGE = COMMON_METRIC_DEF.metric_id("DISK_USAGE")
LEADER_BYTES_IN = COMMON_METRIC_DEF.metric_id("LEADER_BYTES_IN")
LEADER_BYTES_OUT = COMMON_METRIC_DEF.metric_id("LEADER_BYTES_OUT")
