"""Metric samples — the monitor's unit of ingest.

Reference: ``monitor/sampling/holder/PartitionMetricSample.java`` and
``BrokerMetricSample.java`` (typed per-entity metric records with a close()
timestamp), plus the raw wire types from the metrics-reporter module
(``cruise-control-metrics-reporter/.../RawMetricType.java:27-120`` — 94 raw
broker/topic/partition metric types with BROKER/TOPIC/PARTITION scopes).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Hashable, Optional, Tuple

import numpy as np

from cruise_control_tpu.monitor import metric_def as md


class RawMetricScope(enum.Enum):
    BROKER = "broker"
    TOPIC = "topic"
    PARTITION = "partition"


class RawMetricType(enum.Enum):
    """The reporter's full raw-type inventory with the reference's wire ids
    and supported-since version bytes (RawMetricType.java:27-99 — 63 typed
    broker/topic/partition metrics; -1 = present since the first version)."""

    ALL_TOPIC_BYTES_IN = ("broker", 0, 4)
    ALL_TOPIC_BYTES_OUT = ("broker", 1, 4)
    TOPIC_BYTES_IN = ("topic", 2, -1)
    TOPIC_BYTES_OUT = ("topic", 3, -1)
    PARTITION_SIZE = ("partition", 4, -1)
    BROKER_CPU_UTIL = ("broker", 5, 4)
    ALL_TOPIC_REPLICATION_BYTES_IN = ("broker", 6, 4)
    ALL_TOPIC_REPLICATION_BYTES_OUT = ("broker", 7, 4)
    ALL_TOPIC_PRODUCE_REQUEST_RATE = ("broker", 8, 4)
    ALL_TOPIC_FETCH_REQUEST_RATE = ("broker", 9, 4)
    ALL_TOPIC_MESSAGES_IN_PER_SEC = ("broker", 10, 4)
    TOPIC_REPLICATION_BYTES_IN = ("topic", 11, -1)
    TOPIC_REPLICATION_BYTES_OUT = ("topic", 12, -1)
    TOPIC_PRODUCE_REQUEST_RATE = ("topic", 13, -1)
    TOPIC_FETCH_REQUEST_RATE = ("topic", 14, -1)
    TOPIC_MESSAGES_IN_PER_SEC = ("topic", 15, -1)
    BROKER_PRODUCE_REQUEST_RATE = ("broker", 16, 4)
    BROKER_CONSUMER_FETCH_REQUEST_RATE = ("broker", 17, 4)
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = ("broker", 18, 4)
    BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT = ("broker", 19, 4)
    BROKER_REQUEST_QUEUE_SIZE = ("broker", 20, 4)
    BROKER_RESPONSE_QUEUE_SIZE = ("broker", 21, 4)
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = ("broker", 22, 4)
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = ("broker", 23, 4)
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = ("broker", 24, 4)
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = ("broker", 25, 4)
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = ("broker", 26, 4)
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = ("broker", 27, 4)
    BROKER_PRODUCE_TOTAL_TIME_MS_MAX = ("broker", 28, 4)
    BROKER_PRODUCE_TOTAL_TIME_MS_MEAN = ("broker", 29, 4)
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX = ("broker", 30, 4)
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN = ("broker", 31, 4)
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX = ("broker", 32, 4)
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN = ("broker", 33, 4)
    BROKER_PRODUCE_LOCAL_TIME_MS_MAX = ("broker", 34, 4)
    BROKER_PRODUCE_LOCAL_TIME_MS_MEAN = ("broker", 35, 4)
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX = ("broker", 36, 4)
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN = ("broker", 37, 4)
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX = ("broker", 38, 4)
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN = ("broker", 39, 4)
    BROKER_LOG_FLUSH_RATE = ("broker", 40, 4)
    BROKER_LOG_FLUSH_TIME_MS_MAX = ("broker", 41, 4)
    BROKER_LOG_FLUSH_TIME_MS_MEAN = ("broker", 42, 4)
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH = ("broker", 43, 5)
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_999TH = ("broker", 44, 5)
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = ("broker", 45, 5)
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = ("broker", 46, 5)
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = ("broker", 47, 5)
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = ("broker", 48, 5)
    BROKER_PRODUCE_TOTAL_TIME_MS_50TH = ("broker", 49, 5)
    BROKER_PRODUCE_TOTAL_TIME_MS_999TH = ("broker", 50, 5)
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_50TH = ("broker", 51, 5)
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_999TH = ("broker", 52, 5)
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_50TH = ("broker", 53, 5)
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_999TH = ("broker", 54, 5)
    BROKER_PRODUCE_LOCAL_TIME_MS_50TH = ("broker", 55, 5)
    BROKER_PRODUCE_LOCAL_TIME_MS_999TH = ("broker", 56, 5)
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_50TH = ("broker", 57, 5)
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH = ("broker", 58, 5)
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_50TH = ("broker", 59, 5)
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH = ("broker", 60, 5)
    BROKER_LOG_FLUSH_TIME_MS_50TH = ("broker", 61, 5)
    BROKER_LOG_FLUSH_TIME_MS_999TH = ("broker", 62, 5)

    @property
    def scope(self) -> RawMetricScope:
        return RawMetricScope(self.value[0])

    @property
    def wire_id(self) -> int:
        return self.value[1]

    @property
    def supported_since(self) -> int:
        """Version byte this type first appeared in (-1 = always)."""
        return self.value[2]


_BY_WIRE_ID: Dict[int, "RawMetricType"] = {t.wire_id: t for t in RawMetricType}


def raw_type_by_id(wire_id: int) -> "RawMetricType":
    return _BY_WIRE_ID[wire_id]


def broker_metric_types_for_version(version: int) -> Tuple["RawMetricType", ...]:
    """Broker-scope types available at a wire version
    (RawMetricType.brokerMetricTypesDiffForVersion semantics)."""
    return tuple(t for t in RawMetricType
                 if t.scope is RawMetricScope.BROKER
                 and (t.supported_since == -1 or t.supported_since <= version))


@dataclass
class CruiseControlMetric:
    """One raw metric record off the wire (metrics-reporter types)."""

    raw_type: RawMetricType
    time_ms: float
    broker_id: int
    topic: Optional[str] = None
    partition: Optional[int] = None
    value: float = 0.0


@dataclass
class PartitionMetricSample:
    """Per-partition model sample (PartitionMetricSample.java)."""

    broker_id: int
    topic: str
    partition: int
    time_ms: float = 0.0
    metrics: np.ndarray = field(
        default_factory=lambda: np.zeros(md.COMMON_METRIC_DEF.size))

    @property
    def entity(self) -> Tuple[str, int]:
        return (self.topic, self.partition)

    def record(self, metric_id: int, value: float) -> None:
        self.metrics[metric_id] = value

    def close(self, time_ms: float) -> None:
        self.time_ms = time_ms

    def to_dict(self) -> Dict:
        return {
            "brokerId": self.broker_id, "topic": self.topic,
            "partition": self.partition, "time": self.time_ms,
            "metrics": self.metrics.tolist(),
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "PartitionMetricSample":
        s = cls(broker_id=d["brokerId"], topic=d["topic"], partition=d["partition"],
                time_ms=d["time"])
        s.metrics = np.asarray(d["metrics"], dtype=np.float64)
        return s


@dataclass
class BrokerMetricSample:
    """Per-broker model sample (BrokerMetricSample.java)."""

    broker_id: int
    time_ms: float = 0.0
    metrics: np.ndarray = field(
        default_factory=lambda: np.zeros(md.BROKER_METRIC_DEF.size))

    @property
    def entity(self) -> int:
        return self.broker_id

    def record(self, metric_id: int, value: float) -> None:
        self.metrics[metric_id] = value

    def to_dict(self) -> Dict:
        return {"brokerId": self.broker_id, "time": self.time_ms,
                "metrics": self.metrics.tolist()}

    @classmethod
    def from_dict(cls, d: Dict) -> "BrokerMetricSample":
        s = cls(broker_id=d["brokerId"], time_ms=d["time"])
        s.metrics = np.asarray(d["metrics"], dtype=np.float64)
        return s
