"""Metric samples — the monitor's unit of ingest.

Reference: ``monitor/sampling/holder/PartitionMetricSample.java`` and
``BrokerMetricSample.java`` (typed per-entity metric records with a close()
timestamp), plus the raw wire types from the metrics-reporter module
(``cruise-control-metrics-reporter/.../RawMetricType.java:27-120`` — 94 raw
broker/topic/partition metric types with BROKER/TOPIC/PARTITION scopes).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Hashable, Optional, Tuple

import numpy as np

from cruise_control_tpu.monitor import metric_def as md


class RawMetricScope(enum.Enum):
    BROKER = "broker"
    TOPIC = "topic"
    PARTITION = "partition"


class RawMetricType(enum.Enum):
    """The subset of the reporter's 94 raw types the model consumes
    (RawMetricType.java; the rest are passthrough broker health metrics)."""

    ALL_TOPIC_BYTES_IN = ("broker", 0)
    ALL_TOPIC_BYTES_OUT = ("broker", 1)
    ALL_TOPIC_REPLICATION_BYTES_IN = ("broker", 2)
    ALL_TOPIC_REPLICATION_BYTES_OUT = ("broker", 3)
    ALL_TOPIC_PRODUCE_REQUEST_RATE = ("broker", 4)
    ALL_TOPIC_FETCH_REQUEST_RATE = ("broker", 5)
    ALL_TOPIC_MESSAGES_IN_PER_SEC = ("broker", 6)
    BROKER_CPU_UTIL = ("broker", 7)
    BROKER_PRODUCE_REQUEST_RATE = ("broker", 8)
    BROKER_CONSUMER_FETCH_REQUEST_RATE = ("broker", 9)
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = ("broker", 10)
    BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT = ("broker", 11)
    BROKER_REQUEST_QUEUE_SIZE = ("broker", 12)
    BROKER_RESPONSE_QUEUE_SIZE = ("broker", 13)
    BROKER_LOG_FLUSH_RATE = ("broker", 14)
    BROKER_LOG_FLUSH_TIME_MS_MEAN = ("broker", 15)
    BROKER_LOG_FLUSH_TIME_MS_MAX = ("broker", 16)
    TOPIC_BYTES_IN = ("topic", 30)
    TOPIC_BYTES_OUT = ("topic", 31)
    TOPIC_REPLICATION_BYTES_IN = ("topic", 32)
    TOPIC_REPLICATION_BYTES_OUT = ("topic", 33)
    TOPIC_PRODUCE_REQUEST_RATE = ("topic", 34)
    TOPIC_FETCH_REQUEST_RATE = ("topic", 35)
    TOPIC_MESSAGES_IN_PER_SEC = ("topic", 36)
    PARTITION_SIZE = ("partition", 60)

    @property
    def scope(self) -> RawMetricScope:
        return RawMetricScope(self.value[0])


@dataclass
class CruiseControlMetric:
    """One raw metric record off the wire (metrics-reporter types)."""

    raw_type: RawMetricType
    time_ms: float
    broker_id: int
    topic: Optional[str] = None
    partition: Optional[int] = None
    value: float = 0.0


@dataclass
class PartitionMetricSample:
    """Per-partition model sample (PartitionMetricSample.java)."""

    broker_id: int
    topic: str
    partition: int
    time_ms: float = 0.0
    metrics: np.ndarray = field(
        default_factory=lambda: np.zeros(md.COMMON_METRIC_DEF.size))

    @property
    def entity(self) -> Tuple[str, int]:
        return (self.topic, self.partition)

    def record(self, metric_id: int, value: float) -> None:
        self.metrics[metric_id] = value

    def close(self, time_ms: float) -> None:
        self.time_ms = time_ms

    def to_dict(self) -> Dict:
        return {
            "brokerId": self.broker_id, "topic": self.topic,
            "partition": self.partition, "time": self.time_ms,
            "metrics": self.metrics.tolist(),
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "PartitionMetricSample":
        s = cls(broker_id=d["brokerId"], topic=d["topic"], partition=d["partition"],
                time_ms=d["time"])
        s.metrics = np.asarray(d["metrics"], dtype=np.float64)
        return s


@dataclass
class BrokerMetricSample:
    """Per-broker model sample (BrokerMetricSample.java)."""

    broker_id: int
    time_ms: float = 0.0
    metrics: np.ndarray = field(
        default_factory=lambda: np.zeros(md.BROKER_METRIC_DEF.size))

    @property
    def entity(self) -> int:
        return self.broker_id

    def record(self, metric_id: int, value: float) -> None:
        self.metrics[metric_id] = value

    def to_dict(self) -> Dict:
        return {"brokerId": self.broker_id, "time": self.time_ms,
                "metrics": self.metrics.tolist()}

    @classmethod
    def from_dict(cls, d: Dict) -> "BrokerMetricSample":
        s = cls(broker_id=d["brokerId"], time_ms=d["time"])
        s.metrics = np.asarray(d["metrics"], dtype=np.float64)
        return s
