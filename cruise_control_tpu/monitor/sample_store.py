"""Sample persistence (checkpoint/resume of monitor state).

Reference: ``monitor/sampling/SampleStore.java:19`` SPI and
``KafkaSampleStore.java:82-504`` — the reference persists accepted samples to
two Kafka topics and replays them on startup.  Here the durable medium is a
pluggable store; the built-in implementation appends JSONL segment files per
sample type and replays them through the same loader interface
(``SampleLoadingTask`` semantics).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, List, Optional, Protocol

from cruise_control_tpu.monitor.samples import BrokerMetricSample, PartitionMetricSample


class SampleStore(Protocol):
    def store_samples(self, partition_samples: List[PartitionMetricSample],
                      broker_samples: List[BrokerMetricSample]) -> None: ...

    def load_samples(self,
                     on_partition: Callable[[PartitionMetricSample], None],
                     on_broker: Callable[[BrokerMetricSample], None]) -> int: ...

    def close(self) -> None: ...


class NoopSampleStore:
    def store_samples(self, partition_samples, broker_samples) -> None:
        pass

    def load_samples(self, on_partition, on_broker) -> int:
        return 0

    def close(self) -> None:
        pass


class FileSampleStore:
    """JSONL segment files: ``partition_samples.jsonl`` + ``broker_samples.jsonl``.

    Mirrors KafkaSampleStore behavior: append on store, full replay on load,
    bounded retention by rewriting when the file exceeds ``max_records``.
    """

    def __init__(self, directory: str, max_records: int = 1_000_000):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._ppath = os.path.join(directory, "partition_samples.jsonl")
        self._bpath = os.path.join(directory, "broker_samples.jsonl")
        self._lock = threading.Lock()
        self._max_records = max_records
        self._pcount = self._count_lines(self._ppath)
        self._bcount = self._count_lines(self._bpath)

    @staticmethod
    def _count_lines(path: str) -> int:
        if not os.path.exists(path):
            return 0
        with open(path) as f:
            return sum(1 for _ in f)

    def store_samples(self, partition_samples, broker_samples) -> None:
        with self._lock:
            if partition_samples:
                with open(self._ppath, "a") as f:
                    for s in partition_samples:
                        f.write(json.dumps(s.to_dict()) + "\n")
                self._pcount += len(partition_samples)
            if broker_samples:
                with open(self._bpath, "a") as f:
                    for s in broker_samples:
                        f.write(json.dumps(s.to_dict()) + "\n")
                self._bcount += len(broker_samples)
            if self._pcount > self._max_records:
                self._truncate(self._ppath, self._max_records // 2)
                self._pcount = self._count_lines(self._ppath)
            if self._bcount > self._max_records:
                self._truncate(self._bpath, self._max_records // 2)
                self._bcount = self._count_lines(self._bpath)

    @staticmethod
    def _truncate(path: str, keep: int) -> None:
        with open(path) as f:
            lines = f.readlines()
        with open(path, "w") as f:
            f.writelines(lines[-keep:])

    def load_samples(self, on_partition, on_broker) -> int:
        n = 0
        with self._lock:
            if os.path.exists(self._ppath):
                with open(self._ppath) as f:
                    for line in f:
                        if line.strip():
                            on_partition(PartitionMetricSample.from_dict(
                                json.loads(line)))
                            n += 1
            if os.path.exists(self._bpath):
                with open(self._bpath) as f:
                    for line in f:
                        if line.strip():
                            on_broker(BrokerMetricSample.from_dict(json.loads(line)))
                            n += 1
        return n

    def close(self) -> None:
        pass
