"""Sample persistence (checkpoint/resume of monitor state).

Reference: ``monitor/sampling/SampleStore.java:19`` SPI and
``KafkaSampleStore.java:82-504`` — the reference persists accepted samples to
two Kafka topics and replays them on startup.  Two built-in implementations:
``FileSampleStore`` (flat JSONL per sample type, bounded retention) and
``LogSampleStore`` (the KafkaSampleStore shape — two partitioned-log
``Transport`` topics with an N-consumer reload pool; the demo service wires
it whenever ``sample.store.dir`` + reporter mode are both set).
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from cruise_control_tpu.monitor.samples import BrokerMetricSample, PartitionMetricSample


def _count_stored(n: int) -> None:
    """Ingest telemetry: samples persisted to the store (fidelity
    observatory `Monitor.stored-samples`, registered eagerly there)."""
    if n:
        from cruise_control_tpu.common.metrics import registry
        registry().counter("Monitor.stored-samples").inc(n)


class SampleStore(Protocol):
    def store_samples(self, partition_samples: List[PartitionMetricSample],
                      broker_samples: List[BrokerMetricSample]) -> None: ...

    def load_samples(self,
                     on_partition: Callable[[PartitionMetricSample], None],
                     on_broker: Callable[[BrokerMetricSample], None]) -> int: ...

    def close(self) -> None: ...


class NoopSampleStore:
    def store_samples(self, partition_samples, broker_samples) -> None:
        pass

    def load_samples(self, on_partition, on_broker) -> int:
        return 0

    def close(self) -> None:
        pass


class FileSampleStore:
    """JSONL segment files: ``partition_samples.jsonl`` + ``broker_samples.jsonl``.

    Mirrors KafkaSampleStore behavior: append on store, full replay on load,
    bounded retention by rewriting when the file exceeds ``max_records``.
    """

    def __init__(self, directory: str, max_records: int = 1_000_000):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._ppath = os.path.join(directory, "partition_samples.jsonl")
        self._bpath = os.path.join(directory, "broker_samples.jsonl")
        self._lock = threading.Lock()
        self._max_records = max_records
        self._pcount = self._count_lines(self._ppath)
        self._bcount = self._count_lines(self._bpath)

    @staticmethod
    def _count_lines(path: str) -> int:
        if not os.path.exists(path):
            return 0
        with open(path) as f:
            return sum(1 for _ in f)

    def store_samples(self, partition_samples, broker_samples) -> None:
        with self._lock:
            if partition_samples:
                with open(self._ppath, "a") as f:
                    for s in partition_samples:
                        f.write(json.dumps(s.to_dict()) + "\n")
                self._pcount += len(partition_samples)
            if broker_samples:
                with open(self._bpath, "a") as f:
                    for s in broker_samples:
                        f.write(json.dumps(s.to_dict()) + "\n")
                self._bcount += len(broker_samples)
            if self._pcount > self._max_records:
                self._truncate(self._ppath, self._max_records // 2)
                self._pcount = self._count_lines(self._ppath)
            if self._bcount > self._max_records:
                self._truncate(self._bpath, self._max_records // 2)
                self._bcount = self._count_lines(self._bpath)
        _count_stored(len(partition_samples) + len(broker_samples))

    @staticmethod
    def _truncate(path: str, keep: int) -> None:
        with open(path) as f:
            lines = f.readlines()
        with open(path, "w") as f:
            f.writelines(lines[-keep:])

    def load_samples(self, on_partition, on_broker) -> int:
        n = 0
        with self._lock:
            if os.path.exists(self._ppath):
                with open(self._ppath) as f:
                    for line in f:
                        if line.strip():
                            on_partition(PartitionMetricSample.from_dict(
                                json.loads(line)))
                            n += 1
            if os.path.exists(self._bpath):
                with open(self._bpath) as f:
                    for line in f:
                        if line.strip():
                            on_broker(BrokerMetricSample.from_dict(json.loads(line)))
                            n += 1
        return n

    def close(self) -> None:
        pass


class LogSampleStore:
    """Sample store over the partitioned-log ``Transport`` SPI — the
    KafkaSampleStore shape (``KafkaSampleStore.java:82-504``).

    The reference persists accepted samples to TWO Kafka topics (partition
    samples + broker/model-training samples), partitioned by entity hash,
    and on startup replays both with a pool of N consumers, each owning a
    round-robin slice of the partitions.  Here the two topics are two
    ``Transport`` logs (same SPI the metrics reporter publishes over, so a
    FileTransport directory gives durable restart/resume), the partitioner
    is the same entity hash, and the reload pool is ``num_loaders`` threads
    polling their partition slice — applies are serialized through one lock
    because unlike the reference's aggregator our replay callbacks make no
    thread-safety promise.  Retention is the transport's concern (Kafka
    topic retention in the reference; FileTransport keeps everything).
    """

    def __init__(self, partition_transport, broker_transport,
                 num_loaders: int = 8,
                 max_records_per_partition: int = 100_000):
        self._pt = partition_transport
        self._bt = broker_transport
        self.num_loaders = max(1, num_loaders)
        self._apply_lock = threading.Lock()
        # Retention (the role Kafka topic retention plays for the
        # reference's sample topics): without it the logs — and every
        # restart's replay — grow linearly with service age.  Counts are
        # tracked in memory after a lazy initial scan; partitions are
        # trimmed to half the cap when they exceed it.
        self.max_records_per_partition = max_records_per_partition
        self._counts: Dict[Tuple[int, int], int] = {}

    def store_samples(self, partition_samples, broker_samples) -> None:
        for s in partition_samples:
            # Stable entity hash (NOT the salted builtin hash(), which moves
            # every entity to a new partition each process generation and
            # breaks the per-entity single-partition ordering on replay).
            key = zlib.crc32(f"{s.topic}-{s.partition}".encode("utf-8"))
            self._append(self._pt, 0, key % self._pt.num_partitions,
                         json.dumps(s.to_dict()).encode("utf-8"))
        for s in broker_samples:
            self._append(self._bt, 1, s.broker_id % self._bt.num_partitions,
                         json.dumps(s.to_dict()).encode("utf-8"))
        _count_stored(len(partition_samples) + len(broker_samples))

    def _append(self, transport, tid: int, partition: int, record: bytes) -> None:
        transport.append(partition, record)
        if not hasattr(transport, "truncate_tail"):
            return
        key = (tid, partition)
        with self._apply_lock:
            n = self._counts.get(key)
            if n is None:
                # Lazy scan AFTER the append above — already includes it.
                n = transport.record_count(partition)
            else:
                n += 1
            if n > self.max_records_per_partition:
                transport.truncate_tail(partition,
                                        self.max_records_per_partition // 2)
                n = self.max_records_per_partition // 2
            self._counts[key] = n

    def load_samples(self, on_partition, on_broker) -> int:
        from cruise_control_tpu.monitor.fetcher import (
            DefaultMetricSamplerPartitionAssignor as assignor,
        )
        total = [0]

        def drain(transport, partitions, parse, apply):
            n = 0
            for p in partitions:
                offset = 0
                while True:
                    records, offset = transport.poll(p, offset)
                    if not records:
                        break
                    for rec in records:
                        sample = parse(json.loads(rec.decode("utf-8")))
                        with self._apply_lock:
                            apply(sample)
                        n += 1
            with self._apply_lock:
                total[0] += n

        threads = []
        for transport, parse, apply in (
                (self._pt, PartitionMetricSample.from_dict, on_partition),
                (self._bt, BrokerMetricSample.from_dict, on_broker)):
            for part_set in assignor.assign(transport.num_partitions,
                                            self.num_loaders):
                if not part_set:
                    continue
                t = threading.Thread(target=drain,
                                     args=(transport, part_set, parse, apply),
                                     daemon=True)
                t.start()
                threads.append(t)
        for t in threads:
            t.join()
        return total[0]

    def close(self) -> None:
        pass
