"""Metric sampling: SPI, raw-metric processor, and built-in samplers.

Reference: ``monitor/sampling/MetricSampler.java:26`` (SPI),
``CruiseControlMetricsProcessor.java:36-239`` (raw broker/topic/partition
metrics → Partition/BrokerMetricSample with derived NW/disk rates and CPU
estimation) and ``NoopSampler``.  The Kafka-consumer and Prometheus samplers
are deployment plugins behind the same SPI; tests and the demo server use the
synthetic sampler.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Protocol, Sequence, Tuple

import numpy as np

from cruise_control_tpu.model import cpu_model
from cruise_control_tpu.monitor import metric_def as md
from cruise_control_tpu.monitor.metadata import ClusterMetadata
from cruise_control_tpu.monitor.samples import (
    BrokerMetricSample,
    CruiseControlMetric,
    PartitionMetricSample,
    RawMetricType,
)


@dataclass
class SamplerResult:
    partition_samples: List[PartitionMetricSample] = field(default_factory=list)
    broker_samples: List[BrokerMetricSample] = field(default_factory=list)


class MetricSampler(Protocol):
    """Reference: MetricSampler.java — pluggable sample source."""

    def get_samples(self, metadata: ClusterMetadata, start_ms: float,
                    end_ms: float) -> SamplerResult: ...


class NoopSampler:
    def get_samples(self, metadata: ClusterMetadata, start_ms: float,
                    end_ms: float) -> SamplerResult:
        return SamplerResult()


# --------------------------------------------------------------- processor


class CruiseControlMetricsProcessor:
    """Raw reporter metrics → model samples (CruiseControlMetricsProcessor).

    Derivations mirror the reference: per-partition NW rates = topic rate /
    #partitions of that topic on the broker; DISK = reported partition size;
    partition CPU via ``ModelUtils.estimateLeaderCpuUtilPerCore``.
    """

    def process(self, metadata: ClusterMetadata,
                raw_metrics: Iterable[CruiseControlMetric],
                time_ms: float) -> SamplerResult:
        by_broker: Dict[int, Dict] = {}
        for m in raw_metrics:
            b = by_broker.setdefault(m.broker_id, {
                "broker": {}, "topic": {}, "partition_size": {}})
            if m.raw_type.scope.value == "broker":
                b["broker"][m.raw_type] = m.value
            elif m.raw_type.scope.value == "topic":
                b["topic"].setdefault(m.topic, {})[m.raw_type] = m.value
            elif m.raw_type == RawMetricType.PARTITION_SIZE:
                b["partition_size"][(m.topic, m.partition)] = m.value

        result = SamplerResult()
        leaders_on_broker: Dict[int, Dict[str, int]] = {}
        for p in metadata.partitions:
            if p.leader is not None:
                leaders_on_broker.setdefault(p.leader, {}).setdefault(p.topic, 0)
                leaders_on_broker[p.leader][p.topic] += 1

        for broker_id, data in by_broker.items():
            bm = data["broker"]
            bs = BrokerMetricSample(broker_id=broker_id, time_ms=time_ms)
            self._fill_broker_sample(bs, bm)
            result.broker_samples.append(bs)

            for p in metadata.partitions:
                if p.leader != broker_id:
                    continue
                topic_metrics = data["topic"].get(p.topic, {})
                n_lead = leaders_on_broker.get(broker_id, {}).get(p.topic, 1)
                bytes_in = topic_metrics.get(RawMetricType.TOPIC_BYTES_IN, 0.0) / n_lead
                bytes_out = topic_metrics.get(RawMetricType.TOPIC_BYTES_OUT, 0.0) / n_lead
                size = data["partition_size"].get((p.topic, p.partition), 0.0)
                cpu = cpu_model.estimate_leader_cpu_util_per_core(
                    bm.get(RawMetricType.BROKER_CPU_UTIL, 0.0),
                    bm.get(RawMetricType.ALL_TOPIC_BYTES_IN, 0.0),
                    bm.get(RawMetricType.ALL_TOPIC_BYTES_OUT, 0.0),
                    bm.get(RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN, 0.0),
                    bytes_in, bytes_out)
                if cpu is None:
                    # Inconsistent sample — dropped, as in reference.
                    from cruise_control_tpu.obsvc.fidelity import fidelity
                    fidelity().on_dropped("inconsistent")
                    continue
                ps = PartitionMetricSample(broker_id=broker_id, topic=p.topic,
                                           partition=p.partition)
                ps.record(md.CPU_USAGE, cpu)
                ps.record(md.LEADER_BYTES_IN, bytes_in)
                ps.record(md.LEADER_BYTES_OUT, bytes_out)
                ps.record(md.DISK_USAGE, size)
                ps.close(time_ms)
                result.partition_samples.append(ps)
        return result

    @staticmethod
    def _fill_broker_sample(bs: BrokerMetricSample, bm: Dict) -> None:
        bdef = md.BROKER_METRIC_DEF
        mapping = {
            "CPU_USAGE": RawMetricType.BROKER_CPU_UTIL,
            "LEADER_BYTES_IN": RawMetricType.ALL_TOPIC_BYTES_IN,
            "LEADER_BYTES_OUT": RawMetricType.ALL_TOPIC_BYTES_OUT,
            "REPLICATION_BYTES_IN_RATE": RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN,
            "REPLICATION_BYTES_OUT_RATE": RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT,
            "PRODUCE_RATE": RawMetricType.ALL_TOPIC_PRODUCE_REQUEST_RATE,
            "FETCH_RATE": RawMetricType.ALL_TOPIC_FETCH_REQUEST_RATE,
            "MESSAGE_IN_RATE": RawMetricType.ALL_TOPIC_MESSAGES_IN_PER_SEC,
            "BROKER_PRODUCE_REQUEST_RATE": RawMetricType.BROKER_PRODUCE_REQUEST_RATE,
            "BROKER_CONSUMER_FETCH_REQUEST_RATE":
                RawMetricType.BROKER_CONSUMER_FETCH_REQUEST_RATE,
            "BROKER_FOLLOWER_FETCH_REQUEST_RATE":
                RawMetricType.BROKER_FOLLOWER_FETCH_REQUEST_RATE,
            "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT":
                RawMetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT,
            "BROKER_REQUEST_QUEUE_SIZE": RawMetricType.BROKER_REQUEST_QUEUE_SIZE,
            "BROKER_RESPONSE_QUEUE_SIZE": RawMetricType.BROKER_RESPONSE_QUEUE_SIZE,
            "BROKER_LOG_FLUSH_RATE": RawMetricType.BROKER_LOG_FLUSH_RATE,
            "BROKER_LOG_FLUSH_TIME_MS_MEAN": RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN,
            "BROKER_LOG_FLUSH_TIME_MS_MAX": RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MAX,
        }
        for name, raw in mapping.items():
            if raw in bm:
                bs.record(bdef.metric_id(name), bm[raw])


# ---------------------------------------------------------- synthetic source

# Shared demo-workload constants + jitter, used by BOTH the synthetic sampler
# and the reporter pipeline's DemoBrokerMetricsSource so the two demo modes
# produce comparable load shapes.
DEMO_MEAN_BYTES_IN = 1000.0
DEMO_MEAN_BYTES_OUT = 800.0
DEMO_MEAN_SIZE = 5000.0
DEMO_CPU_PER_LEADER = 0.4
DEMO_SEED = 7


def synthetic_jitter(key, seed: int = DEMO_SEED) -> float:
    """Deterministic per-entity workload jitter in [0.8, 1.2)."""
    rng = np.random.default_rng((hash(key) ^ seed) & 0x7FFFFFFF)
    return 0.8 + 0.4 * rng.random()


class SyntheticWorkloadSampler:
    """Deterministic workload generator behind the MetricSampler SPI —
    the in-process stand-in for the metrics-reporter + Kafka pipeline
    (plays the role the embedded-broker harness plays in reference tests)."""

    def __init__(self, mean_bytes_in: float = DEMO_MEAN_BYTES_IN,
                 mean_bytes_out: float = DEMO_MEAN_BYTES_OUT,
                 mean_size: float = DEMO_MEAN_SIZE,
                 cpu_per_partition: float = DEMO_CPU_PER_LEADER,
                 seed: int = DEMO_SEED):
        self.mean_bytes_in = mean_bytes_in
        self.mean_bytes_out = mean_bytes_out
        self.mean_size = mean_size
        self.cpu_per_partition = cpu_per_partition
        self.seed = seed

    def get_samples(self, metadata: ClusterMetadata, start_ms: float,
                    end_ms: float) -> SamplerResult:
        result = SamplerResult()
        t = end_ms
        for p in metadata.partitions:
            if p.leader is None:
                continue
            jitter = synthetic_jitter((p.topic, p.partition), self.seed)
            ps = PartitionMetricSample(broker_id=p.leader, topic=p.topic,
                                       partition=p.partition)
            ps.record(md.CPU_USAGE, self.cpu_per_partition * jitter)
            ps.record(md.LEADER_BYTES_IN, self.mean_bytes_in * jitter)
            ps.record(md.LEADER_BYTES_OUT, self.mean_bytes_out * jitter)
            ps.record(md.DISK_USAGE, self.mean_size * jitter)
            ps.close(t)
            result.partition_samples.append(ps)
        bdef = md.BROKER_METRIC_DEF
        for b in metadata.brokers:
            if not b.alive:
                continue
            bs = BrokerMetricSample(broker_id=b.broker_id, time_ms=t)
            leaders = [p for p in metadata.partitions if p.leader == b.broker_id]
            bs.record(bdef.metric_id("CPU_USAGE"),
                      self.cpu_per_partition * max(len(leaders), 1))
            bs.record(bdef.metric_id("LEADER_BYTES_IN"),
                      self.mean_bytes_in * len(leaders))
            bs.record(bdef.metric_id("LEADER_BYTES_OUT"),
                      self.mean_bytes_out * len(leaders))
            result.broker_samples.append(bs)
        return result
