"""Cluster topology metadata.

Reference: ``common/MetadataClient.java:1-177`` — cached cluster metadata with
TTL and a generation counter that drives model staleness.  The Kafka
``Cluster`` object becomes plain dataclasses; the network client becomes a
pluggable backend (a fake in tests, a real Kafka admin driver in production
deployments — same seam the executor uses).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Tuple


@dataclass(frozen=True)
class BrokerInfo:
    broker_id: int
    rack: str
    host: str
    alive: bool = True


@dataclass(frozen=True)
class PartitionInfo:
    topic: str
    partition: int
    leader: Optional[int]            # broker id, None if leaderless
    replicas: Tuple[int, ...]        # replica-list order (index 0 = preferred)
    in_sync: Tuple[int, ...] = ()
    offline: Tuple[int, ...] = ()


@dataclass
class ClusterMetadata:
    brokers: List[BrokerInfo]
    partitions: List[PartitionInfo]
    generation: int = 0

    def broker_ids(self) -> List[int]:
        return [b.broker_id for b in self.brokers]

    def alive_broker_ids(self) -> List[int]:
        return [b.broker_id for b in self.brokers if b.alive]

    def partitions_of(self, topic: str) -> List[PartitionInfo]:
        return [p for p in self.partitions if p.topic == topic]

    def topics(self) -> List[str]:
        seen, out = set(), []
        for p in self.partitions:
            if p.topic not in seen:
                seen.add(p.topic)
                out.append(p.topic)
        return out

    def partition_count(self, topic: str) -> int:
        return sum(1 for p in self.partitions if p.topic == topic)


class MetadataBackend(Protocol):
    """Where metadata comes from (fake in tests; Kafka driver in prod)."""

    def fetch(self) -> ClusterMetadata: ...


class MetadataClient:
    """TTL cache + generation counter over a MetadataBackend."""

    def __init__(self, backend: MetadataBackend, ttl_ms: int = 5_000,
                 clock=time.monotonic):
        self._backend = backend
        self._ttl_s = ttl_ms / 1000.0
        self._clock = clock
        self._lock = threading.Lock()
        self._cached: Optional[ClusterMetadata] = None
        self._fetched_at = -float("inf")
        self._generation = 0

    @property
    def generation(self) -> int:
        return self._generation

    def refresh_metadata(self, force: bool = False) -> ClusterMetadata:
        with self._lock:
            now = self._clock()
            if force or self._cached is None or now - self._fetched_at > self._ttl_s:
                fresh = self._backend.fetch()
                if self._cached is None or _changed(self._cached, fresh):
                    self._generation += 1
                fresh.generation = self._generation
                self._cached = fresh
                self._fetched_at = now
            return self._cached

    def cluster(self) -> ClusterMetadata:
        return self.refresh_metadata()


def _changed(old: ClusterMetadata, new: ClusterMetadata) -> bool:
    return (old.brokers != new.brokers) or (old.partitions != new.partitions)


class FakeMetadataBackend:
    """Mutable in-process topology for tests (plays the embedded-broker role
    from the reference's CCKafkaIntegrationTestHarness)."""

    def __init__(self, brokers: List[BrokerInfo], partitions: List[PartitionInfo]):
        self.brokers = list(brokers)
        self.partitions = list(partitions)
        self._lock = threading.Lock()

    def fetch(self) -> ClusterMetadata:
        with self._lock:
            return ClusterMetadata(brokers=list(self.brokers),
                                   partitions=list(self.partitions))

    def kill_broker(self, broker_id: int) -> None:
        with self._lock:
            self.brokers = [
                BrokerInfo(b.broker_id, b.rack, b.host, alive=False)
                if b.broker_id == broker_id else b for b in self.brokers]

    def set_partitions(self, partitions: List[PartitionInfo]) -> None:
        with self._lock:
            self.partitions = list(partitions)

    def apply_reassignment(self, topic: str, partition: int,
                           new_replicas: Tuple[int, ...],
                           new_leader: Optional[int] = None) -> None:
        with self._lock:
            out = []
            for p in self.partitions:
                if p.topic == topic and p.partition == partition:
                    out.append(PartitionInfo(
                        topic=topic, partition=partition,
                        leader=new_leader if new_leader is not None else new_replicas[0],
                        replicas=tuple(new_replicas),
                        in_sync=tuple(new_replicas)))
                else:
                    out.append(p)
            self.partitions = out
