"""Metric fetch fan-out.

Reference: ``monitor/sampling/MetricFetcherManager.java:35-223`` — a pool of
sampling threads each fetching its assigned partition set per sampling
round — and ``DefaultMetricSamplerPartitionAssignor.java`` (round-robin
assignment of partitions to fetchers).  Ingest math is vectorized here, but
the FETCH side is network-bound exactly like the reference's, so the fan-out
survives: N fetcher threads drain disjoint transport-partition sets in
parallel, and the combined raw batch feeds one vectorized processor pass.
"""

from __future__ import annotations

import concurrent.futures
import json
import logging
import os
import threading
from typing import Dict, List, Optional, Sequence

from cruise_control_tpu.monitor.samples import CruiseControlMetric
from cruise_control_tpu.monitor.sampler import (
    CruiseControlMetricsProcessor,
    SamplerResult,
)
from cruise_control_tpu.reporter.serde import deserialize_metric
from cruise_control_tpu.reporter.transport import Transport

LOG = logging.getLogger(__name__)


class DefaultMetricSamplerPartitionAssignor:
    """Round-robin partitions over fetchers
    (DefaultMetricSamplerPartitionAssignor.java:62)."""

    @staticmethod
    def assign(num_partitions: int, num_fetchers: int) -> List[List[int]]:
        sets: List[List[int]] = [[] for _ in range(max(num_fetchers, 1))]
        for p in range(num_partitions):
            sets[p % len(sets)].append(p)
        return sets


class ConsumingMetricSampler:
    """MetricSampler SPI impl consuming the reporter wire via the transport.

    The reference's consumer-based ``CruiseControlMetricsReporterSampler``:
    poll serialized raw metrics, deserialize, hand the batch to
    ``CruiseControlMetricsProcessor``.  Fetching fans out across
    ``num_fetchers`` threads with the round-robin partition assignor.
    """

    def __init__(self, transport: Transport, num_fetchers: int = 4,
                 processor: Optional[CruiseControlMetricsProcessor] = None,
                 offsets_path: Optional[str] = None):
        self.transport = transport
        self.num_fetchers = max(1, num_fetchers)
        self.processor = processor or CruiseControlMetricsProcessor()
        # Committed consumer positions (the reference sampler's Kafka
        # consumer-group offsets): without them a DURABLE transport would be
        # re-ingested from offset 0 on every restart — a day of stale raw
        # metrics folded into the current window and re-persisted by the
        # sample store.  None = in-memory only (in-process transports).
        self._offsets_path = offsets_path
        self._offsets: Dict[int, int] = {}
        if offsets_path and os.path.exists(offsets_path):
            try:
                with open(offsets_path, encoding="utf-8") as f:
                    self._offsets = {int(k): int(v)
                                     for k, v in json.load(f).items()}
            except (OSError, ValueError):
                LOG.warning("unreadable consumer-offsets file %s; consuming "
                            "from the log start", offsets_path, exc_info=True)
        self._lock = threading.Lock()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.num_fetchers, thread_name_prefix="metric-fetcher")

    def _commit_offsets(self) -> None:
        if not self._offsets_path:
            return
        with self._lock:
            snapshot = dict(self._offsets)
        tmp = self._offsets_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snapshot, f)
            os.replace(tmp, self._offsets_path)
        except OSError:
            LOG.warning("failed to commit consumer offsets to %s",
                        self._offsets_path, exc_info=True)

    def _fetch_partitions(self, partitions: Sequence[int],
                          start_ms: float, end_ms: float) -> List[CruiseControlMetric]:
        out: List[CruiseControlMetric] = []
        for p in partitions:
            with self._lock:
                offset = self._offsets.get(p, 0)
            records, next_offset = self.transport.poll(p, offset)
            with self._lock:
                self._offsets[p] = next_offset
            for rec in records:
                try:
                    m = deserialize_metric(rec)
                except Exception:
                    LOG.warning("undecodable metric record on partition %d", p,
                                exc_info=True)
                    from cruise_control_tpu.obsvc.fidelity import fidelity
                    fidelity().on_dropped("undecodable")
                    continue
                if m is not None:
                    # No window filter: offsets only advance once, so late
                    # records are folded into the current batch rather than
                    # dropped (the aggregator's window accounting buckets by
                    # the batch close time, as the reference sampler does).
                    out.append(m)
        return out

    def get_samples(self, metadata, start_ms: float, end_ms: float) -> SamplerResult:
        with self._lock:
            pre_fetch = dict(self._offsets)
        assignment = DefaultMetricSamplerPartitionAssignor.assign(
            self.transport.num_partitions, self.num_fetchers)
        futures = [self._pool.submit(self._fetch_partitions, parts, start_ms, end_ms)
                   for parts in assignment if parts]
        raw: List[CruiseControlMetric] = []
        for f in concurrent.futures.as_completed(futures):
            raw.extend(f.result())
        if not raw:
            self._commit_offsets()
            return SamplerResult()
        try:
            result = self.processor.process(metadata, raw, end_ms)
        except Exception:
            # At-least-once: roll the IN-MEMORY positions back too — with
            # only the durable file kept, the next tick in this process
            # would fetch from the advanced positions and then commit them,
            # silently dropping the failed interval.
            with self._lock:
                self._offsets = pre_fetch
            raise
        # Commit AFTER successful processing (the Kafka consumer pattern the
        # reference relies on).
        self._commit_offsets()
        return result

    def close(self) -> None:
        self._pool.shutdown(wait=False)
