"""Windowed metric-sample aggregation.

Reference: core ``aggregator/MetricSampleAggregator.java:84-400`` (cyclic
buffer of N completed windows + 1 active, generation counter, completeness
caching) and ``aggregator/RawMetricValues.java:29-351`` (per-entity ring
buffers, validity predicates, extrapolations AVG_AVAILABLE / AVG_ADJACENT /
FORECAST / NO_VALID_EXTRAPOLATION).

The reference keeps one synchronized RawMetricValues object per entity; here
the whole population lives in three dense planes —

    values f32[E, N+1, M]   (AVG metrics accumulate sums, MAX keep maxima,
                             LATEST keep the newest sample's value)
    counts i32[E, N+1]      samples per entity-window
    times  f64[E, N+1]      newest sample time per entity-window

— so adds are ``np.add.at`` scatters and aggregation/completeness are
vectorized mask algebra over [E, W] instead of per-entity loops.  This is the
hot path SURVEY.md §3.3 flags (O(replicas × windows × metrics)).
"""

from __future__ import annotations

import enum
import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, Hashable, List, Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.common.exceptions import NotEnoughValidWindowsError
from cruise_control_tpu.monitor.metric_def import MetricDef, ValueComputingStrategy

LOG = logging.getLogger(__name__)


class Extrapolation(enum.Enum):
    """Reference: core Extrapolation.java."""

    NONE = "none"                    # enough real samples
    AVG_AVAILABLE = "avg_available"  # some samples, fewer than required
    AVG_ADJACENT = "avg_adjacent"    # no samples; both neighbors usable
    FORECAST = "forecast"            # no samples; linear fit over history
    NO_VALID_EXTRAPOLATION = "none_valid"


@dataclass(frozen=True)
class AggregationOptions:
    """Reference: core AggregationOptions.java."""

    min_valid_entity_ratio: float = 0.0
    min_valid_entity_group_ratio: float = 0.0
    min_valid_windows: int = 1
    # Entities that must be valid regardless of ratio (include_all_topics).
    interested_entities: Optional[frozenset] = None
    # ENTITY: each entity stands alone; ENTITY_GROUP: a group (topic) is
    # invalid if any member is.
    group_granularity: bool = False


@dataclass
class MetricSampleCompleteness:
    valid_entity_ratio: float
    valid_entity_group_ratio: float
    valid_windows: List[int]
    num_entities: int
    num_valid_entities: int
    generation: int = 0
    # Valid entities that needed extrapolation for at least one window
    # (Sensors.md num-partitions-with-extrapolations).
    num_valid_entities_with_extrapolations: int = 0
    # Fidelity-fingerprint accounting over VALID entities only (the windows
    # that actually enter a model): total entity-windows considered and the
    # extrapolated ones by kind.  Defaulted so bare construction on the
    # not-enough-windows fallback path stays valid.
    num_entity_windows: int = 0
    num_windows_avg_available: int = 0
    num_windows_avg_adjacent: int = 0
    num_windows_forecast: int = 0


@dataclass
class ValuesAndExtrapolations:
    """Per-entity aggregation output: f32[M, W] + per-window extrapolations."""

    values: np.ndarray                       # f32[M, W]
    extrapolations: Dict[int, Extrapolation]  # window-list index -> kind
    windows: List[int]                        # absolute window indices (ms-based)


@dataclass
class AggregationResult:
    values_and_extrapolations: Dict[Hashable, ValuesAndExtrapolations]
    completeness: MetricSampleCompleteness


class MetricSampleAggregator:
    """Dense windowed aggregator over a dynamic entity population."""

    def __init__(
        self,
        metric_def: MetricDef,
        num_windows: int = 5,
        window_ms: int = 300_000,
        min_samples_per_window: int = 3,
        max_allowed_extrapolations_per_entity: int = 5,
        initial_capacity: int = 1024,
        group_of=None,
    ):
        self.metric_def = metric_def
        self.num_windows = num_windows
        self.window_ms = window_ms
        self.min_samples = max(min_samples_per_window, 1)
        self.max_extrapolations = max_allowed_extrapolations_per_entity
        self._group_of = group_of or (lambda e: e)
        self._lock = threading.RLock()

        m = metric_def.size
        self._slots = num_windows + 1
        cap = max(initial_capacity, 16)
        self._values = np.zeros((cap, self._slots, m), dtype=np.float64)
        self._counts = np.zeros((cap, self._slots), dtype=np.int32)
        self._times = np.full((cap, self._slots), -np.inf)
        self._slot_window = np.full(self._slots, -1, dtype=np.int64)  # abs window per slot
        self._entity_index: Dict[Hashable, int] = {}
        self._entities: List[Hashable] = []
        self._current_window = -1
        self._first_window = -1
        self._generation = 0
        strat = metric_def.strategy_vector()
        self._avg_mask = strat == 0
        self._max_mask = strat == 1
        self._latest_mask = strat == 2

    # ------------------------------------------------------------- plumbing

    @property
    def generation(self) -> int:
        return self._generation

    @property
    def current_window(self) -> int:
        """Absolute index of the active window; -1 before the first sample.
        Callers (the task runner's window-close detector) compare this
        across an ingest to see which windows just committed."""
        return self._current_window

    def _ensure_entity(self, entity: Hashable) -> int:
        idx = self._entity_index.get(entity)
        if idx is None:
            idx = len(self._entities)
            if idx >= self._values.shape[0]:
                grow = self._values.shape[0]
                self._values = np.concatenate(
                    [self._values, np.zeros_like(self._values)], axis=0)
                self._counts = np.concatenate(
                    [self._counts, np.zeros_like(self._counts)], axis=0)
                self._times = np.concatenate(
                    [self._times, np.full((grow, self._slots), -np.inf)], axis=0)
            self._entity_index[entity] = idx
            self._entities.append(entity)
        return idx

    def _roll_to(self, window: int) -> None:
        """Advance the active window, clearing reused ring slots."""
        if self._current_window < 0:
            self._current_window = window
            self._first_window = window
            self._slot_window[window % self._slots] = window
            return
        if window - self._current_window >= self._slots:
            # Time jumped past the whole ring — wipe everything.
            self._values[:] = 0.0
            self._counts[:] = 0
            self._times[:] = -np.inf
            self._slot_window[:] = [window - (window % self._slots - s) % self._slots
                                    for s in range(self._slots)]
        else:
            for w in range(self._current_window + 1, window + 1):
                s = w % self._slots
                self._values[:, s, :] = 0.0
                self._counts[:, s] = 0
                self._times[:, s] = -np.inf
                self._slot_window[s] = w
        self._current_window = max(self._current_window, window)

    # ----------------------------------------------------------------- adds

    def add_sample(self, entity: Hashable, time_ms: float,
                   metrics: np.ndarray) -> bool:
        return self.add_samples([entity], np.array([time_ms]),
                                np.asarray(metrics)[None, :]) == 1

    def add_samples(self, entities: Sequence[Hashable], times_ms: np.ndarray,
                    metrics: np.ndarray) -> int:
        """Vectorized multi-sample ingest; returns #accepted.

        Samples older than the retained window range are dropped (reference:
        addSample rejects windows that already rolled out).
        """
        with self._lock:
            windows = (np.asarray(times_ms, dtype=np.int64) // self.window_ms)
            first_ingest = self._current_window < 0
            # Windows strictly below the PRE-roll active window were already
            # closed when this batch arrived — out-of-order arrivals that
            # would otherwise scatter into committed (or recycled) window
            # buffers.  Dropped with a counter + debug log; a batch spanning
            # several windows (including the one it advances past) is fine.
            closed_before = self._current_window
            newest = int(windows.max(initial=self._current_window))
            if newest > self._current_window:
                self._roll_to(newest)
            oldest_kept = self._current_window - self.num_windows
            ok = windows >= max(oldest_kept, 0)
            if not first_ingest:
                late = windows < closed_before
                if late.any():
                    n_late = int(late.sum())
                    LOG.debug(
                        "dropping %d out-of-order sample(s) for already-"
                        "closed windows (< %d)", n_late, closed_before)
                    from cruise_control_tpu.obsvc.fidelity import fidelity
                    fidelity().on_dropped("out_of_order", n_late)
                    ok &= ~late
            if not ok.any():
                return 0
            # Track the oldest window that ever ACCEPTED a sample: a batched
            # first ingest must count from its oldest window, not the newest
            # one _roll_to saw (later batches can no longer backfill closed
            # windows — the out-of-order drop above rejects them).
            accepted_oldest = int(windows[ok].min())
            if first_ingest or accepted_oldest < self._first_window:
                self._first_window = max(accepted_oldest, 0)
            idx = np.fromiter((self._ensure_entity(e) for e in entities),
                              dtype=np.int64, count=len(entities))[ok]
            slots = (windows % self._slots)[ok]
            vals = np.asarray(metrics, dtype=np.float64)[ok]
            t = np.asarray(times_ms, dtype=np.float64)[ok]

            # NB: ufunc.at must target the real array — boolean fancy indexing
            # first would scatter into a copy.
            if self._avg_mask.any():
                cols = np.nonzero(self._avg_mask)[0]
                np.add.at(self._values,
                          (idx[:, None], slots[:, None], cols[None, :]),
                          vals[:, self._avg_mask])
            if self._max_mask.any():
                cols = np.nonzero(self._max_mask)[0]
                np.maximum.at(self._values,
                              (idx[:, None], slots[:, None], cols[None, :]),
                              vals[:, self._max_mask])
            if self._latest_mask.any():
                order = np.argsort(t, kind="stable")  # last write = newest
                newer = t[order] >= self._times[idx[order], slots[order]]
                io, so = idx[order][newer], slots[order][newer]
                self._values[io[:, None], so[:, None],
                             np.nonzero(self._latest_mask)[0][None, :]] = \
                    vals[order][newer][:, self._latest_mask]
            np.add.at(self._counts, (idx, slots), 1)
            np.maximum.at(self._times, (idx, slots), t)
            self._generation += 1
            return int(ok.sum())

    # ------------------------------------------------------------ aggregate

    def _window_range(self, from_ms: float, to_ms: float) -> List[int]:
        """Completed windows intersecting [from, to] (active one excluded)."""
        if self._current_window < 0:
            return []
        lo = 0 if from_ms == -np.inf else int(from_ms // self.window_ms)
        hi = (self._current_window if to_ms == np.inf
              else int(to_ms // self.window_ms))
        # Clamp to the first-observed window: with absolute epoch window
        # indices the ring "positions" before the first sample never existed,
        # so they must not count as (trivially-valid) completed windows.
        oldest = max(self._current_window - self.num_windows,
                     self._first_window, 0)
        start = max(lo, oldest)
        end = min(hi, self._current_window - 1)
        return list(range(start, end + 1))

    def _entity_window_planes(self, windows: List[int]):
        """(per-window collapsed values f32[E, W, M], counts i32[E, W])."""
        slots = [w % self._slots for w in windows]
        e_n = len(self._entities)
        vals = self._values[:e_n][:, slots, :].copy()
        counts = self._counts[:e_n][:, slots]
        if self._avg_mask.any():
            denom = np.maximum(counts, 1)[:, :, None]
            vals[:, :, self._avg_mask] = vals[:, :, self._avg_mask] / denom
        return vals, counts

    def aggregate(self, from_ms: float, to_ms: float,
                  options: Optional[AggregationOptions] = None) -> AggregationResult:
        """Reference: MetricSampleAggregator.aggregate :193-240."""
        options = options or AggregationOptions()
        with self._lock:
            windows = self._window_range(from_ms, to_ms)
            if len(windows) < options.min_valid_windows:
                raise NotEnoughValidWindowsError(
                    f"{len(windows)} completed windows in range, "
                    f"need {options.min_valid_windows}")
            vals, counts = self._entity_window_planes(windows)
            e_n, w_n, m = vals.shape

            # --- validity & extrapolation per entity-window --------------
            full = counts >= self.min_samples                       # [E, W]
            some = (counts > 0) & ~full                             # AVG_AVAILABLE
            empty = counts == 0
            # AVG_ADJACENT: both neighbors (within selection) have samples.
            left = np.roll(counts, 1, axis=1) > 0
            left[:, 0] = False
            right = np.roll(counts, -1, axis=1) > 0
            right[:, -1] = False
            adjacent = empty & left & right
            # FORECAST: any earlier window with samples.
            has_prior = np.cumsum(counts, axis=1) - counts > 0
            forecast = empty & ~adjacent & has_prior
            invalid = empty & ~adjacent & ~forecast

            # Fill AVG_ADJACENT values: mean of neighbors.
            if adjacent.any():
                lv = np.roll(vals, 1, axis=1)
                rv = np.roll(vals, -1, axis=1)
                fill = (lv + rv) / 2.0
                vals = np.where(adjacent[:, :, None], fill, vals)
            # Fill FORECAST values: weighted linear fit over the most recent
            # prior non-empty windows (reference RawMetricValues FORECAST —
            # least-squares over up to 5 earlier windows), vectorized with
            # prefix sums restricted to the entities that need it.  A single
            # prior point degenerates to carry-forward (slope 0).
            if forecast.any():
                rows = np.nonzero(forecast.any(axis=1))[0]
                v = vals[rows].astype(np.float64)            # [E', W, M]
                nonempty = counts[rows] > 0                  # [E', W]
                x = np.arange(w_n, dtype=np.float64)[None, :]
                xm = np.where(nonempty, x, 0.0)
                nm = nonempty.astype(np.float64)
                ym = np.where(nonempty[:, :, None], v, 0.0)

                def last5_prior(a):
                    """Sum of a over the 5 windows preceding each w."""
                    pad_shape = (a.shape[0], 1) + a.shape[2:]
                    cum = np.concatenate(
                        [np.zeros(pad_shape, a.dtype), np.cumsum(a, axis=1)],
                        axis=1)                              # cum[:, w] = sum < w
                    lo = np.maximum(np.arange(w_n) - 5, 0)
                    return cum[:, np.arange(w_n)] - cum[:, lo]

                n_p = last5_prior(nm)                        # [E', W]
                sx_p = last5_prior(xm)
                sxx_p = last5_prior(xm * xm)
                sy_p = last5_prior(ym)                       # [E', W, M]
                sxy_p = last5_prior(xm[:, :, None] * ym)
                denom = n_p * sxx_p - sx_p ** 2              # [E', W]
                safe = np.maximum(denom, 1e-12)[:, :, None]
                slope = np.where((denom > 1e-12)[:, :, None],
                                 (n_p[:, :, None] * sxy_p
                                  - sx_p[:, :, None] * sy_p) / safe, 0.0)
                n_safe = np.maximum(n_p, 1.0)[:, :, None]
                intercept = (sy_p - slope * sx_p[:, :, None]) / n_safe
                pred = np.maximum(intercept + slope * x[:, :, None], 0.0)
                # Classification (has_prior) looks back unboundedly; when the
                # nearest non-empty window is >5 back (n_p == 0) the fit has
                # no points — fall back to carrying the last value forward.
                carried = v.copy()
                seen = nonempty.copy()
                for w in range(1, w_n):
                    need = ~seen[:, w]
                    carried[need, w, :] = carried[need, w - 1, :]
                    seen[:, w] |= seen[:, w - 1]
                pred = np.where((n_p > 0)[:, :, None], pred, carried)
                sel = forecast[rows][:, :, None]
                vals[rows] = np.where(sel, pred, vals[rows])

            num_extrapolated = (some | adjacent | forecast).sum(axis=1)
            entity_valid = (~invalid).all(axis=1) & (
                num_extrapolated <= self.max_extrapolations)
            # By-kind extrapolation counts over VALID entities (the windows
            # that actually enter a model) — fidelity-fingerprint inputs.
            valid_rows = entity_valid[:, None]
            n_avg_available = int((some & valid_rows).sum())
            n_avg_adjacent = int((adjacent & valid_rows).sum())
            n_forecast = int((forecast & valid_rows).sum())

            # --- completeness --------------------------------------------
            groups: Dict[Hashable, bool] = {}
            for i, e in enumerate(self._entities):
                g = self._group_of(e)
                groups[g] = groups.get(g, True) and bool(entity_valid[i])
            ratio = float(entity_valid.sum()) / max(e_n, 1)
            gratio = (sum(groups.values()) / max(len(groups), 1)) if groups else 0.0
            completeness = MetricSampleCompleteness(
                valid_entity_ratio=ratio, valid_entity_group_ratio=gratio,
                valid_windows=windows, num_entities=e_n,
                num_valid_entities=int(entity_valid.sum()),
                generation=self._generation,
                num_valid_entities_with_extrapolations=int(
                    (entity_valid & (num_extrapolated > 0)).sum()),
                num_entity_windows=int(entity_valid.sum()) * w_n,
                num_windows_avg_available=n_avg_available,
                num_windows_avg_adjacent=n_avg_adjacent,
                num_windows_forecast=n_forecast)
            if ratio < options.min_valid_entity_ratio:
                raise NotEnoughValidWindowsError(
                    f"valid entity ratio {ratio:.3f} < "
                    f"{options.min_valid_entity_ratio}")
            if gratio < options.min_valid_entity_group_ratio:
                raise NotEnoughValidWindowsError(
                    f"valid group ratio {gratio:.3f} < "
                    f"{options.min_valid_entity_group_ratio}")

            out: Dict[Hashable, ValuesAndExtrapolations] = {}
            interested = options.interested_entities
            for i, e in enumerate(self._entities):
                if not entity_valid[i]:
                    continue
                if interested is not None and e not in interested:
                    continue
                ext: Dict[int, Extrapolation] = {}
                for w in range(w_n):
                    if some[i, w]:
                        ext[w] = Extrapolation.AVG_AVAILABLE
                    elif adjacent[i, w]:
                        ext[w] = Extrapolation.AVG_ADJACENT
                    elif forecast[i, w]:
                        ext[w] = Extrapolation.FORECAST
                out[e] = ValuesAndExtrapolations(
                    values=vals[i].T.astype(np.float32), extrapolations=ext,
                    windows=list(windows))
            return AggregationResult(values_and_extrapolations=out,
                                     completeness=completeness)

    def completeness(self, from_ms: float, to_ms: float,
                     options: Optional[AggregationOptions] = None
                     ) -> MetricSampleCompleteness:
        try:
            return self.aggregate(from_ms, to_ms, options).completeness
        except NotEnoughValidWindowsError:
            return MetricSampleCompleteness(
                valid_entity_ratio=0.0, valid_entity_group_ratio=0.0,
                valid_windows=[], num_entities=len(self._entities),
                num_valid_entities=0, generation=self._generation)

    # -------------------------------------------------------------- queries

    def all_entities(self) -> List[Hashable]:
        with self._lock:
            return list(self._entities)

    def num_available_windows(self) -> int:
        """Completed windows observed since the first sample (the window index
        is absolute ``time_ms // window_ms``, so count from the first-observed
        window, not from zero)."""
        with self._lock:
            if self._current_window < 0:
                return 0
            return min(self.num_windows, self._current_window - self._first_window)

    def retain_entities(self, keep) -> None:
        """Drop entities not in ``keep`` (topology change cleanup)."""
        with self._lock:
            keep_idx = [i for i, e in enumerate(self._entities) if e in keep]
            if len(keep_idx) == len(self._entities):
                return
            sel = np.asarray(keep_idx, dtype=np.int64)
            e_new = [self._entities[i] for i in keep_idx]
            n = self._values.shape[0]
            new_vals = np.zeros_like(self._values)
            new_counts = np.zeros_like(self._counts)
            new_times = np.full_like(self._times, -np.inf)
            new_vals[:len(sel)] = self._values[sel]
            new_counts[:len(sel)] = self._counts[sel]
            new_times[:len(sel)] = self._times[sel]
            self._values, self._counts, self._times = new_vals, new_counts, new_times
            self._entities = e_new
            self._entity_index = {e: i for i, e in enumerate(e_new)}
            self._generation += 1
