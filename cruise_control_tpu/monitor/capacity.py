"""Broker capacity resolution.

Reference: ``config/BrokerCapacityConfigResolver.java`` SPI and
``config/BrokerCapacityConfigFileResolver.java`` (JSON file with per-broker
overrides, JBOD logdir capacities, num cores; broker id -1 is the default
entry; capacities may be flagged as estimated).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource

DEFAULT_CAPACITY_BROKER_ID = -1


@dataclass
class BrokerCapacityInfo:
    capacity: np.ndarray                     # f64[4]
    disk_capacities: Optional[List[float]] = None   # JBOD logdirs
    num_cores: int = 1
    estimated: bool = False
    estimation_info: str = ""


class BrokerCapacityConfigResolver(Protocol):
    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo: ...


class FixedBrokerCapacityResolver:
    """Same capacity for every broker (tests / homogeneous clusters)."""

    def __init__(self, capacity: Dict[Resource, float],
                 disk_capacities: Optional[List[float]] = None,
                 num_cores: int = 1):
        arr = np.zeros(NUM_RESOURCES)
        for k, v in capacity.items():
            arr[int(k)] = v
        self._info = BrokerCapacityInfo(capacity=arr,
                                        disk_capacities=disk_capacities,
                                        num_cores=num_cores)

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        return self._info


class BrokerCapacityConfigFileResolver:
    """JSON-file resolver (BrokerCapacityConfigFileResolver.java:1-333).

    File schema (mirrors the reference's capacity.json family)::

        {"brokerCapacities": [
           {"brokerId": -1, "capacity": {"CPU": "100", "NW_IN": "...",
            "NW_OUT": "...", "DISK": "..."}},                       # default
           {"brokerId": 0,  "capacity": {"DISK": {"/mnt/i01": "250000",
            "/mnt/i02": "250000"}, ...}, "numCores": 8},            # override
        ]}
    """

    _KEYS = {"CPU": Resource.CPU, "NW_IN": Resource.NW_IN,
             "NW_OUT": Resource.NW_OUT, "DISK": Resource.DISK}

    def __init__(self, path: str):
        with open(path) as f:
            doc = json.load(f)
        self._by_broker: Dict[int, BrokerCapacityInfo] = {}
        for entry in doc.get("brokerCapacities", []):
            bid = int(entry["brokerId"])
            cap = np.zeros(NUM_RESOURCES)
            disks: Optional[List[float]] = None
            for key, val in entry.get("capacity", {}).items():
                res = self._KEYS[key]
                if isinstance(val, dict):   # JBOD: logdir -> capacity
                    disks = [float(v) for v in val.values()]
                    cap[int(res)] = sum(disks)
                else:
                    cap[int(res)] = float(val)
            self._by_broker[bid] = BrokerCapacityInfo(
                capacity=cap, disk_capacities=disks,
                num_cores=int(entry.get("numCores", 1)),
                estimated=bid == DEFAULT_CAPACITY_BROKER_ID,
                estimation_info=("default capacity entry"
                                 if bid == DEFAULT_CAPACITY_BROKER_ID else ""))
        if DEFAULT_CAPACITY_BROKER_ID not in self._by_broker:
            raise ValueError(
                f"capacity config must define the default entry "
                f"(brokerId={DEFAULT_CAPACITY_BROKER_ID})")

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        info = self._by_broker.get(broker_id)
        if info is not None:
            return info
        default = self._by_broker[DEFAULT_CAPACITY_BROKER_ID]
        if not allow_estimation:
            raise ValueError(
                f"no explicit capacity for broker {broker_id} and "
                "estimation is disallowed")
        return default
