"""Broker capacity resolution.

Reference: ``config/BrokerCapacityConfigResolver.java`` SPI and
``config/BrokerCapacityConfigFileResolver.java`` (JSON file with per-broker
overrides, JBOD logdir capacities, num cores; broker id -1 is the default
entry; capacities may be flagged as estimated).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource

DEFAULT_CAPACITY_BROKER_ID = -1


@dataclass
class BrokerCapacityInfo:
    capacity: np.ndarray                     # f64[4]
    disk_capacities: Optional[List[float]] = None   # JBOD logdirs
    num_cores: int = 1
    estimated: bool = False
    estimation_info: str = ""


class BrokerCapacityConfigResolver(Protocol):
    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo: ...


class FixedBrokerCapacityResolver:
    """Same capacity for every broker (tests / homogeneous clusters)."""

    def __init__(self, capacity: Dict[Resource, float],
                 disk_capacities: Optional[List[float]] = None,
                 num_cores: int = 1):
        arr = np.zeros(NUM_RESOURCES)
        for k, v in capacity.items():
            arr[int(k)] = v
        self._info = BrokerCapacityInfo(capacity=arr,
                                        disk_capacities=disk_capacities,
                                        num_cores=num_cores)

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        return self._info


class BrokerCapacityConfigFileResolver:
    """JSON-file resolver (BrokerCapacityConfigFileResolver.java:1-333).

    File schema (mirrors the reference's capacity.json family)::

        {"brokerCapacities": [
           {"brokerId": -1, "capacity": {"CPU": "100", "NW_IN": "...",
            "NW_OUT": "...", "DISK": "..."}},                       # default
           {"brokerId": 0,  "capacity": {"DISK": {"/mnt/i01": "250000",
            "/mnt/i02": "250000"}, ...}, "numCores": 8},            # override
        ]}
    """

    _KEYS = {"CPU": Resource.CPU, "NW_IN": Resource.NW_IN,
             "NW_OUT": Resource.NW_OUT, "DISK": Resource.DISK}

    def __init__(self, path: str):
        with open(path) as f:
            doc = json.load(f)
        self._by_broker: Dict[int, BrokerCapacityInfo] = {}
        for entry in doc.get("brokerCapacities", []):
            bid = int(entry["brokerId"])
            cap = np.zeros(NUM_RESOURCES)
            disks: Optional[List[float]] = None
            for key, val in entry.get("capacity", {}).items():
                res = self._KEYS[key]
                if isinstance(val, dict):   # JBOD: logdir -> capacity
                    disks = [float(v) for v in val.values()]
                    cap[int(res)] = sum(disks)
                else:
                    cap[int(res)] = float(val)
            self._by_broker[bid] = BrokerCapacityInfo(
                capacity=cap, disk_capacities=disks,
                num_cores=int(entry.get("numCores", 1)),
                estimated=bid == DEFAULT_CAPACITY_BROKER_ID,
                estimation_info=("default capacity entry"
                                 if bid == DEFAULT_CAPACITY_BROKER_ID else ""))
        if DEFAULT_CAPACITY_BROKER_ID not in self._by_broker:
            raise ValueError(
                f"capacity config must define the default entry "
                f"(brokerId={DEFAULT_CAPACITY_BROKER_ID})")

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        info = self._by_broker.get(broker_id)
        if info is not None:
            return info
        default = self._by_broker[DEFAULT_CAPACITY_BROKER_ID]
        if not allow_estimation:
            raise ValueError(
                f"no explicit capacity for broker {broker_id} and "
                "estimation is disallowed")
        return default


class BrokerEnvCapacityResolver:
    """Environment-variable resolver (the reference's
    ``BrokerCapacityResolver`` provider family: capacity from deployment env
    rather than a file — e.g. containerized brokers exporting
    ``BROKER_CPU_CAPACITY``/``BROKER_NW_IN_CAPACITY``/... at startup)."""

    _ENV_KEYS = {"BROKER_CPU_CAPACITY": Resource.CPU,
                 "BROKER_NW_IN_CAPACITY": Resource.NW_IN,
                 "BROKER_NW_OUT_CAPACITY": Resource.NW_OUT,
                 "BROKER_DISK_CAPACITY": Resource.DISK}

    def __init__(self, env: Optional[Dict[str, str]] = None):
        import os
        env = dict(os.environ if env is None else env)
        cap = np.zeros(NUM_RESOURCES)
        missing = []
        for key, res in self._ENV_KEYS.items():
            if key in env:
                cap[int(res)] = float(env[key])
            else:
                missing.append(key)
        if missing:
            raise ValueError(f"missing capacity env vars: {missing}")
        self._info = BrokerCapacityInfo(capacity=cap, disk_capacities=None,
                                        num_cores=int(env.get("BROKER_NUM_CORES", 1)))

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        return self._info


class TopicConfigDiskCapacityResolver:
    """Per-broker disk capacity learned from the cluster's own reported
    log-dir sizes plus a headroom factor (the reference's topic-config
    provider family: capacity derived from the managed system's metadata
    instead of static config).  Non-disk resources fall back to a base
    resolver."""

    def __init__(self, base: BrokerCapacityConfigResolver,
                 observed_disk_by_broker: Dict[int, float],
                 headroom_factor: float = 1.25):
        self.base = base
        self.observed = dict(observed_disk_by_broker)
        self.headroom = headroom_factor

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        info = self.base.capacity_for_broker(rack, host, broker_id,
                                             allow_estimation)
        observed = self.observed.get(broker_id)
        if observed is None or not allow_estimation:
            # Observed-usage capacity IS an estimation — honor the caller's
            # allow_estimation=False by returning only configured values.
            return info
        cap = np.array(info.capacity, copy=True)
        target = max(cap[int(Resource.DISK)], observed * self.headroom)
        disks = info.disk_capacities
        if disks is not None and cap[int(Resource.DISK)] > 0:
            # JBOD: the model derives broker DISK from the per-logdir sum,
            # so the raise must be applied to the logdirs proportionally.
            scale = target / cap[int(Resource.DISK)]
            disks = [d * scale for d in disks]
        cap[int(Resource.DISK)] = target
        return BrokerCapacityInfo(capacity=cap,
                                  disk_capacities=disks,
                                  num_cores=info.num_cores,
                                  estimated=True,
                                  estimation_info="observed disk + headroom")
