"""LoadMonitor: samples + metadata → frozen cluster snapshots on demand.

Reference: ``monitor/LoadMonitor.java:78-796`` — wiring of aggregators,
metadata client and capacity resolver (ctor :124-191), the
``clusterModel(from, to, requirements, …)`` path :530-582 (aggregate →
populate capacities :477-514 → per-partition load population via
``MonitorUtils.populatePartitionLoad`` :382-447), completeness gating
:630-643, and the fair semaphore bounding concurrent model generations
:378-389.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from cruise_control_tpu.common.exceptions import NotEnoughValidWindowsError
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.builder import ClusterModel
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement
from cruise_control_tpu.monitor import metric_def as md
from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions,
    MetricSampleAggregator,
    MetricSampleCompleteness,
)
from cruise_control_tpu.monitor.capacity import (
    BrokerCapacityConfigResolver,
    FixedBrokerCapacityResolver,
)
from cruise_control_tpu.monitor.metadata import ClusterMetadata, MetadataClient


@dataclass(frozen=True)
class ModelCompletenessRequirements:
    """Reference: monitor/ModelCompletenessRequirements.java."""

    min_required_num_windows: int = 1
    min_monitored_partitions_percentage: float = 0.0
    include_all_topics: bool = False

    def stronger(self, other: "ModelCompletenessRequirements"
                 ) -> "ModelCompletenessRequirements":
        return ModelCompletenessRequirements(
            max(self.min_required_num_windows, other.min_required_num_windows),
            max(self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage),
            self.include_all_topics or other.include_all_topics)


@dataclass
class LoadMonitorState:
    state: str
    num_valid_windows: int
    monitored_partitions_percentage: float
    total_num_partitions: int
    generation: int

    def to_dict(self) -> Dict:
        return {
            "state": self.state,
            "numValidWindows": self.num_valid_windows,
            "monitoredPartitionsPercentage":
                round(self.monitored_partitions_percentage * 100.0, 3),
            "totalNumPartitions": self.total_num_partitions,
            "generation": self.generation,
        }


class LoadMonitor:
    """Turns windowed samples + metadata into analyzer-ready snapshots."""

    def __init__(
        self,
        metadata_client: MetadataClient,
        capacity_resolver: Optional[BrokerCapacityConfigResolver] = None,
        num_windows: int = 5,
        window_ms: int = 300_000,
        min_samples_per_window: int = 1,
        max_concurrent_model_generations: int = 2,
        num_broker_windows: int = 20,
        broker_window_ms: Optional[int] = None,
    ):
        self.metadata_client = metadata_client
        self.capacity_resolver = capacity_resolver or FixedBrokerCapacityResolver(
            {Resource.CPU: 100.0, Resource.NW_IN: 300_000.0,
             Resource.NW_OUT: 200_000.0, Resource.DISK: 300_000.0})
        self.partition_aggregator = MetricSampleAggregator(
            md.COMMON_METRIC_DEF, num_windows=num_windows, window_ms=window_ms,
            min_samples_per_window=min_samples_per_window,
            group_of=lambda e: e[0])     # group = topic
        self.broker_aggregator = MetricSampleAggregator(
            md.BROKER_METRIC_DEF, num_windows=num_broker_windows,
            window_ms=broker_window_ms or window_ms,
            min_samples_per_window=min_samples_per_window)
        # Fair semaphore bounding concurrent model generations (:163-166).
        self._model_semaphore = threading.BoundedSemaphore(
            max_concurrent_model_generations)
        self._resource_matrix = md.COMMON_METRIC_DEF.resource_matrix()
        # Resident-builder bookkeeping (resident_model_builder): one kept
        # ClusterModel that is *updated in place* between requests so the
        # resident model service can ingest deltas instead of re-freezing.
        self._resident_builder: Optional[ClusterModel] = None
        self._resident_fp = None
        self._resident_loads: Dict[Tuple[str, int], np.ndarray] = {}
        self._resident_alive: Dict[int, bool] = {}
        self._register_sensors()

    def _register_sensors(self) -> None:
        """LoadMonitor sensors (Sensors.md: valid-windows,
        total-monitored-windows, monitored-partitions-percentage, num-topics,
        num-partitions-with-extrapolations, cluster-model-creation-timer).

        Completeness runs one full aggregation pass — a scrape samples five
        gauges, so the result is cached for a few seconds instead of being
        recomputed per gauge."""
        from cruise_control_tpu.common.metrics import registry
        reg = registry()
        cache = {"at": 0.0, "value": None}
        cache_lock = threading.Lock()

        def completeness():
            now = time.monotonic()
            with cache_lock:
                if cache["value"] is None or now - cache["at"] > 5.0:
                    cache["value"] = self.partition_aggregator.completeness(
                        -float("inf"), time.time() * 1000)
                    cache["at"] = now
                return cache["value"]

        reg.gauge("LoadMonitor.valid-windows",
                  lambda: len(completeness().valid_windows))
        reg.gauge("LoadMonitor.total-monitored-windows",
                  lambda: self.partition_aggregator.num_available_windows())
        reg.gauge("LoadMonitor.monitored-partitions-percentage",
                  lambda: round(completeness().valid_entity_ratio * 100.0, 3))
        reg.gauge("LoadMonitor.num-valid-partitions",
                  lambda: completeness().num_valid_entities)
        reg.gauge("LoadMonitor.num-partitions-with-extrapolations",
                  lambda: completeness().num_valid_entities_with_extrapolations)
        reg.gauge("LoadMonitor.num-topics",
                  lambda: len({p.topic for p in
                               self.metadata_client.cluster().partitions}))
        self._model_timer = reg.timer("LoadMonitor.cluster-model-creation-timer")

    def _record_fingerprint(self, metadata: ClusterMetadata, completeness,
                            kind: str) -> None:
        """Fidelity observatory: stamp one ModelFingerprint per model
        freeze / resident delta-apply (host-side bookkeeping over the
        completeness output — never touches solver inputs)."""
        from cruise_control_tpu.obsvc.fidelity import fidelity
        fid = fidelity()
        if not fid.enabled:
            return
        fid.record_fingerprint(
            completeness,
            window_ms=self.partition_aggregator.window_ms,
            dead_brokers=[b.broker_id for b in metadata.brokers
                          if not b.alive],
            capacity_source=type(self.capacity_resolver).__name__,
            kind=kind)

    # ---------------------------------------------------------- generation

    @property
    def model_generation(self) -> Tuple[int, int]:
        return (self.metadata_client.generation, self.partition_aggregator.generation)

    def acquire_for_model_generation(self):
        """Context manager bounding concurrent snapshot builds."""
        sem = self._model_semaphore

        class _Ctx:
            def __enter__(self):
                sem.acquire()
                return self

            def __exit__(self, *exc):
                sem.release()
                return False

        return _Ctx()

    # -------------------------------------------------------- completeness

    def meet_completeness_requirements(
            self, requirements: ModelCompletenessRequirements) -> bool:
        """Reference: LoadMonitor.meetCompletenessRequirements :630-643."""
        now = time.time() * 1000
        completeness = self.partition_aggregator.completeness(-float("inf"), now)
        if len(completeness.valid_windows) < requirements.min_required_num_windows:
            return False
        return (completeness.valid_entity_ratio
                >= requirements.min_monitored_partitions_percentage)

    def monitored_partitions_percentage(self) -> float:
        now = time.time() * 1000
        completeness = self.partition_aggregator.completeness(-float("inf"), now)
        return completeness.valid_entity_ratio

    # ------------------------------------------------------- cluster model

    def cluster_model(
        self,
        from_ms: float = -float("inf"),
        to_ms: Optional[float] = None,
        requirements: Optional[ModelCompletenessRequirements] = None,
        allow_capacity_estimation: bool = True,
        pad_replicas_to: int = 1,
        pad_brokers_to: int = 1,
        pad_fn=None,
    ) -> Tuple[ClusterState, Placement, ClusterMeta]:
        """Build a frozen snapshot (LoadMonitor.clusterModel :530-582).

        ``pad_fn(n_replicas, n_brokers) -> (pad_replicas_to, pad_brokers_to)``
        lets the caller pick pad targets from the RAW model counts — the
        compile service's shape-bucket policy needs the counts before the
        freeze, and only this method sees the populated model under the
        generation lock."""
        requirements = requirements or ModelCompletenessRequirements()
        to_ms = time.time() * 1000 if to_ms is None else to_ms
        with self.acquire_for_model_generation(), self._model_timer.time():
            metadata = self.metadata_client.refresh_metadata()
            options = AggregationOptions(
                min_valid_entity_ratio=requirements.min_monitored_partitions_percentage,
                min_valid_windows=requirements.min_required_num_windows,
                group_granularity=requirements.include_all_topics)
            result = self.partition_aggregator.aggregate(from_ms, to_ms, options)
            self._record_fingerprint(metadata, result.completeness, "freeze")
            cm = self._populate(metadata, result, allow_capacity_estimation)
            if pad_fn is not None:
                pad_replicas_to, pad_brokers_to = pad_fn(
                    sum(len(rs) for rs in cm.partitions().values()),
                    len(cm.brokers()))
            return cm.freeze(pad_replicas_to=pad_replicas_to,
                             pad_brokers_to=pad_brokers_to)

    def cluster_model_builder(self, *args, **kwargs) -> ClusterModel:
        """As above but returns the mutable builder (RF-change flows)."""
        requirements = kwargs.get("requirements") or ModelCompletenessRequirements()
        to_ms = time.time() * 1000
        metadata = self.metadata_client.refresh_metadata()
        options = AggregationOptions(
            min_valid_entity_ratio=requirements.min_monitored_partitions_percentage,
            min_valid_windows=requirements.min_required_num_windows)
        result = self.partition_aggregator.aggregate(-float("inf"), to_ms, options)
        self._record_fingerprint(metadata, result.completeness, "freeze")
        return self._populate(metadata, result,
                              kwargs.get("allow_capacity_estimation", True))

    # ------------------------------------------------------ resident builder

    def _metadata_fingerprint(self, metadata: ClusterMetadata,
                              allow_capacity_estimation: bool):
        """Structural identity of the cluster as _populate would build it.
        Order-sensitive on purpose: broker/partition iteration order decides
        dense indices, so a reordering is a different model.  Broker liveness
        is deliberately excluded — alive flips are expressible as deltas."""
        return (
            tuple((b.broker_id, b.rack, b.host) for b in metadata.brokers),
            tuple((p.topic, p.partition, p.leader, tuple(p.replicas))
                  for p in metadata.partitions),
            bool(allow_capacity_estimation),
        )

    def reset_resident_builder(self) -> None:
        """Drop the kept builder; the next resident request rebuilds fresh
        (used when out-of-band state the diff cannot see changed, e.g. the
        set of offline logdirs, or after a device failover)."""
        self._resident_builder = None

    def resident_model_builder(
        self,
        requirements: Optional[ModelCompletenessRequirements] = None,
        allow_capacity_estimation: bool = True,
    ) -> Tuple[ClusterModel, bool]:
        """Return ``(builder, fresh)`` where ``builder`` is the *kept*
        delta-tracking ClusterModel updated in place from the latest metadata
        + aggregates, and ``fresh`` says it was rebuilt from scratch (the
        structural fingerprint changed or no builder existed).

        The steady-state path touches only partitions whose aggregated load
        vector actually changed and brokers whose liveness flipped, so the
        builder's journal — and therefore the device delta — stays sparse.
        Callers must serialize calls (the facade holds the resident-service
        lock across update + snapshot).
        """
        requirements = requirements or ModelCompletenessRequirements()
        to_ms = time.time() * 1000
        metadata = self.metadata_client.refresh_metadata()
        options = AggregationOptions(
            min_valid_entity_ratio=requirements.min_monitored_partitions_percentage,
            min_valid_windows=requirements.min_required_num_windows)
        result = self.partition_aggregator.aggregate(-float("inf"), to_ms, options)
        fp = self._metadata_fingerprint(metadata, allow_capacity_estimation)
        self._record_fingerprint(
            metadata, result.completeness,
            "freeze" if (self._resident_builder is None
                         or fp != self._resident_fp) else "delta")
        if self._resident_builder is None or fp != self._resident_fp:
            cm = self._populate(metadata, result, allow_capacity_estimation)
            cm.enable_delta_tracking()
            self._resident_builder = cm
            self._resident_fp = fp
            self._resident_loads = self._partition_loads(metadata, result)
            self._resident_alive = {b.broker_id: bool(b.alive)
                                    for b in metadata.brokers}
            return cm, True

        cm = self._resident_builder
        loads = self._partition_loads(metadata, result)
        prev = self._resident_loads
        parts = cm.partitions()
        for tp, load in loads.items():
            pl = prev.get(tp)
            if pl is not None and np.array_equal(pl, load):
                continue
            for r in list(parts.get(tp, ())):
                cm.set_replica_load(tp[0], tp[1], r.broker_id, load)
        for tp in prev.keys() - loads.keys():
            # Partition dropped out of the monitored set: a fresh _populate
            # would leave its load at zero.
            zero = np.zeros_like(prev[tp])
            for r in list(parts.get(tp, ())):
                cm.set_replica_load(tp[0], tp[1], r.broker_id, zero)
        self._resident_loads = loads
        for b in metadata.brokers:
            if bool(b.alive) != self._resident_alive.get(b.broker_id, True):
                cm.set_broker_state(b.broker_id, alive=bool(b.alive))
                self._resident_alive[b.broker_id] = bool(b.alive)
        return cm, False

    def _partition_loads(self, metadata: ClusterMetadata, agg_result,
                         ) -> Dict[Tuple[str, int], np.ndarray]:
        """Per-partition aggregated load vectors (f64[4]) — the same numbers
        _populate assigns via set_replica_load."""
        values = agg_result.values_and_extrapolations
        mat = self._resource_matrix
        out: Dict[Tuple[str, int], np.ndarray] = {}
        for p in metadata.partitions:
            if not p.replicas:
                continue
            vae = values.get((p.topic, p.partition))
            if vae is None:
                continue
            per_metric = vae.values.mean(axis=1)       # f32[M]
            out[(p.topic, p.partition)] = mat @ per_metric
        return out

    def _populate(self, metadata: ClusterMetadata, agg_result,
                  allow_capacity_estimation: bool) -> ClusterModel:
        cm = ClusterModel()
        broker_info = {b.broker_id: b for b in metadata.brokers}
        for b in metadata.brokers:
            cap = self.capacity_resolver.capacity_for_broker(
                b.rack, b.host, b.broker_id,
                allow_estimation=allow_capacity_estimation)
            cm.create_broker(rack=b.rack, host=b.host, broker_id=b.broker_id,
                             capacity={r: float(cap.capacity[int(r)])
                                       for r in Resource},
                             disk_capacities=cap.disk_capacities)
        # Collapse windows per metric strategy then map to resources
        # (Load.expectedUtilizationFor :84-98 over the window axis); shared
        # with the resident diff path so both see identical numbers.
        loads = self._partition_loads(metadata, agg_result)
        for p in metadata.partitions:
            if not p.replicas:
                continue
            for i, broker_id in enumerate(p.replicas):
                if broker_id not in broker_info:
                    continue
                cm.create_replica(p.topic, p.partition, broker_id=broker_id,
                                  index=i, is_leader=(broker_id == p.leader))
            load = loads.get((p.topic, p.partition))
            if load is None:
                continue  # not monitored; include_all_topics gate decides upstream
            # Every replica gets the aggregated leader metrics (reference:
            # MonitorUtils.populatePartitionLoad :382-447 sets load per
            # replica); the two-role model derives the follower-role load via
            # effective_follower_load(), so followers are NOT zero.
            for r in cm.partition(p.topic, p.partition):
                cm.set_replica_load(p.topic, p.partition, r.broker_id, load)
        # Dead brokers last so offline flags land on populated replicas.
        for b in metadata.brokers:
            if not b.alive:
                cm.set_broker_state(b.broker_id, alive=False)
        return cm

    # ---------------------------------------------------------------- state

    def state(self, runner_state: str = "RUNNING") -> LoadMonitorState:
        now = time.time() * 1000
        completeness = self.partition_aggregator.completeness(-float("inf"), now)
        return LoadMonitorState(
            state=runner_state,
            num_valid_windows=len(completeness.valid_windows),
            monitored_partitions_percentage=completeness.valid_entity_ratio,
            total_num_partitions=completeness.num_entities,
            generation=self.partition_aggregator.generation,
        )
