"""Sampling scheduler & state machine.

Reference: ``monitor/task/LoadMonitorTaskRunner.java:33-353`` — states
{NOT_STARTED, RUNNING, SAMPLING, PAUSED, BOOTSTRAPPING, TRAINING, LOADING},
the periodic SamplingTask, bootstrap over a historical range (:134-184),
pause/resume (:281-311), and startup sample loading; plus the fetcher fan-out
of ``monitor/sampling/MetricFetcherManager.java:35-223`` collapsed into one
vectorized ingest (dense-array adds make per-partition fetch threads moot).
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Optional

from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.sampler import MetricSampler, SamplerResult
from cruise_control_tpu.monitor.sample_store import NoopSampleStore, SampleStore

LOG = logging.getLogger(__name__)


class RunnerState(enum.Enum):
    NOT_STARTED = "NOT_STARTED"
    RUNNING = "RUNNING"
    SAMPLING = "SAMPLING"
    PAUSED = "PAUSED"
    BOOTSTRAPPING = "BOOTSTRAPPING"
    TRAINING = "TRAINING"
    LOADING = "LOADING"


class LoadMonitorTaskRunner:
    def __init__(
        self,
        load_monitor: LoadMonitor,
        sampler: MetricSampler,
        sample_store: Optional[SampleStore] = None,
        sampling_interval_ms: int = 120_000,
        clock=time.time,
    ):
        self.load_monitor = load_monitor
        self.sampler = sampler
        self.sample_store = sample_store or NoopSampleStore()
        self.sampling_interval_s = sampling_interval_ms / 1000.0
        self._clock = clock
        self._state = RunnerState.NOT_STARTED
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._paused_reason: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._last_sampling_ms: float = 0.0
        # Optional broker-side reporter agents (metrics-reporter pipeline) —
        # started/stopped with the runner.
        self.reporters: list = []

    # ----------------------------------------------------------- lifecycle

    @property
    def state(self) -> RunnerState:
        with self._lock:
            return self._state

    def start(self, load_stored_samples: bool = True) -> None:
        with self._lock:
            if self._state is not RunnerState.NOT_STARTED:
                return
            self._state = RunnerState.LOADING
        if load_stored_samples:
            self._load_samples()
        with self._lock:
            if self._state is RunnerState.LOADING:
                self._state = RunnerState.RUNNING
        for reporter in self.reporters:
            reporter.start()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="sampling-task")
        self._thread.start()

    def shutdown(self) -> None:
        for reporter in self.reporters:
            reporter.stop()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.sample_store.close()

    def _load_samples(self) -> None:
        """SampleLoadingTask: replay the sample store into the aggregators."""
        lm = self.load_monitor

        def on_partition(s):
            lm.partition_aggregator.add_sample(s.entity, s.time_ms, s.metrics)

        def on_broker(s):
            lm.broker_aggregator.add_sample(s.entity, s.time_ms, s.metrics)

        self.sample_store.load_samples(on_partition, on_broker)

    # ------------------------------------------------------------ sampling

    def _loop(self) -> None:
        while not self._stop.wait(min(self.sampling_interval_s, 0.2)):
            now = self._clock() * 1000
            if now - self._last_sampling_ms < self.sampling_interval_ms_effective():
                continue
            try:
                self.run_sampling_once(now)
            except Exception:   # noqa: BLE001 — a transient fetch failure
                # (network-bound samplers: prometheus down, transport IO)
                # must not kill the sampling thread; skip the tick and retry.
                LOG.warning("sampling tick failed; will retry", exc_info=True)

    def sampling_interval_ms_effective(self) -> float:
        return self.sampling_interval_s * 1000.0

    def run_sampling_once(self, now_ms: Optional[float] = None) -> int:
        """One SamplingTask tick: fetch → ingest → persist."""
        with self._lock:
            if self._state not in (RunnerState.RUNNING,):
                return 0
            self._state = RunnerState.SAMPLING
        try:
            now_ms = self._clock() * 1000 if now_ms is None else now_ms
            start = self._last_sampling_ms or (now_ms - self.sampling_interval_s * 1000)
            metadata = self.load_monitor.metadata_client.refresh_metadata()
            # MetricFetcherManager sensors (Sensors.md): per-round fetch
            # timer + failure rate.
            from cruise_control_tpu.common.metrics import registry
            reg = registry()
            try:
                with reg.timer(
                        "MetricFetcherManager.partition-samples-fetcher-timer"
                ).time():
                    result = self.sampler.get_samples(metadata, start, now_ms)
            except Exception:
                reg.counter("MetricFetcherManager."
                            "partition-samples-fetcher-failure-rate").inc()
                raise
            # Fidelity observatory: per-fetch sample counts + broker-liveness
            # flap detection from the metadata this tick refreshed.
            from cruise_control_tpu.obsvc.fidelity import fidelity
            fid = fidelity()
            fid.on_fetch(len(result.partition_samples),
                         len(result.broker_samples))
            fid.record_liveness({b.broker_id: bool(b.alive)
                                 for b in metadata.brokers}, now_ms=now_ms)
            n = self._ingest(result)
            self._last_sampling_ms = now_ms
            return n
        finally:
            with self._lock:
                if self._state is RunnerState.SAMPLING:
                    self._state = RunnerState.RUNNING

    def _ingest(self, result: SamplerResult) -> int:
        import numpy as np

        lm = self.load_monitor
        n = 0
        before = lm.partition_aggregator.current_window
        if result.partition_samples:
            entities = [s.entity for s in result.partition_samples]
            times = np.array([s.time_ms for s in result.partition_samples])
            metrics = np.stack([s.metrics for s in result.partition_samples])
            n += lm.partition_aggregator.add_samples(entities, times, metrics)
        if result.broker_samples:
            entities = [s.entity for s in result.broker_samples]
            times = np.array([s.time_ms for s in result.broker_samples])
            metrics = np.stack([s.metrics for s in result.broker_samples])
            n += lm.broker_aggregator.add_samples(entities, times, metrics)
        self.sample_store.store_samples(result.partition_samples,
                                        result.broker_samples)
        # Window-close detection: any window the ingest rolled the active
        # pointer past just committed.  Bounded to the ring span so a clock
        # jump cannot emit an unbounded event burst.
        after = lm.partition_aggregator.current_window
        if before >= 0 and after > before:
            from cruise_control_tpu.obsvc.fidelity import fidelity
            window_ms = lm.partition_aggregator.window_ms
            span = lm.partition_aggregator.num_windows + 1
            for w in range(max(before, after - span), after):
                fidelity().on_window_close(w, window_ms)
        return n

    # ------------------------------------------------------------ bootstrap

    def bootstrap(self, start_ms: float, end_ms: float,
                  clear_metrics: bool = False) -> int:
        """Re-ingest a historical range (BootstrapTask.java:1-276)."""
        with self._lock:
            prev = self._state
            self._state = RunnerState.BOOTSTRAPPING
        try:
            n = 0
            window = self.load_monitor.partition_aggregator.window_ms
            t = start_ms
            metadata = self.load_monitor.metadata_client.refresh_metadata()
            while t < end_ms:
                result = self.sampler.get_samples(metadata, t, min(t + window, end_ms))
                # Stamp samples into their window.
                for s in result.partition_samples + result.broker_samples:
                    s.time_ms = min(t + window - 1, end_ms)
                n += self._ingest(result)
                t += window
            return n
        finally:
            with self._lock:
                self._state = prev

    # -------------------------------------------------------- pause/resume

    def pause_sampling(self, reason: str = "user requested") -> None:
        with self._lock:
            if self._state in (RunnerState.RUNNING, RunnerState.SAMPLING):
                self._state = RunnerState.PAUSED
                self._paused_reason = reason

    def resume_sampling(self, reason: str = "user requested") -> None:
        with self._lock:
            if self._state is RunnerState.PAUSED:
                self._state = RunnerState.RUNNING
                self._paused_reason = None

    @property
    def paused_reason(self) -> Optional[str]:
        return self._paused_reason
