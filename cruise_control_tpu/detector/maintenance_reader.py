"""Maintenance plans consumed from the message bus.

Reference: ``detector/MaintenanceEventTopicReader.java:1-350`` — the service
reads user-submitted maintenance plans from the ``__MaintenanceEvent`` Kafka
topic (produced by operators/tooling), discards plans older than
``maintenance.plan.expiration.ms``, converts the rest to ``MaintenanceEvent``
anomalies (dedup'd by the idempotence cache), and resumes where it left off
across restarts.  ``MaintenancePlanSerde.java`` defines the wire format: JSON
with a plan type, a per-type version, and a CRC over the content.

Here the topic is a partitioned-log ``Transport`` (the same SPI the metrics
reporter publishes over — ``reporter/transport.py``): a ``FileTransport``
directory for single-box durability or a ``SocketTransport`` pointed at any
``TransportServer``, so a second process can post plans over TCP exactly the
way the reference's producer posts to Kafka.  Consumer positions are
committed to a JSON offsets file after each applied batch (the role of Kafka
committed offsets), so a restart resumes instead of replaying — replayed
plans would be dropped by expiration/idempotence anyway, but committed
offsets keep restart cost O(new plans).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Dict, Optional, Tuple

from cruise_control_tpu.detector.anomalies import MaintenanceEvent

LOG = logging.getLogger(__name__)

#: Plan-type tag -> latest supported serde version (MaintenancePlanSerde's
#: verifyTypeAndVersion: unknown type or a version newer than supported is a
#: deserialization error, not a silent drop).
SUPPORTED_PLANS: Dict[str, int] = {
    "rebalance": 1,
    "add_broker": 1,
    "remove_broker": 1,
    "demote_broker": 1,
    "fix_offline_replicas": 1,
    "topic_replication_factor": 1,
}

DEFAULT_EXPIRATION_MS = 15 * 60 * 1000.0   # maintenance.plan.expiration.ms


def _content_crc(content: Dict) -> int:
    """CRC over the canonical content encoding (sorted keys, no crc field) —
    the serde's integrity check for plans that crossed a network/log hop."""
    return zlib.crc32(
        json.dumps(content, sort_keys=True, separators=(",", ":"))
        .encode("utf-8"))


def serialize_plan(plan: str, time_ms: float, broker_ids=(),
                   topic: Optional[str] = None,
                   replication_factor: Optional[int] = None,
                   version: int = 1) -> bytes:
    """Wire-encode one maintenance plan (MaintenancePlanSerde.serialize)."""
    if plan not in SUPPORTED_PLANS:
        raise ValueError(f"unknown maintenance plan type {plan!r}")
    content = {"planType": plan, "version": int(version),
               "timeMs": float(time_ms),
               "brokers": sorted(int(b) for b in broker_ids)}
    if topic is not None:
        content["topic"] = topic
    if replication_factor is not None:
        content["replicationFactor"] = int(replication_factor)
    return json.dumps({**content, "crc": _content_crc(content)},
                      sort_keys=True).encode("utf-8")


def deserialize_plan(record: bytes) -> Dict:
    """Decode + verify one plan record; raises ValueError on garbage, CRC
    mismatch, unknown type, or a version newer than supported."""
    try:
        obj = json.loads(record.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ValueError(f"undecodable maintenance plan record: {e}") from e
    if not isinstance(obj, dict) or "crc" not in obj:
        raise ValueError("maintenance plan record missing crc")
    stored_crc = obj.pop("crc")
    if _content_crc(obj) != stored_crc:
        raise ValueError("maintenance plan crc mismatch (corrupt record)")
    plan = obj.get("planType")
    latest = SUPPORTED_PLANS.get(plan)
    if latest is None:
        raise ValueError(f"unknown maintenance plan type {plan!r}")
    if int(obj.get("version", 0)) > latest:
        raise ValueError(
            f"cannot deserialize plan type {plan} version {obj.get('version')}"
            f"; latest supported: {latest}")
    # Shape-check the fields the reader consumes: a valid-CRC plan with a
    # missing/mistyped timeMs or brokers must be a per-record drop, not an
    # exception class that escapes the reader's bad-record handling and
    # wedges the stream behind it.
    if not isinstance(obj.get("timeMs"), (int, float)):
        raise ValueError("maintenance plan missing numeric timeMs")
    brokers = obj.get("brokers", [])
    if not (isinstance(brokers, list)
            and all(isinstance(b, int) for b in brokers)):
        raise ValueError("maintenance plan brokers must be a list of ints")
    return obj


class MaintenanceEventReader:
    """Poll a Transport log for maintenance plans and feed the detector.

    One reader instance owns all partitions (the maintenance stream is
    control-plane-rate; the reference uses a single consumer too).  Expired
    and duplicate plans are dropped (expiration here, idempotence in the
    detector); undecodable records are logged and skipped — one corrupt
    record must not wedge the stream behind it.
    """

    def __init__(self, transport, detector,
                 offsets_path: Optional[str] = None,
                 expiration_ms: float = DEFAULT_EXPIRATION_MS,
                 poll_interval_s: float = 5.0,
                 clock=lambda: time.time() * 1000):
        self._transport = transport
        self._detector = detector
        self._offsets_path = offsets_path
        self._expiration_ms = expiration_ms
        self._interval = poll_interval_s
        self._clock = clock
        self._offsets: Dict[int, int] = self._load_offsets()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- offsets

    def _load_offsets(self) -> Dict[int, int]:
        if not self._offsets_path or not os.path.exists(self._offsets_path):
            return {}
        try:
            with open(self._offsets_path) as f:
                return {int(k): int(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            LOG.warning("unreadable maintenance offsets file %s; replaying "
                        "from the log start", self._offsets_path)
            return {}

    def _commit_offsets(self) -> None:
        if not self._offsets_path:
            return
        tmp = self._offsets_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self._offsets.items()}, f)
        os.replace(tmp, self._offsets_path)

    # ---------------------------------------------------------------- poll

    def poll_once(self) -> Tuple[int, int]:
        """Drain every partition once; returns (accepted, dropped)."""
        accepted = dropped = 0
        now = self._clock()
        progressed = False
        for p in range(self._transport.num_partitions):
            offset = self._offsets.get(p, 0)
            while True:
                records, next_offset = self._transport.poll(p, offset)
                if not records:
                    break
                progressed = True
                for rec in records:
                    # The whole per-record path is guarded: any malformed
                    # field is THIS record's problem — offsets must still
                    # advance past it or the stream wedges forever.
                    try:
                        plan = deserialize_plan(rec)
                        stale = (now - float(plan["timeMs"])
                                 > self._expiration_ms)
                        event = MaintenanceEvent(
                            plan=plan["planType"],
                            broker_ids=tuple(plan.get("brokers", ())),
                            topic=plan.get("topic"),
                            replication_factor=plan.get("replicationFactor"))
                    except (ValueError, TypeError, KeyError) as e:
                        LOG.warning("dropping bad maintenance plan: %s", e)
                        dropped += 1
                        continue
                    if stale:
                        # Stale plan (producer/consumer/network delay past
                        # the validity period) — acting on it now could fight
                        # the operator's current intent.
                        dropped += 1
                    elif self._detector.submit(event):
                        accepted += 1
                    else:
                        dropped += 1          # idempotence-cache duplicate
                offset = next_offset
            self._offsets[p] = offset
        if progressed:
            self._commit_offsets()
        return accepted, dropped

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="maintenance-event-reader")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.poll_once()
            except Exception:      # noqa: BLE001 — a dead bus must not kill
                LOG.exception("maintenance event poll failed; will retry")
