"""Anomaly detector manager.

Reference: ``detector/AnomalyDetectorManager.java:50-572`` — owns the
detectors, a priority queue of anomalies, and a single handler task consuming
it; the notifier decides FIX / CHECK / IGNORE; FIX routes through the façade's
propose+execute path (anomaly.fix wired by the façade).  Detection runs on
per-type schedules; here a single scheduler thread ticks each detector at its
interval, and ``run_detection_once`` drives everything synchronously for
tests.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from cruise_control_tpu.detector.anomalies import Anomaly, AnomalyType
from cruise_control_tpu.detector.notifier import (
    AnomalyNotificationResult,
    NoopNotifier,
)
from cruise_control_tpu.obsvc.audit import audit_log
from cruise_control_tpu.obsvc.tracer import tracer as _obsvc_tracer

LOG = logging.getLogger(__name__)


@dataclass
class AnomalyState:
    """Recent-anomaly bookkeeping surfaced via GET /state."""

    recent: Dict[str, List[Dict]] = field(default_factory=dict)
    metrics: Dict[str, int] = field(default_factory=dict)
    ongoing_self_healing: Optional[str] = None

    def record(self, anomaly: Anomaly, status: str) -> None:
        lst = self.recent.setdefault(anomaly.anomaly_type.name, [])
        entry = anomaly.describe()
        entry["status"] = status
        lst.append(entry)
        del lst[:-10]
        self.metrics[status] = self.metrics.get(status, 0) + 1


class AnomalyDetectorManager:
    def __init__(
        self,
        detectors: Dict[AnomalyType, object],
        notifier=None,
        fixer: Optional[Callable[[Anomaly], bool]] = None,
        detection_interval_s: float = 300.0,
        clock=time.monotonic,
    ):
        self.detectors = dict(detectors)
        self.notifier = notifier or NoopNotifier()
        self._fixer = fixer
        self.interval_s = detection_interval_s
        self._clock = clock
        self._queue: List[Anomaly] = []
        self._qlock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.state = AnomalyState()
        self._check_later: List[tuple] = []   # (due_monotonic_s, anomaly)
        self._anomaly_detected_s: Dict[int, float] = {}
        self._register_sensors()

    def _register_sensors(self) -> None:
        """AnomalyDetector sensors (Sensors.md;
        AnomalyDetectorManager.java:173-192)."""
        from cruise_control_tpu.common.metrics import registry
        reg = registry()
        self._rate_counters = {
            t: reg.counter(f"AnomalyDetector.{t.name.lower()}-rate")
            for t in self.detectors
        }
        self._self_healing_started = reg.counter(
            "AnomalyDetector.number-of-self-healing-started")
        self._fix_start_timer = reg.timer(
            "AnomalyDetector.mean-time-to-start-fix-ms")
        for t in self.detectors:
            reg.gauge(
                f"AnomalyDetector.{t.name.lower()}-self-healing-enabled",
                (lambda tt: lambda: int(bool(
                    self.notifier.self_healing_enabled().get(tt, False)
                    if hasattr(self.notifier, "self_healing_enabled") else False)))(t))
        reg.gauge("AnomalyDetector.has-ongoing-self-healing",
                  lambda: int(self.state.ongoing_self_healing is not None))
        reg.gauge("AnomalyDetector.anomaly-queue-size",
                  lambda: len(self._queue))

    # ------------------------------------------------------------ lifecycle

    def start_detection(self) -> None:
        """AnomalyDetectorManager.startDetection :215-226."""
        t = threading.Thread(target=self._detection_loop, daemon=True,
                             name="anomaly-detector")
        t.start()
        self._threads.append(t)
        h = threading.Thread(target=self._handler_loop, daemon=True,
                             name="anomaly-handler")
        h.start()
        self._threads.append(h)

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    # ------------------------------------------------------------ detection

    def _detection_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.run_detection_once(handle=False)

    def run_detection_once(self, handle: bool = True) -> int:
        """Run every detector; enqueue anomalies; optionally drain the queue
        synchronously (test mode)."""
        n = 0
        for anomaly_type, detector in self.detectors.items():
            try:
                found = detector.detect()
            except Exception:      # noqa: BLE001 — a broken detector must not stop others
                LOG.exception("detector %s failed", anomaly_type.name)
                continue
            for a in found:
                self._enqueue(a)
                n += 1
        if handle:
            self.handle_pending()
        return n

    def _enqueue(self, anomaly: Anomaly) -> None:
        with self._qlock:
            heapq.heappush(self._queue, anomaly)
        # Count only first-time detections: CHECK-delayed anomalies re-enter
        # through this path and must not inflate the detection-rate sensor.
        first_time = id(anomaly) not in self._anomaly_detected_s
        self._anomaly_detected_s.setdefault(id(anomaly), self._clock())
        if first_time:
            counter = self._rate_counters.get(anomaly.anomaly_type)
            if counter is not None:
                counter.inc()
        self.state.record(anomaly, "DETECTED")

    # ------------------------------------------------------------- handling

    def _handler_loop(self) -> None:
        while not self._stop.wait(0.2):
            self.handle_pending()

    def handle_pending(self) -> int:
        """AnomalyHandlerTask :326-440: pop by priority, consult notifier."""
        handled = 0
        now_s = self._clock()
        with self._qlock:
            due = [a for t, a in self._check_later if t <= now_s]
            self._check_later = [(t, a) for t, a in self._check_later if t > now_s]
        for a in due:
            self._enqueue(a)
        while True:
            with self._qlock:
                if not self._queue:
                    break
                anomaly = heapq.heappop(self._queue)
            self._handle(anomaly)
            handled += 1
        return handled

    def _handle(self, anomaly: Anomaly) -> None:
        type_name = anomaly.anomaly_type.name
        action = self.notifier.on_anomaly(anomaly)
        if action.result is AnomalyNotificationResult.IGNORE:
            # Drop the detection timestamp too: id() can be reused after GC
            # and a stale entry would poison mean-time-to-start-fix.
            self._anomaly_detected_s.pop(id(anomaly), None)
            self.state.record(anomaly, "IGNORED")
            audit_log().record(type_name, anomaly.describe(), "IGNORED")
            return
        if action.result is AnomalyNotificationResult.CHECK:
            with self._qlock:
                self._check_later.append(
                    (self._clock() + action.delay_ms / 1000.0, anomaly))
            self.state.record(anomaly, "CHECK_WITH_DELAY")
            audit_log().record(type_name, anomaly.describe(),
                               "CHECK_WITH_DELAY")
            return
        # FIX
        entry_id = audit_log().record(type_name, anomaly.describe(), "FIX")
        self.state.ongoing_self_healing = type_name
        self._self_healing_started.inc()
        detected = self._anomaly_detected_s.pop(id(anomaly), None)
        if detected is not None:
            self._fix_start_timer.update_ms((self._clock() - detected) * 1000.0)
        try:
            ok = False
            with _obsvc_tracer().span(f"selfheal.{type_name.lower()}"):
                if anomaly.fix is not None:
                    ok = bool(anomaly.fix())
                elif self._fixer is not None:
                    ok = bool(self._fixer(anomaly))
            outcome = "FIX_STARTED" if ok else "FIX_FAILED_TO_START"
            self.state.record(anomaly, outcome)
            audit_log().set_outcome(entry_id, outcome)
        except Exception:          # noqa: BLE001 — keep the handler alive
            LOG.exception("fix for %s failed", type_name)
            self.state.record(anomaly, "FIX_FAILED_TO_START")
            audit_log().set_outcome(entry_id, "FIX_FAILED_TO_START")
        finally:
            self.state.ongoing_self_healing = None

    # ---------------------------------------------------------------- state

    def state_summary(self) -> Dict:
        return {
            "selfHealingEnabled": {t.name: v for t, v in
                                   self.notifier.self_healing_enabled().items()},
            "recentAnomalies": self.state.recent,
            "metrics": self.state.metrics,
            "ongoingSelfHealing": self.state.ongoing_self_healing,
            "selfHealingAudit": audit_log().entries(),
        }
