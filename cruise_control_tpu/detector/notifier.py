"""Anomaly notification / self-healing policy.

Reference: ``detector/notifier/AnomalyNotifier.java`` SPI,
``SelfHealingNotifier.java:57-148`` (broker-failure alert after 15 min,
auto-fix after 30 min; per-type self-healing enable flags),
``NoopNotifier``, ``SlackSelfHealingNotifier`` (webhook alerting — here a
pluggable alert callback, since outbound webhooks are deployment glue).
"""

from __future__ import annotations

import enum
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from cruise_control_tpu.detector.anomalies import Anomaly, AnomalyType, BrokerFailures

LOG = logging.getLogger(__name__)

BROKER_FAILURE_ALERT_THRESHOLD_MS = 15 * 60 * 1000   # SelfHealingNotifier.java:67
BROKER_FAILURE_SELF_HEALING_THRESHOLD_MS = 30 * 60 * 1000  # :68


class AnomalyNotificationResult(enum.Enum):
    FIX = "fix"
    CHECK = "check"      # re-evaluate after delay_ms
    IGNORE = "ignore"


@dataclass
class NotificationAction:
    result: AnomalyNotificationResult
    delay_ms: float = 0.0


class NoopNotifier:
    def on_anomaly(self, anomaly: Anomaly) -> NotificationAction:
        return NotificationAction(AnomalyNotificationResult.IGNORE)

    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return {t: False for t in AnomalyType}

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        return False


class SelfHealingNotifier:
    """Threshold-based self-healing policy (SelfHealingNotifier.java)."""

    def __init__(
        self,
        self_healing_enabled: bool = False,
        alert_callback: Optional[Callable[[Anomaly, bool], None]] = None,
        clock=lambda: time.time() * 1000,
        broker_failure_alert_threshold_ms: float = BROKER_FAILURE_ALERT_THRESHOLD_MS,
        broker_failure_self_healing_threshold_ms: float =
            BROKER_FAILURE_SELF_HEALING_THRESHOLD_MS,
    ):
        self._enabled: Dict[AnomalyType, bool] = {
            t: self_healing_enabled for t in AnomalyType}
        self._alert = alert_callback or (lambda anomaly, auto_fix: None)
        self._clock = clock
        self.alert_threshold_ms = broker_failure_alert_threshold_ms
        self.self_healing_threshold_ms = broker_failure_self_healing_threshold_ms
        self._alerted: Dict[int, bool] = {}

    # -------------------------------------------------------------- toggles

    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return dict(self._enabled)

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        old = self._enabled.get(anomaly_type, False)
        self._enabled[anomaly_type] = enabled
        return old

    # --------------------------------------------------------------- policy

    def on_anomaly(self, anomaly: Anomaly) -> NotificationAction:
        if isinstance(anomaly, BrokerFailures):
            return self._on_broker_failure(anomaly)
        if not self._enabled.get(anomaly.anomaly_type, False):
            self._alert(anomaly, False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        if not anomaly.fixable:
            self._alert(anomaly, False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        return NotificationAction(AnomalyNotificationResult.FIX)

    def _on_broker_failure(self, anomaly: BrokerFailures) -> NotificationAction:
        """Grace-period logic (SelfHealingNotifier.java:106-148): alert after
        the alert threshold, auto-fix only after the self-healing threshold
        (measured from the EARLIEST broker failure)."""
        now = self._clock()
        if not anomaly.failed_brokers:
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        earliest = min(anomaly.failed_brokers.values())
        alert_time = earliest + self.alert_threshold_ms
        fix_time = earliest + self.self_healing_threshold_ms
        if now < alert_time:
            return NotificationAction(AnomalyNotificationResult.CHECK,
                                      delay_ms=alert_time - now)
        auto_fix = self._enabled.get(AnomalyType.BROKER_FAILURE, False)
        if not self._alerted.get(anomaly.anomaly_id):
            self._alerted[anomaly.anomaly_id] = True
            self._alert(anomaly, auto_fix and now >= fix_time)
        if not auto_fix:
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        if now < fix_time:
            return NotificationAction(AnomalyNotificationResult.CHECK,
                                      delay_ms=fix_time - now)
        return NotificationAction(AnomalyNotificationResult.FIX)
