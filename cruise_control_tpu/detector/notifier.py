"""Anomaly notification / self-healing policy.

Reference: ``detector/notifier/AnomalyNotifier.java`` SPI,
``SelfHealingNotifier.java:57-148`` (broker-failure alert after 15 min,
auto-fix after 30 min; per-type self-healing enable flags),
``NoopNotifier``, and ``SlackSelfHealingNotifier`` → the
``WebhookSelfHealingNotifier`` below (JSON webhook POST per alert) plus a
pluggable alert callback for custom receivers.
"""

from __future__ import annotations

import enum
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from cruise_control_tpu.detector.anomalies import Anomaly, AnomalyType, BrokerFailures

LOG = logging.getLogger(__name__)

BROKER_FAILURE_ALERT_THRESHOLD_MS = 15 * 60 * 1000   # SelfHealingNotifier.java:67
BROKER_FAILURE_SELF_HEALING_THRESHOLD_MS = 30 * 60 * 1000  # :68


class AnomalyNotificationResult(enum.Enum):
    FIX = "fix"
    CHECK = "check"      # re-evaluate after delay_ms
    IGNORE = "ignore"


@dataclass
class NotificationAction:
    result: AnomalyNotificationResult
    delay_ms: float = 0.0


class NoopNotifier:
    def on_anomaly(self, anomaly: Anomaly) -> NotificationAction:
        return NotificationAction(AnomalyNotificationResult.IGNORE)

    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return {t: False for t in AnomalyType}

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        return False


class SelfHealingNotifier:
    """Threshold-based self-healing policy (SelfHealingNotifier.java)."""

    def __init__(
        self,
        self_healing_enabled: bool = False,
        alert_callback: Optional[Callable[[Anomaly, bool], None]] = None,
        clock=lambda: time.time() * 1000,
        broker_failure_alert_threshold_ms: float = BROKER_FAILURE_ALERT_THRESHOLD_MS,
        broker_failure_self_healing_threshold_ms: float =
            BROKER_FAILURE_SELF_HEALING_THRESHOLD_MS,
    ):
        self._enabled: Dict[AnomalyType, bool] = {
            t: self_healing_enabled for t in AnomalyType}
        self._alert = alert_callback or (lambda anomaly, auto_fix: None)
        self._clock = clock
        self.alert_threshold_ms = broker_failure_alert_threshold_ms
        self.self_healing_threshold_ms = broker_failure_self_healing_threshold_ms
        self._alerted: Dict[int, bool] = {}

    # -------------------------------------------------------------- toggles

    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return dict(self._enabled)

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        old = self._enabled.get(anomaly_type, False)
        self._enabled[anomaly_type] = enabled
        return old

    # --------------------------------------------------------------- policy

    def on_anomaly(self, anomaly: Anomaly) -> NotificationAction:
        if isinstance(anomaly, BrokerFailures):
            return self._on_broker_failure(anomaly)
        if not self._enabled.get(anomaly.anomaly_type, False):
            self._alert(anomaly, False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        if not anomaly.fixable:
            self._alert(anomaly, False)
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        return NotificationAction(AnomalyNotificationResult.FIX)

    def _on_broker_failure(self, anomaly: BrokerFailures) -> NotificationAction:
        """Grace-period logic (SelfHealingNotifier.java:106-148): alert after
        the alert threshold, auto-fix only after the self-healing threshold
        (measured from the EARLIEST broker failure)."""
        now = self._clock()
        if not anomaly.failed_brokers:
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        earliest = min(anomaly.failed_brokers.values())
        alert_time = earliest + self.alert_threshold_ms
        fix_time = earliest + self.self_healing_threshold_ms
        if now < alert_time:
            return NotificationAction(AnomalyNotificationResult.CHECK,
                                      delay_ms=alert_time - now)
        auto_fix = self._enabled.get(AnomalyType.BROKER_FAILURE, False)
        if not self._alerted.get(anomaly.anomaly_id):
            self._alerted[anomaly.anomaly_id] = True
            self._alert(anomaly, auto_fix and now >= fix_time)
        if not auto_fix:
            return NotificationAction(AnomalyNotificationResult.IGNORE)
        if now < fix_time:
            return NotificationAction(AnomalyNotificationResult.CHECK,
                                      delay_ms=fix_time - now)
        return NotificationAction(AnomalyNotificationResult.FIX)


class WebhookSelfHealingNotifier(SelfHealingNotifier):
    """Webhook-alerting notifier (SlackSelfHealingNotifier.java:40-117 —
    POST a JSON message to a configured webhook URL per alert; Slack, MS
    Teams and generic receivers all accept this shape).

    Posts happen on the caller's thread with a short timeout and never raise:
    a broken webhook must not take down anomaly handling.
    """

    def __init__(self, webhook_url: str, channel: str = "",
                 sender: str = "cruise-control-tpu", timeout_s: float = 5.0,
                 post_fn=None, **kwargs):
        super().__init__(alert_callback=self._post_alert, **kwargs)
        self.webhook_url = webhook_url
        self.channel = channel
        self.sender = sender
        self.timeout_s = timeout_s
        self._post = post_fn or self._http_post

    def _http_post(self, payload: dict) -> None:
        import json as _json
        import urllib.request
        req = urllib.request.Request(
            self.webhook_url, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()

    def _post_alert(self, anomaly: Anomaly, auto_fix_triggered: bool) -> None:
        payload = {
            "username": self.sender,
            "text": (f"{anomaly.anomaly_type.name} detected: {anomaly}. "
                     f"Self healing {'started' if auto_fix_triggered else 'not started'}."),
        }
        if self.channel:
            payload["channel"] = self.channel
        try:
            self._post(payload)
        except Exception:    # noqa: BLE001 — alerting must never break handling
            LOG.warning("webhook alert failed", exc_info=True)
