"""The six anomaly detectors.

Reference classes, mapped one-to-one:
- ``GoalViolationDetector.java:51-290``  — fresh model per completeness tier,
  violated = detection goal produces proposals; balancedness score.
- ``BrokerFailureDetector.java:44-233``  — liveness watch + persisted
  failed-broker list with first-failure timestamps.
- ``DiskFailureDetector.java:1-118``     — offline-logdir scan.
- ``MetricAnomalyDetector.java`` + ``SlowBrokerFinder.java:1-478`` —
  percentile history checks; slow brokers vs peers and own history.
- ``TopicAnomalyDetector.java`` + RF/partition-size finders.
- ``MaintenanceEventDetector.java`` + idempotence cache.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from cruise_control_tpu.analyzer import GoalOptimizer, OptimizationOptions
from cruise_control_tpu.analyzer.goals.registry import DEFAULT_ANOMALY_DETECTION_GOALS
from cruise_control_tpu.common.exceptions import (
    NotEnoughValidWindowsError,
    OptimizationFailureError,
)
from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    BrokerFailures,
    DiskFailures,
    GoalViolations,
    MaintenanceEvent,
    MetricAnomaly,
    TopicAnomaly,
)
from cruise_control_tpu.monitor import metric_def as md
from cruise_control_tpu.monitor.load_monitor import LoadMonitor

LOG = logging.getLogger(__name__)


class GoalViolationDetector:
    """Runs the anomaly-detection goals over a fresh snapshot."""

    def __init__(self, load_monitor: LoadMonitor,
                 goal_names: Optional[Sequence[str]] = None,
                 excluded_topics: Optional[Set[str]] = None):
        self.load_monitor = load_monitor
        self.goal_names = list(goal_names or DEFAULT_ANOMALY_DETECTION_GOALS)
        self.excluded_topics = frozenset(excluded_topics or ())
        self._last_generation = None
        self.last_balancedness_score: float = 100.0

    def detect(self) -> List[Anomaly]:
        # Refresh first so the recorded generation reflects current topology.
        self.load_monitor.metadata_client.refresh_metadata()
        generation = self.load_monitor.model_generation
        if generation == self._last_generation:
            return []     # :114-121 — skip unchanged models
        self._last_generation = generation
        try:
            state, placement, meta = self.load_monitor.cluster_model(
                pad_replicas_to=64, pad_brokers_to=8)
        except NotEnoughValidWindowsError:
            return []
        fixable: List[str] = []
        unfixable: List[str] = []
        options = OptimizationOptions(
            excluded_topics=self.excluded_topics,
            is_triggered_by_goal_violation=True,
            only_move_immigrant_replicas=False)
        for name in self.goal_names:
            optimizer = GoalOptimizer(goal_names=[name])
            try:
                result = optimizer.optimizations(state, placement, meta,
                                                 options=options)
            except OptimizationFailureError:
                unfixable.append(name)
                continue
            if result.proposals:
                fixable.append(name)
        if not fixable and not unfixable:
            self.last_balancedness_score = 100.0
            return []
        total = len(self.goal_names) or 1
        self.last_balancedness_score = 100.0 * (
            1 - (len(fixable) + len(unfixable)) / total)
        return [GoalViolations(fixable=fixable, unfixable=unfixable)]


class BrokerFailureDetector:
    """Liveness diff + durable failed-broker record (the reference persists
    to a ZK znode :118; here a JSON file plays that role)."""

    def __init__(self, metadata_client, persist_path: Optional[str] = None,
                 clock=lambda: time.time() * 1000):
        self.metadata_client = metadata_client
        self.persist_path = persist_path
        self._clock = clock
        self._failed: Dict[int, float] = {}
        if persist_path and os.path.exists(persist_path):
            try:
                with open(persist_path) as f:
                    self._failed = {int(k): v for k, v in json.load(f).items()}
            except (ValueError, OSError):
                LOG.warning("could not load failed-broker record", exc_info=True)

    def detect(self) -> List[Anomaly]:
        metadata = self.metadata_client.refresh_metadata(force=True)
        now = self._clock()
        dead = {b.broker_id for b in metadata.brokers if not b.alive}
        changed = False
        for b in dead:
            if b not in self._failed:
                self._failed[b] = now
                changed = True
        for b in list(self._failed):
            if b not in dead:
                del self._failed[b]
                changed = True
        if changed:
            self._persist()
        if not self._failed:
            return []
        return [BrokerFailures(failed_brokers=dict(self._failed))]

    def _persist(self) -> None:
        if not self.persist_path:
            return
        with open(self.persist_path, "w") as f:
            json.dump({str(k): v for k, v in self._failed.items()}, f)

    @property
    def failed_brokers(self) -> Dict[int, float]:
        return dict(self._failed)


class DiskFailureDetector:
    """Offline-logdir scan via an injectable provider (the reference queries
    AdminClient.describeLogDirs)."""

    def __init__(self, offline_disks_provider: Callable[[], Dict[int, List[int]]]):
        self.provider = offline_disks_provider

    def detect(self) -> List[Anomaly]:
        offline = {b: list(d) for b, d in (self.provider() or {}).items() if d}
        if not offline:
            return []
        return [DiskFailures(failed_disks=offline)]


class MetricAnomalyDetector:
    """Percentile-based broker metric anomalies + SlowBrokerFinder.

    SlowBrokerFinder.java:40-80: a broker is slow when its log-flush time is
    high vs its own history AND vs its peers; repeated slowness escalates
    from check to demote to remove.
    """

    def __init__(self, broker_aggregator, percentile: float = 95.0,
                 margin: float = 1.5,
                 metric_names: Sequence[str] = ("BROKER_LOG_FLUSH_TIME_MS_MEAN",),
                 slow_broker_demotion_score: int = 2,
                 slow_broker_removal_score: int = 5):
        self.agg = broker_aggregator
        self.percentile = percentile
        self.margin = margin
        self.metric_ids = [md.BROKER_METRIC_DEF.metric_id(n) for n in metric_names]
        self.metric_names = list(metric_names)
        self._slow_scores: Dict[int, int] = {}
        self.demotion_score = slow_broker_demotion_score
        self.removal_score = slow_broker_removal_score

    def detect(self) -> List[Anomaly]:
        try:
            result = self.agg.aggregate(-float("inf"), float("inf"))
        except NotEnoughValidWindowsError:
            return []
        vae = result.values_and_extrapolations
        if len(vae) < 2:
            return []
        out: List[Anomaly] = []
        for mid, name in zip(self.metric_ids, self.metric_names):
            latest = {b: v.values[mid, -1] for b, v in vae.items()}
            history = {b: v.values[mid, :-1] for b, v in vae.items()
                       if v.values.shape[1] > 1}
            peer_median = float(np.median(list(latest.values())))
            slow_now: Set[int] = set()
            for b, value in latest.items():
                hist = history.get(b)
                own_thresh = (np.percentile(hist, self.percentile) * self.margin
                              if hist is not None and hist.size else np.inf)
                peer_thresh = peer_median * self.margin
                if value > peer_thresh and (hist is None or value > own_thresh
                                            or not hist.size):
                    slow_now.add(b)
                    score = self._slow_scores.get(b, 0) + 1
                    self._slow_scores[b] = score
                    action = ("remove" if score >= self.removal_score
                              else "demote" if score >= self.demotion_score
                              else "check")
                    out.append(MetricAnomaly(
                        broker_id=b, metric_name=name, current_value=float(value),
                        threshold=float(min(own_thresh, peer_thresh)),
                        suggested_action=action))
            for b in list(self._slow_scores):
                if b not in slow_now:
                    self._slow_scores[b] = max(self._slow_scores[b] - 1, 0)
        return out


class TopicAnomalyDetector:
    """RF and partition-size violations (TopicReplicationFactorAnomalyFinder
    :283, PartitionSizeAnomalyFinder :129)."""

    def __init__(self, metadata_client, partition_aggregator=None,
                 target_replication_factor: Optional[int] = None,
                 max_partition_size_bytes: Optional[float] = None):
        self.metadata_client = metadata_client
        self.partition_aggregator = partition_aggregator
        self.target_rf = target_replication_factor
        self.max_partition_size = max_partition_size_bytes

    def detect(self) -> List[Anomaly]:
        out: List[Anomaly] = []
        metadata = self.metadata_client.refresh_metadata()
        if self.target_rf is not None:
            bad_topics: Dict[str, int] = {}
            for p in metadata.partitions:
                if len(p.replicas) != self.target_rf:
                    bad_topics[p.topic] = len(p.replicas)
            for topic, rf in bad_topics.items():
                out.append(TopicAnomaly(
                    topic=topic,
                    reason=f"replication factor {rf} != target {self.target_rf}",
                    target_replication_factor=self.target_rf))
        if self.max_partition_size is not None and self.partition_aggregator:
            try:
                result = self.partition_aggregator.aggregate(-float("inf"),
                                                             float("inf"))
            except NotEnoughValidWindowsError:
                return out
            for (topic, part), vae in result.values_and_extrapolations.items():
                size = float(vae.values[md.DISK_USAGE, -1])
                if size > self.max_partition_size:
                    out.append(TopicAnomaly(
                        topic=topic,
                        reason=f"partition {part} size {size:.0f} exceeds "
                               f"{self.max_partition_size:.0f}"))
        return out


class MaintenanceEventDetector:
    """User-submitted plans with idempotence (MaintenanceEventTopicReader +
    IdempotenceCache; the Kafka topic becomes an in-process queue that a REST
    endpoint or file watcher feeds)."""

    def __init__(self, idempotence_ttl_ms: float = 60_000,
                 clock=lambda: time.time() * 1000):
        self._queue: List[MaintenanceEvent] = []
        self._lock = threading.Lock()
        self._seen: Dict[Tuple, float] = {}
        self._ttl = idempotence_ttl_ms
        self._clock = clock

    def submit(self, event: MaintenanceEvent) -> bool:
        with self._lock:
            now = self._clock()
            for k, t in list(self._seen.items()):
                if now - t > self._ttl:
                    del self._seen[k]
            if event.key() in self._seen:
                return False
            self._seen[event.key()] = now
            self._queue.append(event)
            return True

    def detect(self) -> List[Anomaly]:
        with self._lock:
            out, self._queue = self._queue, []
            return list(out)
