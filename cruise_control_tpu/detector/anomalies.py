"""Anomaly types.

Reference: core ``detector/Anomaly.java`` / ``AnomalyType.java`` and the main
module's concrete anomalies (``GoalViolations``, ``BrokerFailures``,
``DiskFailures``, ``KafkaMetricAnomaly``, ``TopicAnomaly``,
``MaintenanceEvent``).  Priority order mirrors
``KafkaAnomalyType.java`` (broker failure heals before goal violations).
"""

from __future__ import annotations

import enum
import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


class AnomalyType(enum.IntEnum):
    """Lower value = higher handling priority (KafkaAnomalyType.java)."""

    BROKER_FAILURE = 0
    DISK_FAILURE = 1
    METRIC_ANOMALY = 2
    GOAL_VIOLATION = 3
    TOPIC_ANOMALY = 4
    MAINTENANCE_EVENT = 5
    # Service-level-objective burn (no reference analog): an observability
    # signal — a latency or solve objective burning its error budget — fed
    # into the same detector→notifier→audit loop the reference uses for
    # goal violations.  Lowest priority: cluster-health anomalies heal first.
    SLO_VIOLATION = 6


_ids = itertools.count()


@dataclass
class Anomaly:
    anomaly_type: AnomalyType
    detection_time_ms: float = field(default_factory=lambda: time.time() * 1000)
    anomaly_id: int = field(default_factory=lambda: next(_ids))
    # Filled by the manager: callable that performs the fix via the façade.
    fix: Optional[Callable[[], bool]] = None
    fixable: bool = True

    def __lt__(self, other: "Anomaly") -> bool:
        return ((self.anomaly_type, self.detection_time_ms)
                < (other.anomaly_type, other.detection_time_ms))

    def describe(self) -> Dict:
        return {"type": self.anomaly_type.name,
                "detectionMs": self.detection_time_ms,
                "anomalyId": self.anomaly_id}


@dataclass
class GoalViolations(Anomaly):
    """Goals whose detection run produced proposals (= violated)."""

    fixable_violated_goals: List[str] = field(default_factory=list)
    unfixable_violated_goals: List[str] = field(default_factory=list)

    def __init__(self, fixable=None, unfixable=None, **kw):
        super().__init__(AnomalyType.GOAL_VIOLATION, **kw)
        self.fixable_violated_goals = list(fixable or [])
        self.unfixable_violated_goals = list(unfixable or [])
        self.fixable = bool(self.fixable_violated_goals)

    def describe(self) -> Dict:
        d = super().describe()
        d["fixableViolatedGoals"] = self.fixable_violated_goals
        d["unfixableViolatedGoals"] = self.unfixable_violated_goals
        return d


@dataclass
class BrokerFailures(Anomaly):
    failed_brokers: Dict[int, float] = field(default_factory=dict)  # id -> failed at ms

    def __init__(self, failed_brokers=None, **kw):
        super().__init__(AnomalyType.BROKER_FAILURE, **kw)
        self.failed_brokers = dict(failed_brokers or {})

    def describe(self) -> Dict:
        d = super().describe()
        d["failedBrokers"] = self.failed_brokers
        return d


@dataclass
class DiskFailures(Anomaly):
    failed_disks: Dict[int, List[int]] = field(default_factory=dict)  # broker -> disks

    def __init__(self, failed_disks=None, **kw):
        super().__init__(AnomalyType.DISK_FAILURE, **kw)
        self.failed_disks = dict(failed_disks or {})

    def describe(self) -> Dict:
        d = super().describe()
        d["failedDisks"] = self.failed_disks
        return d


@dataclass
class MetricAnomaly(Anomaly):
    """A broker metric outside its historical percentile bounds."""

    broker_id: int = -1
    metric_name: str = ""
    current_value: float = 0.0
    threshold: float = 0.0
    # SlowBrokerFinder escalation: demote or remove the broker.
    suggested_action: str = "check"       # check | demote | remove

    def __init__(self, broker_id=-1, metric_name="", current_value=0.0,
                 threshold=0.0, suggested_action="check", **kw):
        super().__init__(AnomalyType.METRIC_ANOMALY, **kw)
        self.broker_id = broker_id
        self.metric_name = metric_name
        self.current_value = current_value
        self.threshold = threshold
        self.suggested_action = suggested_action

    def describe(self) -> Dict:
        d = super().describe()
        d.update({"brokerId": self.broker_id, "metric": self.metric_name,
                  "value": self.current_value, "threshold": self.threshold,
                  "suggestedAction": self.suggested_action})
        return d


@dataclass
class TopicAnomaly(Anomaly):
    """Topic property violations (replication factor / partition size)."""

    topic: str = ""
    reason: str = ""
    target_replication_factor: Optional[int] = None

    def __init__(self, topic="", reason="", target_replication_factor=None, **kw):
        super().__init__(AnomalyType.TOPIC_ANOMALY, **kw)
        self.topic = topic
        self.reason = reason
        self.target_replication_factor = target_replication_factor

    def describe(self) -> Dict:
        d = super().describe()
        d.update({"topic": self.topic, "reason": self.reason})
        return d


@dataclass
class SloViolationAnomaly(Anomaly):
    """A service-level objective burning its error budget in BOTH burn-rate
    windows (obsvc/slo.py evaluates the objectives over the sensor-history
    rings).  Not self-fixable — the point is the audit/alert trail."""

    objective: str = ""
    sensor: str = ""
    threshold: float = 0.0
    worst_value: float = 0.0
    burn_rate_short: float = 0.0
    burn_rate_long: float = 0.0

    def __init__(self, objective="", sensor="", threshold=0.0,
                 worst_value=0.0, burn_rate_short=0.0, burn_rate_long=0.0,
                 **kw):
        super().__init__(AnomalyType.SLO_VIOLATION, **kw)
        self.objective = objective
        self.sensor = sensor
        self.threshold = threshold
        self.worst_value = worst_value
        self.burn_rate_short = burn_rate_short
        self.burn_rate_long = burn_rate_long
        self.fixable = False

    def describe(self) -> Dict:
        d = super().describe()
        d.update({"objective": self.objective, "sensor": self.sensor,
                  "threshold": self.threshold, "worstValue": self.worst_value,
                  "burnRateShort": self.burn_rate_short,
                  "burnRateLong": self.burn_rate_long})
        return d


@dataclass
class MaintenanceEvent(Anomaly):
    """User-submitted maintenance plan (MaintenanceEventDetector).

    plan: one of add_broker / remove_broker / demote_broker / rebalance /
    fix_offline_replicas / topic_replication_factor.
    """

    plan: str = "rebalance"
    broker_ids: Tuple[int, ...] = ()
    topic: Optional[str] = None
    replication_factor: Optional[int] = None

    def __init__(self, plan="rebalance", broker_ids=(), topic=None,
                 replication_factor=None, **kw):
        super().__init__(AnomalyType.MAINTENANCE_EVENT, **kw)
        self.plan = plan
        self.broker_ids = tuple(broker_ids)
        self.topic = topic
        self.replication_factor = replication_factor

    def key(self) -> Tuple:
        """Idempotence key (IdempotenceCache semantics)."""
        return (self.plan, self.broker_ids, self.topic, self.replication_factor)

    def describe(self) -> Dict:
        d = super().describe()
        d.update({"plan": self.plan, "brokers": list(self.broker_ids)})
        return d
