"""Anomaly detection and self-healing.

Reference: ``detector/AnomalyDetectorManager.java`` + the six detectors and
the notifier SPI (``detector/notifier/*``).  Detection consumes the same
frozen snapshots the analyzer uses; fixes route through the façade's normal
propose→execute path exactly as the reference's self-healing does
(SURVEY.md §3.5).
"""

from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyType,
    BrokerFailures,
    DiskFailures,
    GoalViolations,
    MetricAnomaly,
    SloViolationAnomaly,
    TopicAnomaly,
)
from cruise_control_tpu.detector.notifier import (
    AnomalyNotificationResult,
    NoopNotifier,
    SelfHealingNotifier,
)
from cruise_control_tpu.detector.manager import AnomalyDetectorManager

__all__ = [
    "Anomaly",
    "AnomalyType",
    "GoalViolations",
    "BrokerFailures",
    "DiskFailures",
    "MetricAnomaly",
    "SloViolationAnomaly",
    "TopicAnomaly",
    "AnomalyNotificationResult",
    "SelfHealingNotifier",
    "NoopNotifier",
    "AnomalyDetectorManager",
]
