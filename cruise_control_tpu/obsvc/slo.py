"""SLO burn-rate evaluation over sensor history rings (slo.*).

The detector pipeline reacts to cluster anomalies (broker failure, goal
violation) but not to the service degrading itself — a solve suddenly taking
50 rounds, an endpoint's p99 creeping past its budget.  This module closes
that loop: per-endpoint latency and per-solve round/time objectives are
evaluated over the :mod:`~cruise_control_tpu.obsvc.history` rings with
multi-window burn rates (Google SRE-workbook style):

- a window's *burn rate* is the fraction of its samples violating the
  threshold, divided by the error budget (``slo.error.budget``).  Burn 1.0
  means the budget is being consumed exactly as provisioned; >1.0 burns
  faster;
- an objective alerts only when BOTH the short window (fast signal) and the
  long window (sustained, de-flaps single spikes) are at or above
  ``slo.burn.rate.threshold``;
- an empty ring is no violation — absence of evidence is not burn;
- samples timestamped in the future (clock skew between the sampler and the
  evaluator) are clamped to "now" so they land in the short window instead
  of being silently dropped.

Violations surface as :class:`SloViolationAnomaly` through the existing
detector → notifier → self-healing-audit path (unfixable, so the notifier
IGNOREs them into the audit ring and alert log).
"""

from __future__ import annotations

import fnmatch
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from cruise_control_tpu.detector.anomalies import SloViolationAnomaly
from cruise_control_tpu.obsvc.history import HistoryRecorder, history


@dataclass(frozen=True)
class SloObjective:
    """One objective: sensors matching ``pattern`` must keep their history
    scalar at or under ``threshold`` (history stores timers as p99_ms)."""

    name: str
    pattern: str
    threshold: float

    def matches(self, sensor: str) -> bool:
        return fnmatch.fnmatch(sensor, self.pattern)


def objectives_from_config(config) -> List[SloObjective]:
    """The seven built-in objectives, thresholds from ``slo.*`` keys."""
    return [
        SloObjective(
            name="memory-headroom",
            pattern="Memory.device-utilization",
            threshold=float(config.get("slo.memory.utilization.max"))),
        SloObjective(
            name="endpoint-latency-p99",
            pattern="KafkaCruiseControlServlet.*-successful-request-execution-timer",
            threshold=float(config.get("slo.endpoint.latency.p99.ms"))),
        SloObjective(
            name="solve-time",
            pattern="GoalOptimizer.proposal-computation-timer",
            threshold=float(config.get("slo.solve.time.ms"))),
        SloObjective(
            name="solve-rounds",
            pattern="Solver.*.rounds",
            threshold=float(config.get("slo.solve.rounds.max"))),
        SloObjective(
            # Execution throughput, inverted so "bad" is ABOVE threshold:
            # the gauge is the flight recorder's EWMA seconds-per-move,
            # which reads 0.0 while no batch is live — idle never burns.
            name="execution-throughput",
            pattern="Executor.seconds-per-move",
            threshold=float(config.get("slo.execution.seconds.per.move.max"))),
        SloObjective(
            # Model freshness: age of the fidelity fingerprint's newest
            # valid window.  The gauge reads 0.0 before the first
            # fingerprint, so cold boot never burns.
            name="model-freshness",
            pattern="Monitor.fingerprint-age-ms",
            threshold=float(config.get("slo.model.age.max.ms"))),
        SloObjective(
            # Model validity, inverted so "bad" is ABOVE threshold: the
            # gauge is 1 - valid-partition-ratio (0.0 with no fingerprint).
            name="model-validity",
            pattern="Monitor.invalid-partition-ratio",
            threshold=1.0 - float(
                config.get("slo.model.valid.partition.ratio.min"))),
    ]


class SloEvaluator:
    """Evaluates objectives over the history rings with two burn windows."""

    def __init__(self, objectives: List[SloObjective],
                 error_budget: float = 0.1,
                 short_window_s: float = 300.0,
                 long_window_s: float = 3_600.0,
                 burn_threshold: float = 1.0,
                 recorder: Optional[HistoryRecorder] = None,
                 clock=time.time):
        self.objectives = list(objectives)
        self.error_budget = max(float(error_budget), 1e-9)
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.burn_threshold = float(burn_threshold)
        self._recorder = recorder
        self._clock = clock

    def _history(self) -> HistoryRecorder:
        return self._recorder if self._recorder is not None else history()

    def _burn(self, points: List[List[float]], threshold: float,
              window_s: float, now_ms: float) -> Optional[float]:
        """Burn rate over one window, or None when the window holds no
        samples (no evidence → no verdict)."""
        cutoff = now_ms - window_s * 1000.0
        # Clock skew: future-stamped samples count as "now", not never.
        windowed = [min(ts, now_ms) for ts, _ in points]
        in_window = [v for (ts, v), wts in zip(points, windowed)
                     if wts >= cutoff]
        if not in_window:
            return None
        bad = sum(1 for v in in_window if v > threshold)
        return (bad / len(in_window)) / self.error_budget

    def evaluate(self) -> List[Dict[str, Any]]:
        """All (objective, sensor) burn verdicts; ``violating`` only when
        both windows meet the burn threshold."""
        now_ms = self._clock() * 1000.0
        hist = self._history()
        out: List[Dict[str, Any]] = []
        for obj in self.objectives:
            for sensor, points in hist.history(pattern=obj.pattern).items():
                if not points:
                    continue
                short = self._burn(points, obj.threshold,
                                   self.short_window_s, now_ms)
                long_ = self._burn(points, obj.threshold,
                                   self.long_window_s, now_ms)
                violating = (short is not None and long_ is not None
                             and short >= self.burn_threshold
                             and long_ >= self.burn_threshold)
                out.append({
                    "objective": obj.name,
                    "sensor": sensor,
                    "threshold": obj.threshold,
                    "worstValue": max(v for _, v in points),
                    "burnShort": round(short, 4) if short is not None else None,
                    "burnLong": round(long_, 4) if long_ is not None else None,
                    "violating": violating,
                })
        return out

    def violations(self) -> List[Dict[str, Any]]:
        return [v for v in self.evaluate() if v["violating"]]


class SloViolationDetector:
    """Detector-manager plugin: maps burn verdicts to anomalies."""

    def __init__(self, evaluator: SloEvaluator):
        self.evaluator = evaluator

    def detect(self) -> List[SloViolationAnomaly]:
        return [
            SloViolationAnomaly(
                objective=v["objective"],
                sensor=v["sensor"],
                threshold=v["threshold"],
                worst_value=v["worstValue"],
                burn_rate_short=v["burnShort"],
                burn_rate_long=v["burnLong"],
            )
            for v in self.evaluator.violations()
        ]


def evaluator_from_config(config, recorder: Optional[HistoryRecorder] = None,
                          clock=time.time) -> SloEvaluator:
    return SloEvaluator(
        objectives_from_config(config),
        error_budget=float(config.get("slo.error.budget")),
        short_window_s=float(config.get("slo.burn.window.short.s")),
        long_window_s=float(config.get("slo.burn.window.long.s")),
        burn_threshold=float(config.get("slo.burn.rate.threshold")),
        recorder=recorder,
        clock=clock,
    )
