"""Model-fidelity observatory: ingest telemetry + fingerprint flight recorder.

The solver/executor side is deeply instrumented (convergence recorder,
memory ledger, execution observatory) but the load monitor that *feeds*
them was a black box: completeness existed as point-in-time gauges with no
history and no lineage from a proposal back to the data quality it was
decided on.  This module closes the loop from the ingest side:

- **Fingerprint** — :meth:`ModelFidelityRecorder.record_fingerprint` runs
  at every model freeze / resident delta-apply and condenses the
  aggregator's completeness output into a ``ModelFingerprint`` dict:
  ``{generation, windowEndMs, ageMs, validWindows, validPartitionRatio,
  extrapolatedFraction (by kind), deadBrokers, capacitySource, kind}``.
  The optimizer stamps it onto every ``OptimizerResult`` / proposal, the
  executor journal and oplog carry its generation, and
  ``GET /execution_progress`` joins it into the live batch — so any
  executed move traces back to the model quality it was solved from.

- **Ingest telemetry** — the fetch/sample/aggregate pipeline reports
  per-fetch sample counts, dropped samples by cause (undecodable /
  inconsistent / out-of-order), window-close events with ingest→commit
  latency, and broker-liveness flaps.  Surfaced as ``Monitor.*`` sensors
  on ``/metrics`` (and thus the history rings), a bounded per-window
  quality ring on ``GET /model_quality``, and ``modelQualityState`` in
  ``GET /state``.

- **Staleness verdict** — :meth:`staleness_reason` checks the current
  fingerprint against ``anomaly.model.min.valid.partition.ratio`` /
  ``anomaly.model.max.age.ms``; the anomaly-fix dispatch IGNOREs fixes
  (audit reason ``stale_model``) and proposal responses carry an advisory
  ``modelStale`` flag when the verdict is non-None.

Everything is host-side bookkeeping over already-materialized numpy
completeness output: solver executables, jit cache keys, and proposal
cache keys are byte-identical with the recorder on or off (the PR-9/12/17
off-path discipline — asserted by tests/test_fidelity.py).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

LOG = logging.getLogger(__name__)

# Extrapolation kinds a fingerprint breaks its fraction down by (matches
# monitor.aggregator.Extrapolation members that fill values).
EXTRAPOLATION_KINDS = ("AVG_AVAILABLE", "AVG_ADJACENT", "FORECAST")

# Dropped-sample causes with a dedicated counter (Monitor.dropped-samples-*
# plus the ISSUE-named Monitor.out-of-order-samples).
DROP_CAUSES = ("undecodable", "inconsistent", "out_of_order")

_DROP_SENSOR = {
    "undecodable": "Monitor.dropped-samples-undecodable",
    "inconsistent": "Monitor.dropped-samples-inconsistent",
    "out_of_order": "Monitor.out-of-order-samples",
}


class ModelFidelityRecorder:
    """Bounded flight recorder of model fidelity: the per-window quality
    ring, the current/recent fingerprints, and the staleness verdict.

    Thresholds default to "gate disabled" (ratio 0.0, max age 0) so the
    recorder never vetoes self-healing unless ``anomaly.model.*`` keys are
    configured; the advisory ``modelStale`` flag follows the same verdict.
    """

    def __init__(self, enabled: bool = True, ring_size: int = 64,
                 min_valid_partition_ratio: float = 0.0,
                 max_age_ms: int = 0, clock=time.time):
        self.enabled = enabled
        self.min_valid_partition_ratio = float(min_valid_partition_ratio)
        self.max_age_ms = int(max_age_ms)
        self._clock = clock
        self._lock = threading.Lock()
        self._fingerprint: Optional[Dict[str, Any]] = None
        self._fingerprints: deque = deque(maxlen=ring_size)  # freeze history
        self._windows: deque = deque(maxlen=ring_size)       # window closes
        self._flaps: deque = deque(maxlen=ring_size)         # liveness flips
        self._alive: Dict[int, bool] = {}
        self._freezes = 0
        self._delta_applies = 0
        self._last_fetch = {"partitionSamples": 0, "brokerSamples": 0,
                            "atMs": None}

    def configure(self, enabled: bool, ring_size: Optional[int] = None,
                  min_valid_partition_ratio: Optional[float] = None,
                  max_age_ms: Optional[int] = None) -> None:
        """Reconfigure in place (the singleton is referenced widely)."""
        with self._lock:
            self.enabled = enabled
            if ring_size is not None and ring_size != self._fingerprints.maxlen:
                self._fingerprints = deque(self._fingerprints,
                                           maxlen=ring_size)
                self._windows = deque(self._windows, maxlen=ring_size)
                self._flaps = deque(self._flaps, maxlen=ring_size)
            if min_valid_partition_ratio is not None:
                self.min_valid_partition_ratio = float(
                    min_valid_partition_ratio)
            if max_age_ms is not None:
                self.max_age_ms = int(max_age_ms)

    # -- ingest side -------------------------------------------------------

    def on_fetch(self, n_partition: int, n_broker: int) -> None:
        """One sampler fetch round's accepted sample counts."""
        from cruise_control_tpu.common.metrics import registry
        registry().counter("Monitor.fetched-samples").inc(
            int(n_partition) + int(n_broker))
        if not self.enabled:
            return
        with self._lock:
            self._last_fetch = {"partitionSamples": int(n_partition),
                                "brokerSamples": int(n_broker),
                                "atMs": round(self._clock() * 1000.0, 1)}

    def on_dropped(self, cause: str, count: int = 1) -> None:
        """A sample dropped before aggregation, by cause (always counted —
        the drop is pipeline behavior, not observatory bookkeeping)."""
        from cruise_control_tpu.common.metrics import registry
        sensor = _DROP_SENSOR.get(cause)
        if sensor is None:
            raise ValueError(f"unknown drop cause {cause!r}")
        registry().counter(sensor).inc(int(count))

    def on_window_close(self, window: int, window_ms: int,
                        now_ms: Optional[float] = None) -> None:
        """A completed window rolled out of "active": bump the close
        counter, record ingest→commit latency (wall time from the window's
        end to the roll that committed it), ring the event, and push an
        event-driven history sample so ``/metrics/history`` captures every
        transition even when windows close faster than the sampler
        interval (bounded by the history ring's own cap)."""
        from cruise_control_tpu.common.metrics import registry
        reg = registry()
        now_ms = self._clock() * 1000.0 if now_ms is None else float(now_ms)
        window_end_ms = (int(window) + 1) * int(window_ms)
        latency_ms = max(now_ms - window_end_ms, 0.0)
        reg.counter("Monitor.window-closes").inc()
        reg.timer("Monitor.ingest-commit-latency-ms").update_ms(latency_ms)
        from cruise_control_tpu.obsvc.history import history
        history().record_event("Monitor.window-closes",
                               float(reg.counter("Monitor.window-closes").count),
                               ts_ms=now_ms)
        if not self.enabled:
            return
        with self._lock:
            self._windows.append({
                "window": int(window),
                "windowEndMs": window_end_ms,
                "closedAtMs": round(now_ms, 1),
                "ingestCommitMs": round(latency_ms, 1),
            })

    def record_liveness(self, alive: Dict[int, bool],
                        now_ms: Optional[float] = None) -> None:
        """Broker-liveness flap detector: every alive-bit flip against the
        last observed state counts as one flap."""
        from cruise_control_tpu.common.metrics import registry
        now_ms = self._clock() * 1000.0 if now_ms is None else float(now_ms)
        with self._lock:
            flips = [(b, a) for b, a in alive.items()
                     if b in self._alive and self._alive[b] != bool(a)]
            self._alive = {b: bool(a) for b, a in alive.items()}
            if self.enabled:
                for broker, now_alive in flips:
                    self._flaps.append({"broker": int(broker),
                                        "alive": bool(now_alive),
                                        "atMs": round(now_ms, 1)})
        if flips:
            registry().counter("Monitor.broker-liveness-flaps").inc(len(flips))

    # -- fingerprint side --------------------------------------------------

    def record_fingerprint(self, completeness, window_ms: int,
                           dead_brokers: Sequence[int] = (),
                           capacity_source: str = "",
                           kind: str = "freeze",
                           now_ms: Optional[float] = None
                           ) -> Optional[Dict[str, Any]]:
        """Condense one aggregation's completeness into a fingerprint and
        make it current.  ``kind`` is ``freeze`` (full model build) or
        ``delta`` (resident builder delta-apply).  Returns the fingerprint
        (a plain dict — safe to stamp onto results), or None when off."""
        if not self.enabled:
            return None
        from cruise_control_tpu.common.metrics import registry
        now_ms = self._clock() * 1000.0 if now_ms is None else float(now_ms)
        valid_windows = list(getattr(completeness, "valid_windows", []) or [])
        window_end_ms = ((max(valid_windows) + 1) * int(window_ms)
                         if valid_windows else None)
        denom = max(int(getattr(completeness, "num_entity_windows", 0)), 1)
        by_kind = {
            "AVG_AVAILABLE": getattr(completeness,
                                     "num_windows_avg_available", 0) / denom,
            "AVG_ADJACENT": getattr(completeness,
                                    "num_windows_avg_adjacent", 0) / denom,
            "FORECAST": getattr(completeness,
                                "num_windows_forecast", 0) / denom,
        }
        fp = {
            "generation": int(getattr(completeness, "generation", 0)),
            "windowEndMs": window_end_ms,
            "ageMs": (round(max(now_ms - window_end_ms, 0.0), 1)
                      if window_end_ms is not None else None),
            "validWindows": len(valid_windows),
            "validPartitionRatio": round(
                float(getattr(completeness, "valid_entity_ratio", 0.0)), 6),
            "extrapolatedFraction": {k: round(v, 6)
                                     for k, v in by_kind.items()},
            "deadBrokers": sorted(int(b) for b in dead_brokers),
            "capacitySource": capacity_source,
            "kind": kind,
            "frozenAtMs": round(now_ms, 1),
        }
        with self._lock:
            self._fingerprint = fp
            self._fingerprints.append(fp)
            if kind == "delta":
                self._delta_applies += 1
            else:
                self._freezes += 1
        registry().counter("Monitor.model-delta-applies" if kind == "delta"
                           else "Monitor.model-freezes").inc()
        return fp

    def current_fingerprint(self, now_ms: Optional[float] = None
                            ) -> Optional[Dict[str, Any]]:
        """The latest fingerprint with ``ageMs`` recomputed at read time."""
        with self._lock:
            fp = self._fingerprint
        if fp is None:
            return None
        now_ms = self._clock() * 1000.0 if now_ms is None else float(now_ms)
        out = dict(fp)
        if out.get("windowEndMs") is not None:
            out["ageMs"] = round(max(now_ms - out["windowEndMs"], 0.0), 1)
        return out

    def fingerprint_age_ms(self) -> float:
        """Gauge read: age of the current fingerprint's newest window; 0.0
        before the first fingerprint (no evidence is not staleness)."""
        fp = self.current_fingerprint()
        if fp is None or fp.get("ageMs") is None:
            return 0.0
        return float(fp["ageMs"])

    def valid_partition_ratio(self) -> float:
        fp = self.current_fingerprint()
        return float(fp["validPartitionRatio"]) if fp else 0.0

    def invalid_partition_ratio(self) -> float:
        """Inverted validity for the model-validity SLO objective ("bad" is
        ABOVE threshold); 0.0 before the first fingerprint, so cold boot
        and fidelity-off runs never burn."""
        fp = self.current_fingerprint()
        if fp is None:
            return 0.0
        return max(1.0 - float(fp["validPartitionRatio"]), 0.0)

    def extrapolated_fraction(self) -> float:
        fp = self.current_fingerprint()
        if fp is None:
            return 0.0
        return float(sum(fp["extrapolatedFraction"].values()))

    def staleness_reason(self, now_ms: Optional[float] = None
                         ) -> Optional[str]:
        """Non-None when the current fingerprint violates a configured
        ``anomaly.model.*`` threshold.  Returns a short reason string for
        audit entries; None when fresh, when thresholds are unset (their
        defaults), or when no fingerprint exists yet (the completeness
        gate upstream already covers the cold-start case)."""
        fp = self.current_fingerprint(now_ms)
        if fp is None:
            return None
        if (self.min_valid_partition_ratio > 0.0
                and fp["validPartitionRatio"] < self.min_valid_partition_ratio):
            return (f"valid-partition-ratio {fp['validPartitionRatio']:.3f} "
                    f"< {self.min_valid_partition_ratio}")
        if (self.max_age_ms > 0 and fp.get("ageMs") is not None
                and fp["ageMs"] > self.max_age_ms):
            return f"fingerprint-age {fp['ageMs']:.0f}ms > {self.max_age_ms}ms"
        return None

    def record_stale_gate(self) -> None:
        """One self-healing fix vetoed on a stale model."""
        from cruise_control_tpu.common.metrics import registry
        registry().counter("Monitor.stale-model-gates").inc()

    # -- read side ---------------------------------------------------------

    def quality(self) -> Dict[str, Any]:
        """The ``GET /model_quality`` payload."""
        with self._lock:
            windows = list(self._windows)
            fps = list(self._fingerprints)
            flaps = list(self._flaps)
            last_fetch = dict(self._last_fetch)
        return {
            "enabled": self.enabled,
            "fingerprint": self.current_fingerprint(),
            "stale": self.staleness_reason(),
            "thresholds": {
                "minValidPartitionRatio": self.min_valid_partition_ratio,
                "maxAgeMs": self.max_age_ms,
            },
            "windowQuality": windows,
            "recentFingerprints": fps,
            "livenessFlaps": flaps,
            "lastFetch": last_fetch,
        }

    def state_summary(self) -> Dict[str, Any]:
        """The ``modelQualityState`` section of GET /state."""
        with self._lock:
            freezes = self._freezes
            deltas = self._delta_applies
            retained = len(self._windows)
            maxlen = self._windows.maxlen
        fp = self.current_fingerprint()
        return {
            "enabled": self.enabled,
            "fingerprint": fp,
            "stale": self.staleness_reason(),
            "modelFreezes": freezes,
            "modelDeltaApplies": deltas,
            "windowsRetained": retained,
            "ringSize": maxlen,
        }

    def reset(self) -> None:
        with self._lock:
            self._fingerprint = None
            self._fingerprints.clear()
            self._windows.clear()
            self._flaps.clear()
            self._alive = {}
            self._freezes = 0
            self._delta_applies = 0
            self._last_fetch = {"partitionSamples": 0, "brokerSamples": 0,
                                "atMs": None}


_RECORDER = ModelFidelityRecorder()


def fidelity() -> ModelFidelityRecorder:
    return _RECORDER


def register_sensors() -> None:
    """Idempotently (re-)register the Monitor.* fidelity family on the
    process registry.  Gauges exist recorder-on or -off (they read 0.0
    before the first fingerprint), and the counters are materialized
    eagerly so the sensor-drift guard sees them on a fresh boot."""
    from cruise_control_tpu.common.metrics import registry
    reg = registry()
    reg.gauge("Monitor.fingerprint-age-ms",
              lambda: fidelity().fingerprint_age_ms())
    reg.gauge("Monitor.valid-partition-ratio",
              lambda: fidelity().valid_partition_ratio())
    reg.gauge("Monitor.invalid-partition-ratio",
              lambda: fidelity().invalid_partition_ratio())
    reg.gauge("Monitor.extrapolated-fraction",
              lambda: fidelity().extrapolated_fraction())
    reg.counter("Monitor.fetched-samples")
    reg.counter("Monitor.stored-samples")
    for sensor in _DROP_SENSOR.values():
        reg.counter(sensor)
    reg.counter("Monitor.window-closes")
    reg.timer("Monitor.ingest-commit-latency-ms")
    reg.counter("Monitor.broker-liveness-flaps")
    reg.counter("Monitor.model-freezes")
    reg.counter("Monitor.model-delta-applies")
    reg.counter("Monitor.stale-model-gates")


register_sensors()
