"""Operation audit log.

A dedicated ``cruise_control_tpu.operations`` logger recording every
state-changing operation the service performs — one line per lifecycle
event, machine-grep-able ``key=value`` pairs:

    op=start task=<uuid> principal=<who> endpoint=<ep> params=<query>
    op=finish task=<uuid> ... partial=true
    op=abort task=<uuid> ... reason=user
    op=preempted task=<uuid> ... reason=deadline

Wired at the three places state changes originate:

- servlet user-task dispatch (task created / finished / aborted / preempted),
- executor batch start/finish (proposal execution actually touching the
  cluster),
- anomaly-fix dispatch (self-healing operations nobody asked for have the
  highest audit value).

Operators route it independently of the service log (it propagates to the
root handlers by default; attach a handler to ``cruise_control_tpu.operations``
to split it out).  The principal rides a contextvar set by the servlet's
auth gate, so deeply nested call sites never thread it explicitly.
"""

from __future__ import annotations

import logging
from contextvars import ContextVar

OPLOG = logging.getLogger("cruise_control_tpu.operations")

# Outcomes a record may carry (the contract documented in OPERATIONS.md).
OUTCOMES = ("start", "finish", "abort", "preempted")

_principal: ContextVar[str] = ContextVar("cc_operation_principal",
                                         default="anonymous")


def set_principal(name: str):
    """Bind the authenticated principal for this request context; returns
    the contextvar token (callers may reset, but request-scoped contexts
    are discarded wholesale so most never need to)."""
    return _principal.set(name or "anonymous")


def current_principal() -> str:
    return _principal.get()


# Request correlation: the servlet binds the request's X-Request-ID here
# (minted when absent), and the UserTaskManager worker inherits it via
# contextvars.copy_context() — so the executor can label its batch span,
# journal batch_start line, and flight-recorder batch with the request that
# asked for the moves (the multi-tenant attribution hook).
_request_id: ContextVar[str | None] = ContextVar("cc_operation_request_id",
                                                 default=None)


def set_request_id(request_id: str | None):
    """Bind the correlation id for this request context; returns the
    contextvar token."""
    return _request_id.set(request_id or None)


def current_request_id() -> str | None:
    return _request_id.get()


def _fmt(value) -> str:
    s = str(value)
    # One event per line is the whole point — never let a value break it.
    s = s.replace("\n", "\\n").replace("\r", "")
    if " " in s or s == "":
        return '"%s"' % s.replace('"', "'")
    return s


def record(outcome: str, *, task_id: str = "-", endpoint: str = "-",
           params: str = "", principal: str | None = None, **extra) -> None:
    """Emit one operation event.  ``outcome`` is one of :data:`OUTCOMES`;
    ``extra`` key=value pairs (reason=, executed=, anomaly=, ...) append in
    sorted order so lines diff stably."""
    if outcome not in OUTCOMES:
        raise ValueError(f"unknown operation outcome {outcome!r}")
    fields = {
        "op": outcome,
        "task": task_id or "-",
        "principal": principal if principal is not None else _principal.get(),
        "endpoint": endpoint,
        "params": params,
    }
    fields.update({k: v for k, v in sorted(extra.items()) if v is not None})
    OPLOG.info(" ".join(f"{k}={_fmt(v)}" for k, v in fields.items()))
