"""Self-healing audit log: detector decision → facade action → execution outcome.

The reference scatters the self-healing story across the operation logger
(``cruisecontrol.operation``), per-type anomaly rates, and executor state;
reconstructing "what did the detector decide, what did it run, and how did
that execution end" means grepping logs.  This bounded in-memory log keeps
the three stages of each self-healing attempt in one queryable record,
surfaced as ``selfHealingAudit`` inside the ``AnomalyDetectorState``
substate of ``GET /state``.

Stages (all best-effort, never raising into the caller):

1. :meth:`AuditLog.record` — the detector manager logs every resolved
   anomaly with its decision (``IGNORED`` / ``CHECK`` / ``FIX``).
2. :meth:`AuditLog.set_action` — the facade's ``_fix_anomaly`` dispatcher
   annotates the newest open entry of that anomaly type with the concrete
   operation it started (``rebalance``, ``remove_broker``, ...).
3. :meth:`AuditLog.attach_execution_outcome` — the executor's batch
   teardown attaches completed/dead/aborted counts to the newest entry
   still waiting on an execution (entries whose fix never started an
   execution simply keep ``executionOutcome: null``).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_IDS = itertools.count(1)


class AuditLog:
    def __init__(self, maxlen: int = 256):
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=maxlen)

    def configure(self, maxlen: int) -> None:
        with self._lock:
            if maxlen != self._entries.maxlen:
                self._entries = deque(self._entries, maxlen=maxlen)

    def record(self, anomaly_type: str, description: Any,
               decision: str) -> int:
        entry = {
            "id": next(_IDS),
            "timestampMs": int(time.time() * 1000),
            "anomalyType": anomaly_type,
            "description": description,
            "decision": decision,
            "action": None,
            "outcome": None,
            "executionOutcome": None,
        }
        with self._lock:
            self._entries.append(entry)
        return entry["id"]

    def set_action(self, anomaly_type: str, action: str) -> None:
        """Annotate the newest action-less entry of this type (stage 2)."""
        with self._lock:
            for entry in reversed(self._entries):
                if (entry["anomalyType"] == anomaly_type
                        and entry["action"] is None):
                    entry["action"] = action
                    return

    def set_outcome(self, entry_id: int, outcome: str) -> None:
        with self._lock:
            for entry in reversed(self._entries):
                if entry["id"] == entry_id:
                    entry["outcome"] = outcome
                    return

    def attach_execution_outcome(self, completed: int, dead: int,
                                 aborted: int, moved_mb: float,
                                 provenance_paths: Optional[Dict[str, int]]
                                 = None) -> None:
        """Stage 3: executor batch finished.  Attach to the newest entry
        whose fix started an execution and has no outcome yet; executions
        started directly by users (no pending audit entry) are dropped.
        ``provenance_paths`` (execution observatory) is the batch's
        relax/rounding/repair/greedy move histogram — how the fix's moves
        were derived, joined to how they landed."""
        with self._lock:
            for entry in reversed(self._entries):
                if (entry["outcome"] == "FIX_STARTED"
                        and entry["executionOutcome"] is None):
                    entry["executionOutcome"] = {
                        "completed": completed,
                        "dead": dead,
                        "aborted": aborted,
                        "movedMB": round(moved_mb, 1),
                        "timestampMs": int(time.time() * 1000),
                    }
                    if provenance_paths:
                        entry["executionOutcome"]["provenancePaths"] = dict(
                            provenance_paths)
                    return

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._entries]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_AUDIT = AuditLog()


def audit_log() -> AuditLog:
    return _AUDIT
