"""``POST /profile`` backing: one-shot ``jax.profiler`` capture windows.

Wraps ``jax.profiler.start_trace``/``stop_trace`` with a non-reentrant
lock (the XLA profiler is a process singleton — overlapping captures
abort) and writes a TensorBoard-loadable trace directory per capture:
``<trace.profile.dir>/profile-<epoch_ms>``.  View with
``tensorboard --logdir <dir>`` → Profile plugin, or feed the contained
``*.trace.json.gz`` to Perfetto.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Dict, Optional

MAX_DURATION_S = 600.0

_LOCK = threading.Lock()
_DEFAULT_DIR: Optional[str] = None


class ProfileInProgress(RuntimeError):
    """A capture window is already open (the XLA profiler is a singleton)."""


def configure(profile_dir: str) -> None:
    global _DEFAULT_DIR
    _DEFAULT_DIR = profile_dir or None


def default_dir() -> str:
    if _DEFAULT_DIR:
        return _DEFAULT_DIR
    return os.path.join(tempfile.gettempdir(), "cruise_control_tpu_profiles")


def capture(duration_s: float,
            out_dir: Optional[str] = None) -> Dict[str, Any]:
    """Block for ``duration_s`` while the JAX profiler records all device
    + host activity, then return the trace directory."""
    if not (0.0 < duration_s <= MAX_DURATION_S):
        raise ValueError(
            f"duration_s must be in (0, {MAX_DURATION_S:g}], "
            f"got {duration_s!r}")
    if not _LOCK.acquire(blocking=False):
        raise ProfileInProgress("a profile capture is already running")
    try:
        import jax

        trace_dir = os.path.join(out_dir or default_dir(),
                                 f"profile-{int(time.time() * 1000)}")
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        try:
            time.sleep(duration_s)
        finally:
            jax.profiler.stop_trace()
        return {"trace_dir": trace_dir, "duration_s": duration_s}
    finally:
        _LOCK.release()
