"""``POST /profile`` backing: one-shot ``jax.profiler`` capture windows.

Wraps ``jax.profiler.start_trace``/``stop_trace`` with a non-reentrant
lock (the XLA profiler is a process singleton — overlapping captures
abort) and writes a TensorBoard-loadable trace directory per capture:
``<trace.profile.dir>/profile-<epoch_ms>``.  View with
``tensorboard --logdir <dir>`` → Profile plugin, or feed the contained
``*.trace.json.gz`` to Perfetto.

Two entry points share the same singleton lock:

* :func:`capture` — synchronous (scripts, tests): block through the
  window, return the trace dir.
* :func:`start_async` — ``POST /profile``: open the window on a daemon
  thread and return immediately; :func:`status` is the pollable
  busy/done/trace_dir view backing ``GET /profile``.  A second start
  while a window is open (either entry point) raises
  :class:`ProfileInProgress` — the 409 contract.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Dict, Optional

MAX_DURATION_S = 600.0

_LOCK = threading.Lock()
_DEFAULT_DIR: Optional[str] = None
# Last/current async capture, guarded by _STATE_LOCK: {"busy", "done",
# "trace_dir", "duration_s", "started_ms", "error"}.
_STATE_LOCK = threading.Lock()
_ASYNC_STATE: Dict[str, Any] = {}


class ProfileInProgress(RuntimeError):
    """A capture window is already open (the XLA profiler is a singleton)."""


def configure(profile_dir: str) -> None:
    global _DEFAULT_DIR
    _DEFAULT_DIR = profile_dir or None
    with _STATE_LOCK:
        _ASYNC_STATE.clear()


def default_dir() -> str:
    if _DEFAULT_DIR:
        return _DEFAULT_DIR
    return os.path.join(tempfile.gettempdir(), "cruise_control_tpu_profiles")


def _check_duration(duration_s: float) -> None:
    if not (0.0 < duration_s <= MAX_DURATION_S):
        raise ValueError(
            f"duration_s must be in (0, {MAX_DURATION_S:g}], "
            f"got {duration_s!r}")


def _capture_locked(duration_s: float, trace_dir: str) -> None:
    """Run one capture window; caller holds ``_LOCK``."""
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    try:
        time.sleep(duration_s)
    finally:
        jax.profiler.stop_trace()


def capture(duration_s: float,
            out_dir: Optional[str] = None) -> Dict[str, Any]:
    """Block for ``duration_s`` while the JAX profiler records all device
    + host activity, then return the trace directory."""
    _check_duration(duration_s)
    if not _LOCK.acquire(blocking=False):
        raise ProfileInProgress("a profile capture is already running")
    try:
        trace_dir = os.path.join(out_dir or default_dir(),
                                 f"profile-{int(time.time() * 1000)}")
        _capture_locked(duration_s, trace_dir)
        return {"trace_dir": trace_dir, "duration_s": duration_s}
    finally:
        _LOCK.release()


def start_async(duration_s: float,
                out_dir: Optional[str] = None) -> Dict[str, Any]:
    """Open a capture window on a daemon thread and return immediately
    (the ``POST /profile`` 202 path).  Raises :class:`ProfileInProgress`
    while any window — sync or async — is open."""
    _check_duration(duration_s)
    if not _LOCK.acquire(blocking=False):
        raise ProfileInProgress("a profile capture is already running")
    # _LOCK is held from here until the worker releases it: status() and
    # further starts see busy for the whole window.
    trace_dir = os.path.join(out_dir or default_dir(),
                             f"profile-{int(time.time() * 1000)}")
    with _STATE_LOCK:
        _ASYNC_STATE.clear()
        _ASYNC_STATE.update(busy=True, done=False, trace_dir=trace_dir,
                            duration_s=duration_s,
                            started_ms=int(time.time() * 1000), error=None)

    def worker():
        error = None
        try:
            _capture_locked(duration_s, trace_dir)
        except Exception as e:   # noqa: BLE001 — surfaced via status()
            error = f"{type(e).__name__}: {e}"
        finally:
            # State first, lock second: a new start_async can only win the
            # lock after this capture's outcome is recorded.
            with _STATE_LOCK:
                _ASYNC_STATE.update(busy=False, done=error is None,
                                    error=error)
            _LOCK.release()

    threading.Thread(target=worker, name="profile-capture",
                     daemon=True).start()
    return {"trace_dir": trace_dir, "duration_s": duration_s}


def status() -> Dict[str, Any]:
    """Pollable capture state for ``GET /profile``: ``busy`` while any
    window is open, plus the last async capture's outcome."""
    with _STATE_LOCK:
        state = dict(_ASYNC_STATE)
    state.setdefault("busy", False)
    state.setdefault("done", False)
    state.setdefault("trace_dir", None)
    # A synchronous capture() also holds the singleton lock; report it.
    if not state["busy"] and _LOCK.locked():
        state["busy"] = True
    return state
