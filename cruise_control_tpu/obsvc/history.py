"""Sensor history rings (obs.history.*).

Sensors were point-in-time snapshots: a scrape sees the current value and
nothing evaluates trends, so latency/solve-time regressions were only
visible by rerunning bench.  This module runs an interval sampler thread
that snapshots the :class:`~cruise_control_tpu.common.metrics.MetricRegistry`
into bounded per-sensor time-series rings:

- one scalar per sensor per sample — counters record ``count``, timers
  record ``p99_ms`` and gauges their value — keeping a ring entry tiny;
- each timer additionally feeds ``<name>.p50_ms`` / ``<name>.max_ms``
  sibling rings (the bare name stays p99 — burn-rate windows and existing
  dashboards read it unchanged);
- rings are bounded (``obs.history.ring.size``), oldest samples evicted;
- the sampler's own liveness is observable: every snapshot bumps the
  ``Obs.history-samples`` counter.

Read via ``GET /metrics/history``; the SLO evaluator (obsvc/slo.py) runs
its burn-rate windows over these rings.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from cruise_control_tpu.common.metrics import registry

SAMPLES_SENSOR = "Obs.history-samples"

# Extra per-timer quantile rings recorded under dotted sibling names —
# ring names, not registry sensors, so they stay invisible to the
# sensor-drift guard and to SLO patterns anchored on ``*-timer``.
TIMER_SIBLING_STATS = ("p50_ms", "max_ms")


def _scalar(record: Dict[str, Any]) -> Optional[float]:
    """The one number a history ring keeps per sensor per sample."""
    kind = record.get("type")
    if kind == "counter":
        return float(record.get("count", 0))
    if kind == "timer":
        return float(record.get("p99_ms", record.get("mean_ms", 0.0)))
    value = record.get("value")
    if isinstance(value, bool):
        return float(int(value))
    if isinstance(value, (int, float)):
        return float(value)
    return None     # errored gauge / non-numeric value: no sample


class HistoryRecorder:
    """Interval sampler thread snapshotting the registry into bounded
    per-sensor time-series rings."""

    def __init__(self, interval_s: float = 10.0, ring_size: int = 360,
                 clock=time.time):
        self.interval_s = interval_s
        self.ring_size = ring_size
        self._clock = clock
        self._series: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Materialized at construction so the sensor-drift guard sees the
        # self-sensor on a fresh boot, before the first interval elapses.
        self._samples_counter = registry().counter(SAMPLES_SENSOR)

    def configure(self, interval_s: float, ring_size: int) -> None:
        """Reconfigure in place (the singleton is referenced widely).  A
        shrunk ring size applies to existing rings on their next append."""
        with self._lock:
            self.interval_s = interval_s
            if ring_size != self.ring_size:
                self.ring_size = ring_size
                self._series = {name: deque(ring, maxlen=ring_size)
                                for name, ring in self._series.items()}

    # -- sampling ----------------------------------------------------------

    def sample_once(self) -> int:
        """Take one registry snapshot into the rings; returns sensors
        sampled.  Also the test seam — no thread required."""
        snap = registry().snapshot()
        ts_ms = round(self._clock() * 1000.0, 1)
        n = 0
        with self._lock:
            for name, record in snap.items():
                value = _scalar(record)
                if value is None:
                    continue
                self._append(name, ts_ms, value)
                n += 1
                if record.get("type") == "timer":
                    for stat in TIMER_SIBLING_STATS:
                        v = record.get(stat)
                        if isinstance(v, (int, float)):
                            self._append(f"{name}.{stat}", ts_ms, float(v))
        self._samples_counter.inc()
        return n

    def _append(self, name: str, ts_ms: float, value: float) -> None:
        """Caller holds ``self._lock``."""
        ring = self._series.get(name)
        if ring is None:
            ring = self._series[name] = deque(maxlen=self.ring_size)
        ring.append((ts_ms, value))

    def record_event(self, name: str, value: float,
                     ts_ms: Optional[float] = None) -> None:
        """Event-driven sample hook: push one point into ``name``'s ring
        outside the interval sampler, so transitions faster than the
        sampling interval (e.g. monitor window closes) still land in
        ``/metrics/history``.  Bounded by the ring's existing cap."""
        if ts_ms is None:
            ts_ms = round(self._clock() * 1000.0, 1)
        with self._lock:
            self._append(name, float(ts_ms), float(value))

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:   # noqa: BLE001 — sampler must never die silently
                import logging
                logging.getLogger(__name__).exception("history sample failed")

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="sensor-history")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- read side ---------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str) -> List[List[float]]:
        """[[ts_ms, value], ...] oldest first; empty for unknown sensors."""
        with self._lock:
            ring = self._series.get(name)
            return [list(p) for p in ring] if ring else []

    def history(self, pattern: Optional[str] = None,
                since_ms: Optional[float] = None) -> Dict[str, List]:
        """Rings matching an fnmatch ``pattern`` (all when None), optionally
        truncated to samples at/after ``since_ms``."""
        with self._lock:
            names = [n for n in self._series
                     if pattern is None or fnmatch.fnmatch(n, pattern)]
            out = {n: [list(p) for p in self._series[n]] for n in names}
        if since_ms is not None:
            out = {n: [p for p in pts if p[0] >= since_ms]
                   for n, pts in out.items()}
        return out

    # Bound on glob-query responses: a ``sensor=*`` against a service with
    # hundreds of rings must not serialize them all by default.
    DEFAULT_SERIES_LIMIT = 64
    MAX_SERIES_LIMIT = 1024

    def history_bounded(self, pattern: Optional[str] = None,
                        since_ms: Optional[float] = None,
                        limit: int = DEFAULT_SERIES_LIMIT):
        """:meth:`history` with a bounded series count: at most ``limit``
        rings (name-sorted, capped at ``MAX_SERIES_LIMIT``); the second
        return value flags whether matches were dropped."""
        limit = max(1, min(int(limit), self.MAX_SERIES_LIMIT))
        out = self.history(pattern=pattern, since_ms=since_ms)
        if len(out) <= limit:
            return out, False
        kept = sorted(out)[:limit]
        return {n: out[n] for n in kept}, True

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


_HISTORY = HistoryRecorder()


def history() -> HistoryRecorder:
    return _HISTORY
