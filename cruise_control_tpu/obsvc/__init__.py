"""Observability service: span tracing, profiler capture, self-healing audit.

Public surface:

* :func:`tracer` — the process :class:`~cruise_control_tpu.obsvc.tracer.Tracer`
  singleton (disabled by default; ``span()`` is a shared no-op until
  ``trace.enabled=true``).
* :func:`audit_log` — the bounded self-healing audit log (always on; a
  deque append per anomaly decision).
* :mod:`~cruise_control_tpu.obsvc.profiler` — ``POST /profile`` captures.
* :func:`configure` — apply ``trace.*`` config keys at service build time.
"""

from __future__ import annotations

from cruise_control_tpu.obsvc.audit import AuditLog, audit_log
from cruise_control_tpu.obsvc.tracer import Span, Tracer, tracer

__all__ = ["AuditLog", "Span", "Tracer", "audit_log", "configure",
           "tracer"]


def configure(config) -> Tracer:
    """Wire ``trace.*`` keys into the obsvc singletons.

    Called from ``main.build_app`` right after the compile service is
    configured; safe to call repeatedly (tests rebuild apps in-process).
    """
    from cruise_control_tpu.obsvc import profiler

    tr = tracer()
    tr.configure(enabled=bool(config.get("trace.enabled")),
                 ring_size=int(config.get("trace.ring.size")))
    audit_log().configure(maxlen=int(config.get("trace.audit.log.size")))
    profiler.configure(str(config.get("trace.profile.dir") or ""))
    return tr
