"""Observability service: span tracing, profiler capture, self-healing audit,
solver convergence recording, sensor history rings and SLO evaluation.

Public surface:

* :func:`tracer` — the process :class:`~cruise_control_tpu.obsvc.tracer.Tracer`
  singleton (disabled by default; ``span()`` is a shared no-op until
  ``trace.enabled=true``).
* :func:`audit_log` — the bounded self-healing audit log (always on; a
  deque append per anomaly decision).
* :func:`convergence` — the solver convergence flight recorder (per-round
  curves; disabled until ``trace.solver.rounds=true``).
* :func:`execution` — the execution flight recorder (move provenance,
  throughput/ETA, AIMD tuner events; on by default,
  ``execution.observatory.enabled``; GET /execution_progress,
  ``Executor.*`` throughput sensors).
* :func:`fidelity` — the model-fidelity recorder (ingest telemetry,
  per-window quality ring, ModelFingerprint stamping and the staleness
  verdict; on by default, ``monitor.fidelity.enabled``;
  GET /model_quality, ``Monitor.*`` sensors).
* :func:`history` — the sensor history sampler (bounded per-sensor
  time-series rings; on by default, ``obs.history.enabled``).
* :func:`memory_ledger` — the device-buffer & executable-cost ledgers
  (``memory.enabled``; GET /memory, ``Memory.*`` sensors, the lane-dispatch
  headroom guard).
* :mod:`~cruise_control_tpu.obsvc.slo` — burn-rate SLO evaluation over the
  history rings, feeding ``SloViolationAnomaly`` into the detector.
* :mod:`~cruise_control_tpu.obsvc.profiler` — ``POST /profile`` captures.
* :func:`configure` — apply ``trace.*`` / ``obs.*`` / ``slo.*`` /
  ``memory.*`` config keys at service build time.
"""

from __future__ import annotations

from cruise_control_tpu.obsvc.audit import AuditLog, audit_log
from cruise_control_tpu.obsvc.convergence import ConvergenceRecorder, convergence
from cruise_control_tpu.obsvc.execution import (ExecutionFlightRecorder,
                                                execution)
from cruise_control_tpu.obsvc.fidelity import ModelFidelityRecorder, fidelity
from cruise_control_tpu.obsvc.history import HistoryRecorder, history
from cruise_control_tpu.obsvc.memory import (DeviceMemoryLedger,
                                             ExecutableCostLedger,
                                             cost_ledger, memory_ledger)
from cruise_control_tpu.obsvc.tracer import Span, Tracer, tracer

__all__ = ["AuditLog", "ConvergenceRecorder", "DeviceMemoryLedger",
           "ExecutableCostLedger", "ExecutionFlightRecorder",
           "HistoryRecorder", "ModelFidelityRecorder", "Span", "Tracer",
           "audit_log", "configure", "convergence", "cost_ledger",
           "execution", "fidelity", "history", "memory_ledger", "tracer"]


def configure(config) -> Tracer:
    """Wire ``trace.*`` / ``obs.*`` keys into the obsvc singletons.

    Called from ``main.build_app`` right after the compile service is
    configured; safe to call repeatedly (tests rebuild apps in-process).
    """
    # Lazy: solver imports obsvc.tracer mid-module, so obsvc cannot import
    # the solver at module level without closing the cycle.
    from cruise_control_tpu.analyzer import solver as _solver
    from cruise_control_tpu.obsvc import memory as _memory
    from cruise_control_tpu.obsvc import profiler

    tr = tracer()
    tr.configure(enabled=bool(config.get("trace.enabled")),
                 ring_size=int(config.get("trace.ring.size")))
    audit_log().configure(maxlen=int(config.get("trace.audit.log.size")))
    profiler.configure(str(config.get("trace.profile.dir") or ""))

    record_rounds = bool(config.get("trace.solver.rounds"))
    _solver.set_round_recording(record_rounds)
    convergence().configure(enabled=record_rounds,
                            ring_size=int(config.get("trace.solver.ring.size")))

    execution().configure(
        enabled=bool(config.get("execution.observatory.enabled")),
        ring_size=int(config.get("execution.history.ring.size")),
        alpha=float(config.get("execution.throughput.ewma.alpha")))

    fidelity().configure(
        enabled=bool(config.get("monitor.fidelity.enabled")),
        ring_size=int(config.get("monitor.fidelity.ring.size")),
        min_valid_partition_ratio=float(
            config.get("anomaly.model.min.valid.partition.ratio")),
        max_age_ms=int(config.get("anomaly.model.max.age.ms")))

    _memory.configure(config)

    hist = history()
    hist.configure(
        interval_s=float(config.get("obs.history.interval.ms")) / 1000.0,
        ring_size=int(config.get("obs.history.ring.size")))
    if bool(config.get("obs.history.enabled")):
        hist.start()
    else:
        hist.stop()
    return tr
