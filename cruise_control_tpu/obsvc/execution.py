"""Execution observatory: move provenance + data-plane flight recorder.

Closes the decision→data-plane loop the solver-side observability (PR-9
convergence recorder, PR-12 memory ledger) left open.  Two halves share one
recorder:

- **Analyzer half** — the optimizer stamps every ``ExecutionProposal`` with
  a provenance record: the goal that proposed it, the solve id from the
  convergence recorder, the path the placement change took
  (``relax`` / ``rounding`` / ``repair`` / ``greedy``), the goal's round
  count, and the per-move cost delta.  The relax fast path stashes its
  post-rounding placement here so the optimizer can split relax-stage moves
  from greedy-repair moves with a three-way diff.

- **Executor half** — a bounded flight recorder of the batch actually
  hitting the cluster: per-broker inflight moves, an EWMA of move-completion
  throughput (seconds-per-move), batch ETA, and the AIMD concurrency
  tuner's decisions with the signal that triggered each.

Everything is host-side bookkeeping over already-materialized numpy
snapshots and executor task state: the solver's executables and jit cache
keys are byte-identical with the recorder on or off (the PR-9/12 off-path
discipline — asserted by tests/test_execution_obs.py).

Read via ``GET /execution_progress``; a summary rides the
``executionState`` section of ``GET /state``; throughput surfaces as
``Executor.*`` gauges on ``/metrics`` (and thus the history rings).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

# Canonical provenance path labels, in pipeline order.  ``relax`` = changed
# by the fractional solve + rounding only; ``rounding`` = changed by the
# relax stage AND again by greedy repair; ``repair`` = changed by greedy
# repair of a rounded placement only; ``greedy`` = changed by a pure greedy
# solve (no relax fast path, fallback, or polish pass).
PATHS = ("relax", "rounding", "repair", "greedy")

_IDS = itertools.count(1)


def path_histogram(proposals: Sequence[Any]) -> Dict[str, int]:
    """Provenance-path counts for a proposal set; moves whose provenance is
    missing (recorder was off at solve time) count under ``unknown``."""
    hist: Dict[str, int] = {}
    for p in proposals:
        prov = getattr(p, "provenance", None)
        path = (prov or {}).get("path") or "unknown"
        hist[path] = hist.get(path, 0) + 1
    return hist


class ExecutionFlightRecorder:
    """Bounded flight recorder joining move provenance with live execution.

    The executor reports transitions through :meth:`on_transition` (its
    ``_transition`` choke point), so the recorder sees every task exactly
    once per state change; throughput and per-broker inflight counts are
    derived from those events, never from polling.
    """

    def __init__(self, enabled: bool = True, ring_size: int = 64,
                 alpha: float = 0.3):
        self.enabled = enabled
        self.alpha = float(alpha)
        self._ring: deque = deque(maxlen=ring_size)   # finished batches
        self._pending: List[Dict[str, Any]] = []      # drained by bench.py
        self._tuner: deque = deque(maxlen=ring_size)  # AIMD tuner events
        self._lock = threading.Lock()
        self._recorded = 0
        # Analyzer-side stash: goal name -> host copy of the post-rounding
        # placement, set by relax.py and consumed (popped) by the optimizer's
        # per-goal provenance diff.
        self._rounded: Dict[str, Any] = {}
        # Live batch state (executor side).
        self._batch: Optional[Dict[str, Any]] = None
        self._inflight: Dict[int, int] = {}   # broker id -> inflight moves
        self._in_progress = 0
        self._completed = 0
        self._ewma_spm: Optional[float] = None  # EWMA seconds-per-move
        self._last_completion_s: Optional[float] = None

    def configure(self, enabled: bool, ring_size: Optional[int] = None,
                  alpha: Optional[float] = None) -> None:
        """Reconfigure in place (the singleton is referenced widely)."""
        with self._lock:
            self.enabled = enabled
            if ring_size is not None and ring_size != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=ring_size)
                self._tuner = deque(self._tuner, maxlen=ring_size)
            if alpha is not None:
                self.alpha = float(alpha)

    # -- analyzer side: relax-stage stash ---------------------------------

    def stash_rounded(self, goal_name: str, rounded) -> None:
        """relax.py parks the post-rounding placement (host copy) here so
        the optimizer can attribute relax vs repair moves per partition."""
        if not self.enabled:
            return
        with self._lock:
            self._rounded[goal_name] = rounded

    def pop_rounded(self, goal_name: str):
        with self._lock:
            return self._rounded.pop(goal_name, None)

    def clear_rounded(self) -> None:
        with self._lock:
            self._rounded.clear()

    # -- executor side: batch lifecycle -----------------------------------

    def begin_batch(self, tasks: Sequence[Any],
                    principal: Optional[str] = None,
                    request_id: Optional[str] = None,
                    execution_id: Optional[int] = None) -> None:
        """Adopt a live task list at execution start.  ``tasks`` are the
        executor's ``ExecutionTask`` objects — the recorder keeps the refs
        and reads their ``state`` when asked for progress."""
        if not self.enabled:
            return
        hist = path_histogram([t.proposal for t in tasks])
        # The batch's model fingerprint: the first stamped proposal wins —
        # all tasks of one batch come from one solve (and thus one model
        # generation); None when the fidelity recorder was off at solve time.
        fingerprint = next((fp for fp in
                            (getattr(t.proposal, "fingerprint", None)
                             for t in tasks) if fp is not None), None)
        with self._lock:
            self._batch = {
                "executionId": (execution_id if execution_id is not None
                                else next(_IDS)),
                "startedMs": round(time.time() * 1000.0, 1),
                "principal": principal,
                "requestId": request_id,
                "total": len(tasks),
                "pathHistogram": hist,
                "tasks": list(tasks),
                "tunerIncreases": 0,
                "tunerDecreases": 0,
                "fingerprint": fingerprint,
            }
            self._inflight = {}
            self._in_progress = 0
            self._completed = 0
            self._ewma_spm = None
            self._last_completion_s = None

    def on_transition(self, task, to_state, now_ms: float) -> None:
        """One task state change (called from the executor's ``_transition``
        choke point, BEFORE the tracker mutates ``task.state`` — so
        ``task.state`` is still the from-state here).  Updates per-broker
        inflight counts and, on completion, the seconds-per-move EWMA."""
        if not self.enabled:
            return
        to_name = getattr(to_state, "name", str(to_state))
        from_name = getattr(task.state, "name", str(task.state))
        with self._lock:
            if self._batch is None:
                return
            brokers = task.brokers_involved
            if to_name == "IN_PROGRESS":
                self._in_progress += 1
                for b in brokers:
                    self._inflight[b] = self._inflight.get(b, 0) + 1
            elif from_name == "IN_PROGRESS":
                # Leaving IN_PROGRESS (completed / aborting / dead).
                self._in_progress = max(0, self._in_progress - 1)
                for b in brokers:
                    left = self._inflight.get(b, 0) - 1
                    if left > 0:
                        self._inflight[b] = left
                    else:
                        self._inflight.pop(b, None)
            if to_name == "COMPLETED":
                self._completed += 1
                now_s = now_ms / 1000.0
                if self._last_completion_s is not None:
                    dt = max(now_s - self._last_completion_s, 1e-6)
                    if self._ewma_spm is None:
                        self._ewma_spm = dt
                    else:
                        self._ewma_spm = (self.alpha * dt
                                          + (1.0 - self.alpha) * self._ewma_spm)
                self._last_completion_s = now_s

    def record_tuner(self, direction: str, signal: str, cap: int) -> None:
        """One AIMD concurrency-tuner decision (``increase`` on a healthy
        probe round, ``decrease`` on distress) with the triggering signal."""
        if not self.enabled:
            return
        from cruise_control_tpu.common.metrics import registry
        event = {
            "timestampMs": round(time.time() * 1000.0, 1),
            "direction": direction,
            "signal": signal,
            "cap": int(cap),
        }
        with self._lock:
            self._tuner.append(event)
            if self._batch is not None:
                key = ("tunerIncreases" if direction == "increase"
                       else "tunerDecreases")
                self._batch[key] += 1
        registry().counter(f"Executor.tuner-{direction}s").inc()

    def end_batch(self, completed: int, dead: int, aborted: int,
                  moved_mb: float) -> Optional[Dict[str, Any]]:
        """Close the live batch; returns (and rings) its summary."""
        if not self.enabled:
            return None
        with self._lock:
            b = self._batch
            if b is None:
                return None
            self._batch = None
            self._inflight = {}
            self._in_progress = 0
            now_ms = round(time.time() * 1000.0, 1)
            duration_ms = max(now_ms - b["startedMs"], 0.0)
            mps = (completed / (duration_ms / 1000.0)
                   if duration_ms > 0 and completed else 0.0)
            summary = {
                "id": next(_IDS),
                "executionId": b["executionId"],
                "timestampMs": now_ms,
                "durationMs": round(duration_ms, 1),
                "moves": b["total"],
                "completed": int(completed),
                "dead": int(dead),
                "aborted": int(aborted),
                "movedMb": round(float(moved_mb), 3),
                "movesPerSecond": round(mps, 4),
                "pathHistogram": b["pathHistogram"],
                "principal": b["principal"],
                "requestId": b["requestId"],
                "tunerIncreases": b["tunerIncreases"],
                "tunerDecreases": b["tunerDecreases"],
            }
            if b.get("fingerprint") is not None:
                summary["modelGeneration"] = b["fingerprint"].get("generation")
            self._ring.append(summary)
            self._pending.append(summary)
            self._recorded += 1
        return summary

    # -- read side ---------------------------------------------------------

    def seconds_per_move(self) -> float:
        """EWMA seconds-per-move of the live batch; 0.0 while idle, so the
        execution-throughput SLO objective never burns between batches."""
        with self._lock:
            if self._batch is None or self._ewma_spm is None:
                return 0.0
            return self._ewma_spm

    def moves_per_second(self) -> float:
        spm = self.seconds_per_move()
        return 1.0 / spm if spm > 0 else 0.0

    def eta_seconds(self) -> float:
        """Remaining-move count × EWMA seconds-per-move; 0.0 while idle or
        before the first two completions (no rate estimate yet)."""
        with self._lock:
            b = self._batch
            if b is None or self._ewma_spm is None:
                return 0.0
            remaining = max(b["total"] - self._completed, 0)
            return remaining * self._ewma_spm

    def inflight_moves(self) -> int:
        with self._lock:
            return self._in_progress

    def progress(self) -> Dict[str, Any]:
        """The ``GET /execution_progress`` payload: batch metadata joined
        with per-task provenance + live state, the throughput estimate, and
        recent tuner events / batch summaries."""
        with self._lock:
            ring = list(self._ring)
            tuner = list(self._tuner)
            b = self._batch
            out: Dict[str, Any] = {
                "enabled": self.enabled,
                "active": b is not None,
                "tunerEvents": tuner,
                "recentBatches": ring,
            }
            if b is None:
                return out
            tasks = []
            for t in b["tasks"]:
                p = t.proposal
                tasks.append({
                    "topicPartition": str(p.topic_partition),
                    "type": t.task_type.value,
                    "state": t.state.value,
                    "provenance": p.provenance,
                })
            remaining = max(b["total"] - self._completed, 0)
            spm = self._ewma_spm
            out["batch"] = {
                "executionId": b["executionId"],
                "startedMs": b["startedMs"],
                "principal": b["principal"],
                "requestId": b["requestId"],
                "total": b["total"],
                "pathHistogram": b["pathHistogram"],
                "tunerIncreases": b["tunerIncreases"],
                "tunerDecreases": b["tunerDecreases"],
            }
            if b.get("fingerprint") is not None:
                out["batch"]["modelFingerprint"] = b["fingerprint"]
            out["tasks"] = tasks
            out["throughput"] = {
                "completed": self._completed,
                "remaining": remaining,
                "inflight": self._in_progress,
                "secondsPerMove": round(spm, 4) if spm else None,
                "movesPerSecond": round(1.0 / spm, 4) if spm else None,
                "etaSeconds": round(remaining * spm, 2) if spm else None,
            }
            out["inflightPerBroker"] = {str(k): v
                                        for k, v in self._inflight.items()}
            return out

    def drain(self) -> List[Dict[str, Any]]:
        """Batch summaries added since the last drain (bench.py storm rows);
        the ring itself is untouched."""
        with self._lock:
            out, self._pending = self._pending, []
        return out

    def state_summary(self) -> Dict[str, Any]:
        """The ``executionState`` section of GET /state."""
        with self._lock:
            ring = list(self._ring)
            recorded = self._recorded
            maxlen = self._ring.maxlen
            active = self._batch is not None
            inflight = self._in_progress
        return {
            "enabled": self.enabled,
            "active": active,
            "inflight": inflight,
            "recorded": recorded,
            "retained": len(ring),
            "ringSize": maxlen,
            "lastBatch": ring[-1] if ring else None,
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pending.clear()
            self._tuner.clear()
            self._rounded.clear()
            self._batch = None
            self._inflight = {}
            self._in_progress = 0
            self._completed = 0
            self._ewma_spm = None
            self._last_completion_s = None
            self._recorded = 0


_RECORDER = ExecutionFlightRecorder()


def execution() -> ExecutionFlightRecorder:
    return _RECORDER


def register_sensors() -> None:
    """Idempotently (re-)register the throughput gauges on the process
    metric registry.  Gauges exist recorder-on or -off (they read 0.0 while
    idle/disabled), so ``/metrics`` and the history sampler always export
    the ``Executor.`` throughput family."""
    from cruise_control_tpu.common.metrics import registry
    reg = registry()
    reg.gauge("Executor.seconds-per-move",
              lambda: execution().seconds_per_move())
    reg.gauge("Executor.moves-per-second",
              lambda: execution().moves_per_second())
    reg.gauge("Executor.eta-seconds", lambda: execution().eta_seconds())
    reg.gauge("Executor.inflight-moves",
              lambda: float(execution().inflight_moves()))
    reg.counter("Executor.tuner-increases")
    reg.counter("Executor.tuner-decreases")


register_sensors()
