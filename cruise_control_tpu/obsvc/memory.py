"""Device-memory & executable-cost observatory (``memory.*``).

The observability stack sees *time* at kernel granularity (compilesvc
telemetry, spans, convergence curves) but was blind to *bytes*: nothing
tracked live device-buffer occupancy or per-executable peak/temp memory,
yet "what fits in HBM, and who owns it" is the gating question for the
multi-tenant resident pool and multi-device lane sharding.  This module
adds two host-side ledgers plus a dispatch guard — zero traced code, so
every jit cache key and executable stays byte-identical to a ledger-free
build (asserted in tests/test_memory.py):

* :class:`DeviceMemoryLedger` — per-subsystem live-bytes accounting.
  Subsystems post alloc/free/donate/pin/release events (resident model
  freezes and donations, lane-batch mask/placement blocks, warmup
  tensors); totals are reconciled against ``device.memory_stats()``
  where the backend exposes it (TPU/GPU; XLA:CPU returns None).
* :class:`ExecutableCostLedger` — per-executable compile-time cost rows
  keyed by the existing compilesvc bucket labels (``R…-C…[-L…]``).
  Populated from the solver's compile-detection seam: ``lowered`` mode
  (service default) re-lowers the jitted function on abstract avals and
  records ``cost_analysis()`` flops / bytes-accessed plus argument and
  output sizes; ``full`` mode (bench/profile opt-in) additionally AOT
  compiles and records ``memory_analysis()`` temp / generated-code
  bytes.  ``peak_bytes`` is the derived arg+out+temp+generated sum
  (``CompiledMemoryStats`` exposes no peak field).
* the **headroom guard** — the lane-chunk planner consults projected
  peak bytes per lane width and shrinks a what-if batch onto narrower
  chunks (or refuses the dispatch outright, degraded-style, never a
  crash) when the projection exceeds ``memory.headroom.fraction`` of
  the device budget.

Surfaces: ``GET /memory``, ``memoryState`` in ``/state``, ``Memory.*``
sensors (and thereby the ``/metrics/history`` rings + the
memory-headroom SLO objective), ``peak_bytes``/``temp_bytes`` columns
on bench rows and ``scripts/profile_solve.py`` goals.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from cruise_control_tpu.common.metrics import registry as _metric_registry

LOG = logging.getLogger(__name__)

# Canonical subsystem names (free-form strings are accepted; these are the
# ones the stack posts today and the ones docs/MEMORY.md documents).
SUBSYS_RESIDENT = "resident-model"
SUBSYS_LANES = "lane-batch"
SUBSYS_WARMUP = "warmup"

LIVE_BYTES_SENSOR = "Memory.live-bytes"
UTILIZATION_SENSOR = "Memory.device-utilization"
DRIFT_SENSOR = "Memory.reconcile-drift-bytes"
POSTS_SENSOR = "Memory.posts"
IMBALANCE_SENSOR = "Memory.post-imbalances"
SHRINKS_SENSOR = "Memory.headroom-shrinks"
REFUSALS_SENSOR = "Memory.headroom-refusals"
COST_ROWS_SENSOR = "Memory.cost-rows"
ANALYSIS_FAILURES_SENSOR = "Memory.analysis-failures"

ANALYSIS_MODES = ("off", "lowered", "full")


def measure_bytes(tree: Any) -> int:
    """Total device-relevant bytes of a pytree: the ``nbytes`` sum over
    array leaves (jax Arrays and numpy arrays; scalars/None are free).
    Works on donated/deleted jax Arrays too — shape/dtype metadata
    outlives the buffer, which is exactly what accounting needs."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            import numpy as np
            n = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        except Exception:   # noqa: BLE001 — exotic leaf: skip, never raise
            continue
        total += n
    return total


def _abstractify(tree: Any):
    """Map concrete array leaves to ShapeDtypeStructs so ``fn.lower`` never
    touches (possibly donated-and-deleted) device buffers; non-array leaves
    pass through unchanged so static/python arguments trace as they did."""
    import jax

    def one(leaf):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return leaf
        return jax.ShapeDtypeStruct(tuple(shape), dtype)

    return jax.tree_util.tree_map(one, tree)


class ExecutableCostLedger:
    """Per-executable compile-cost rows, keyed by compilesvc bucket label.

    ``observe_compile`` is called from the solver's compile-detection seam
    (``_CompileTracked``) AFTER a fresh XLA compile was measured; it is
    exception-safe and strictly host-side.  Each unique label is analyzed
    once per mode (re-compiles of the same bucket only bump ``count``), so
    the bounded analysis bill is one extra trace per executable family."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[str, Dict[str, Any]] = {}
        self._failures = _metric_registry().counter(ANALYSIS_FAILURES_SENSOR)
        _metric_registry().settable_gauge(COST_ROWS_SENSOR).set(0)

    # -- write side --------------------------------------------------------

    def observe_compile(self, label: str, fn, args: tuple, kwargs: dict,
                        mode: str) -> None:
        if mode == "off":
            return
        with self._lock:
            row = self._rows.get(label)
            if row is not None and row.get("mode") == mode:
                row["count"] += 1
                return
        try:
            row = self._analyze(label, fn, args, kwargs, mode)
        except Exception:   # noqa: BLE001 — observability must never break a solve
            self._failures.inc()
            LOG.debug("cost analysis failed for %s", label, exc_info=True)
            return
        with self._lock:
            prev = self._rows.get(label)
            if prev is not None:
                row["count"] = prev["count"] + 1
            self._rows[label] = row
            _metric_registry().settable_gauge(COST_ROWS_SENSOR).set(
                len(self._rows))

    def _analyze(self, label: str, fn, args: tuple, kwargs: dict,
                 mode: str) -> Dict[str, Any]:
        lowered = fn.lower(*_abstractify(args), **_abstractify(kwargs))
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        cost = cost or {}
        arg_bytes = measure_bytes(args) + measure_bytes(kwargs)
        out_bytes = measure_bytes(getattr(lowered, "out_info", None))
        row: Dict[str, Any] = {
            "label": label,
            "mode": mode,
            "count": 1,
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            "arg_bytes": int(arg_bytes),
            "out_bytes": int(out_bytes),
            "temp_bytes": None,
            "generated_code_bytes": None,
            # Derived peak (CompiledMemoryStats has no peak field): the
            # arg+out+temp+generated sum.  In ``lowered`` mode temp/code
            # sizes are unknown, so the peak is the arg+out floor.
            "peak_bytes": int(arg_bytes + out_bytes),
        }
        if mode == "full":
            # Full mode needs an AOT compile (a second XLA compile of the
            # family — jit's dispatch cache does not dedupe it).  Deferred:
            # the Lowered is stashed and ``finalize_full`` pays the compile
            # outside whatever timed region triggered this observation, so
            # bench/profile cold-compile measurements stay honest.
            row["pending"] = True
            row["_lowered"] = lowered
        return row

    def finalize_full(self) -> int:
        """AOT-compile every pending full-mode row, filling temp/generated
        bytes and the true derived peak.  Returns rows finalized.  Callers
        (bench/profile emit paths) invoke this OUTSIDE timed regions; a
        compile failure marks the row non-pending and bumps
        ``Memory.analysis-failures`` rather than raising."""
        with self._lock:
            pending = [(label, row["_lowered"])
                       for label, row in self._rows.items()
                       if row.get("pending") and "_lowered" in row]
        done = 0
        for label, lowered in pending:
            update: Dict[str, Any] = {"pending": False}
            try:
                mem = lowered.compile().memory_analysis()
                if mem is not None:
                    arg = int(getattr(mem, "argument_size_in_bytes", 0))
                    out = int(getattr(mem, "output_size_in_bytes", 0))
                    temp = int(getattr(mem, "temp_size_in_bytes", 0))
                    code = int(getattr(mem, "generated_code_size_in_bytes", 0))
                    update.update(arg_bytes=arg, out_bytes=out,
                                  temp_bytes=temp,
                                  generated_code_bytes=code,
                                  peak_bytes=arg + out + temp + code)
            except Exception:   # noqa: BLE001 — accounting never raises
                self._failures.inc()
                LOG.debug("full cost analysis failed for %s", label,
                          exc_info=True)
            with self._lock:
                row = self._rows.get(label)
                if row is not None:
                    row.update(update)
                    row.pop("_lowered", None)
            done += 1
        return done

    def ingest(self, label: str, row: Dict[str, Any]) -> None:
        """Direct row insert (tests / replay of captured artifacts)."""
        with self._lock:
            self._rows[label] = dict(row, label=label)
            _metric_registry().settable_gauge(COST_ROWS_SENSOR).set(
                len(self._rows))

    # -- read side ---------------------------------------------------------

    @staticmethod
    def _public(row: Dict[str, Any]) -> Dict[str, Any]:
        # Underscore keys hold non-serializable internals (the stashed
        # Lowered awaiting finalize_full) — never exposed.
        return {k: v for k, v in row.items() if not k.startswith("_")}

    def rows(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: self._public(v) for k, v in sorted(self._rows.items())}

    def row(self, label: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            r = self._rows.get(label)
            return self._public(r) if r is not None else None

    def peak_for_lanes(self, base_label: str, lanes: int) -> Optional[int]:
        """Projected peak bytes of ``<base_label>-L<lanes>``: the recorded
        row when one exists, otherwise a linear rescale from the nearest
        recorded width of the same family (lane peak is dominated by the
        per-lane masks/placements/temps, all ∝ lanes).  None with no data —
        the guard then has no basis to refuse."""
        exact = self.row(f"{base_label}-L{int(lanes)}")
        if exact is not None and exact.get("peak_bytes"):
            return int(exact["peak_bytes"])
        best: Optional[Tuple[int, int]] = None
        prefix = f"{base_label}-L"
        with self._lock:
            for label, r in self._rows.items():
                if not label.startswith(prefix) or not r.get("peak_bytes"):
                    continue
                tail = label[len(prefix):]
                if not tail.isdigit():
                    continue
                w = int(tail)
                if best is None or abs(w - lanes) < abs(best[0] - lanes):
                    best = (w, int(r["peak_bytes"]))
        if best is None:
            return None
        w, peak = best
        return int(peak * (int(lanes) / max(w, 1)))

    def maxima(self) -> Dict[str, int]:
        """Worst-case columns across all rows — what a bench row reports
        (``peak_bytes``/``temp_bytes``) for the executables it exercised."""
        with self._lock:
            peaks = [r.get("peak_bytes") or 0 for r in self._rows.values()]
            temps = [r.get("temp_bytes") or 0 for r in self._rows.values()]
        return {"peak_bytes": max(peaks, default=0),
                "temp_bytes": max(temps, default=0)}

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()
            _metric_registry().settable_gauge(COST_ROWS_SENSOR).set(0)


class DeviceMemoryLedger:
    """Process-wide device-buffer ledger + dispatch headroom guard.

    Host-side bookkeeping only: subsystems post signed byte events and the
    ledger maintains per-subsystem live totals (clamped at zero — a free
    exceeding the tracked allocation bumps ``Memory.post-imbalances``
    instead of going negative), pin/release balance, and gauges for the
    history rings.  Disabled (the module default until ``configure`` runs)
    every entry point is a cheap no-op."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self.analysis_mode = "lowered"
        self.headroom_fraction = 0.9
        self.budget_override_bytes = 0
        self.costs = ExecutableCostLedger()
        self._live: Dict[str, int] = {}
        self._peak: Dict[str, int] = {}
        self._pins: Dict[str, int] = {}
        self._events: Dict[str, int] = {}
        reg = _metric_registry()
        self._posts = reg.counter(POSTS_SENSOR)
        self._imbalances = reg.counter(IMBALANCE_SENSOR)
        self._shrinks = reg.counter(SHRINKS_SENSOR)
        self._refusals = reg.counter(REFUSALS_SENSOR)
        self._live_gauge = reg.settable_gauge(LIVE_BYTES_SENSOR)
        self._util_gauge = reg.settable_gauge(UTILIZATION_SENSOR)
        self._drift_gauge = reg.settable_gauge(DRIFT_SENSOR)
        self._subsys_gauges: Dict[str, Any] = {}
        self._live_gauge.set(0)
        self._util_gauge.set(0.0)
        self._drift_gauge.set(0)

    # -- configuration -----------------------------------------------------

    def configure(self, enabled: bool, headroom_fraction: float = 0.9,
                  budget_bytes: int = 0,
                  analysis_mode: str = "lowered") -> None:
        if analysis_mode not in ANALYSIS_MODES:
            raise ValueError(f"memory.analysis.mode must be one of "
                             f"{ANALYSIS_MODES}, got {analysis_mode!r}")
        with self._lock:
            self.enabled = bool(enabled)
            self.headroom_fraction = float(headroom_fraction)
            self.budget_override_bytes = int(budget_bytes)
            self.analysis_mode = analysis_mode
        if self.enabled:
            # Materialize the canonical subsystem gauges so the sensor-drift
            # guard sees Memory.* on a fresh boot, before the first post.
            for subsys in (SUBSYS_RESIDENT, SUBSYS_LANES, SUBSYS_WARMUP):
                self._gauge(subsys)

    def _gauge(self, subsystem: str):
        g = self._subsys_gauges.get(subsystem)
        if g is None:
            g = _metric_registry().settable_gauge(
                f"Memory.{subsystem}.live-bytes")
            g.set(self._live.get(subsystem, 0))
            self._subsys_gauges[subsystem] = g
        return g

    # -- write side --------------------------------------------------------

    def post(self, subsystem: str, nbytes: int, kind: str = "alloc",
             note: str = "") -> None:
        """One ledger event.  ``alloc`` adds ``nbytes`` to the subsystem's
        live total, ``free`` subtracts, ``donate`` records an in-place
        buffer swap (old freed, equal-size new allocated: net zero by
        construction), ``pin``/``release`` track refcounts only."""
        del note
        if not self.enabled:
            return
        nbytes = int(nbytes)
        with self._lock:
            self._posts.inc()
            self._events[kind] = self._events.get(kind, 0) + 1
            if kind == "pin":
                self._pins[subsystem] = self._pins.get(subsystem, 0) + 1
                return
            if kind == "release":
                pins = self._pins.get(subsystem, 0) - 1
                if pins < 0:
                    pins = 0
                    self._imbalances.inc()
                self._pins[subsystem] = pins
                return
            if kind == "donate":
                return      # net-zero by contract; counted, not summed
            live = self._live.get(subsystem, 0)
            if kind == "free":
                nbytes = -nbytes
            live += nbytes
            if live < 0:
                live = 0
                self._imbalances.inc()
            self._live[subsystem] = live
            self._peak[subsystem] = max(self._peak.get(subsystem, 0), live)
            total = sum(self._live.values())
        self._gauge(subsystem).set(live)
        self._live_gauge.set(total)
        budget = self.device_budget_bytes()
        if budget:
            self._util_gauge.set(round(total / budget, 6))

    def observe_compile(self, label: str, fn, args: tuple,
                        kwargs: dict) -> None:
        """Compile-time cost hook (called by the solver's compile-detection
        proxy on each fresh XLA compile).  No-op while disabled."""
        if not self.enabled:
            return
        self.costs.observe_compile(label, fn, args, kwargs,
                                   self.analysis_mode)

    # -- read side ---------------------------------------------------------

    def live_bytes(self, subsystem: Optional[str] = None) -> int:
        with self._lock:
            if subsystem is not None:
                return self._live.get(subsystem, 0)
            return sum(self._live.values())

    def pins(self, subsystem: Optional[str] = None) -> int:
        with self._lock:
            if subsystem is not None:
                return self._pins.get(subsystem, 0)
            return sum(self._pins.values())

    @property
    def imbalance_count(self) -> int:
        """Process-lifetime imbalance events (the counter is a registry
        sensor shared across ledger instances — diff around a scenario)."""
        return int(self._imbalances.count)

    def events(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._events)

    @staticmethod
    def backend_memory_stats() -> Optional[Dict[str, int]]:
        """``device.memory_stats()`` of the first device, or None where the
        backend does not expose it (XLA:CPU)."""
        try:
            import jax
            stats = jax.devices()[0].memory_stats()
        except Exception:   # noqa: BLE001 — probing must never raise
            return None
        if not stats:
            return None
        return {k: int(v) for k, v in stats.items()
                if isinstance(v, (int, float))}

    def device_budget_bytes(self) -> Optional[int]:
        """The guard's denominator: the configured override when set,
        otherwise the backend-reported limit, otherwise None (no basis to
        guard — every dispatch admits)."""
        if self.budget_override_bytes > 0:
            return self.budget_override_bytes
        stats = self.backend_memory_stats()
        if stats:
            for key in ("bytes_limit", "bytes_reservable_limit"):
                if stats.get(key):
                    return int(stats[key])
        return None

    def reconcile(self) -> Dict[str, Any]:
        """Tracked totals vs backend-reported stats.  ``driftBytes`` is
        backend in-use minus tracked (None without backend stats): the
        untracked remainder — executables, constants, anything a subsystem
        does not post — not an error unless it trends."""
        tracked = self.live_bytes()
        stats = self.backend_memory_stats()
        drift = None
        if stats and "bytes_in_use" in stats:
            drift = int(stats["bytes_in_use"]) - tracked
        self._drift_gauge.set(drift if drift is not None else 0)
        return {"trackedBytes": tracked, "backend": stats,
                "driftBytes": drift}

    # -- dispatch headroom guard -------------------------------------------

    def guard_lane_plan(self, plan: List, s_n: int, base_label: str,
                        ladder, compiled_widths=()) -> Tuple[List, bool]:
        """Shrink-or-refuse a lane-chunk plan against projected peak bytes.

        Returns ``(plan, refused)``.  For the widest chunk in ``plan``, the
        cost ledger projects peak bytes (recorded row, or a rescale from
        the nearest recorded width); when the projection exceeds
        ``headroom_fraction × device budget`` the plan is re-chunked at the
        widest ladder width that fits (``Memory.headroom-shrinks``).  When
        even the narrowest width does not fit the dispatch is refused
        (``Memory.headroom-refusals``) — the caller degrades, never
        crashes.  With no budget, no projection, or the ledger disabled the
        plan passes through untouched: no evidence, no refusal."""
        if not self.enabled or not plan:
            return plan, False
        budget = self.device_budget_bytes()
        if not budget:
            return plan, False
        limit = self.headroom_fraction * budget
        width = max(c.size for c in plan)
        projected = self.costs.peak_for_lanes(base_label, width)
        if projected is None or projected <= limit:
            return plan, False
        widths = sorted({int(w) for w in ladder if int(w) >= 1})
        fit = None
        for w in reversed([w for w in widths if w < width]):
            p = self.costs.peak_for_lanes(base_label, w)
            if p is not None and p <= limit:
                fit = w
                break
        if fit is None:
            self._refusals.inc()
            LOG.warning(
                "memory headroom guard REFUSED a %d-lane dispatch: projected "
                "peak %d B > %.0f%% of %d B at every ladder width",
                s_n, projected, self.headroom_fraction * 100.0, budget)
            return plan, True
        from cruise_control_tpu.compilesvc.chunking import plan_lane_chunks
        self._shrinks.inc()
        LOG.info(
            "memory headroom guard shrank a %d-lane dispatch to %d-wide "
            "chunks (projected peak %d B > %.0f%% of %d B)",
            s_n, fit, projected, self.headroom_fraction * 100.0, budget)
        return plan_lane_chunks(s_n, widths, compiled=compiled_widths,
                                max_chunk=fit), False

    # -- surfaces ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /memory`` body (also ``memoryState`` minus the cost
        table in ``/state``)."""
        with self._lock:
            subsystems = {
                name: {"liveBytes": self._live.get(name, 0),
                       "peakBytes": self._peak.get(name, 0),
                       "pins": self._pins.get(name, 0)}
                for name in sorted(set(self._live) | set(self._pins)
                                   | set(self._peak)
                                   | set(self._subsys_gauges))}
            events = dict(sorted(self._events.items()))
        return {
            "enabled": self.enabled,
            "analysisMode": self.analysis_mode,
            "headroomFraction": self.headroom_fraction,
            "deviceBudgetBytes": self.device_budget_bytes(),
            "liveBytes": self.live_bytes(),
            "subsystems": subsystems,
            "events": events,
            "guard": {"shrinks": int(self._shrinks.count),
                      "refusals": int(self._refusals.count)},
            "reconcile": self.reconcile(),
            "costs": self.costs.rows(),
        }

    def state_summary(self) -> Dict[str, Any]:
        """The compact ``memoryState`` block for ``GET /state``."""
        snap = self.snapshot()
        snap.pop("costs", None)
        snap["costRows"] = len(self.costs.rows())
        return snap

    def verify_balanced(self, drift_tolerance_fraction: float = 0.5,
                        ) -> List[str]:
        """Invariant checks for fuzzsvc ``memory_ledger_balanced``: no
        negative live totals (structurally impossible — an imbalance
        counter bump is the violation signal), pins drained, and tracked
        total within tolerance of the backend's in-use bytes when the
        backend reports them."""
        problems: List[str] = []
        with self._lock:
            for name, live in self._live.items():
                if live < 0:
                    problems.append(f"negative live bytes for {name}: {live}")
            for name, pins in self._pins.items():
                if pins != 0:
                    problems.append(f"undrained pins for {name}: {pins}")
        rec = self.reconcile()
        stats = rec["backend"]
        if stats and stats.get("bytes_in_use") and rec["trackedBytes"]:
            in_use = int(stats["bytes_in_use"])
            if rec["trackedBytes"] > in_use * (1.0 + drift_tolerance_fraction):
                problems.append(
                    f"tracked {rec['trackedBytes']} B exceeds backend "
                    f"in-use {in_use} B beyond tolerance")
        return problems

    def reset(self) -> None:
        """Drop all accounting (tests / hermeticity).  Keeps configuration."""
        with self._lock:
            self._live.clear()
            self._peak.clear()
            self._pins.clear()
            self._events.clear()
        for g in self._subsys_gauges.values():
            g.set(0)
        self._live_gauge.set(0)
        self._util_gauge.set(0.0)
        self._drift_gauge.set(0)
        self.costs.reset()


_LEDGER: Optional[DeviceMemoryLedger] = None
_LEDGER_LOCK = threading.Lock()


def memory_ledger() -> DeviceMemoryLedger:
    """Process-wide ledger singleton (disabled until ``configure``)."""
    global _LEDGER
    if _LEDGER is None:
        with _LEDGER_LOCK:
            if _LEDGER is None:
                _LEDGER = DeviceMemoryLedger()
    return _LEDGER


def set_memory_ledger(ledger: Optional[DeviceMemoryLedger]) -> None:
    """Test seam: swap (or with None, lazily rebuild) the singleton."""
    global _LEDGER
    _LEDGER = ledger


def cost_ledger() -> ExecutableCostLedger:
    return memory_ledger().costs


def configure(config) -> DeviceMemoryLedger:
    """Wire ``memory.*`` config keys into the ledger singleton."""
    ledger = memory_ledger()
    ledger.configure(
        enabled=bool(config.get("memory.enabled")),
        headroom_fraction=float(config.get("memory.headroom.fraction")),
        budget_bytes=int(config.get("memory.device.budget.bytes")),
        analysis_mode=str(config.get("memory.analysis.mode")))
    return ledger
