"""Solver convergence flight recorder (trace.solver.rounds).

The solve itself used to be a black box: each goal's ``lax.while_loop`` runs
up to 96 rounds on device and reported only the final rounds/moves/violated
numbers.  With ``trace.solver.rounds`` on, the solver threads a per-round
stats buffer through the loop carry (analyzer/solver.py) and this module
keeps a bounded ring of the resulting per-solve, per-goal curves plus the
derived statistics the ROADMAP's convex-fast-path and learned-move-priority
items need:

- ``rounds_to_90pct`` — first round reaching 90% of the solve's total
  metric improvement (where greedy convergence flattens);
- ``acceptance_rate`` — mean per-round accepted moves over the peak round
  (how quickly the batch acceptance decays);
- ``stall_rounds`` — rounds that improved neither the violation count nor
  the stats metric;
- per-lane early-exit rounds for warm/cold what-if batches.

Read via ``GET /solver_stats``; a summary rides the ``convergence`` section
of ``GET /state``.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

# Canonical round-stats buffer layout.  analyzer/solver.py imports these and
# stacks its per-round row in exactly this column order; this module stays
# dependency-free so the solver (which imports obsvc.tracer mid-module) can
# import it without a cycle.
ROUND_COL_APPLIED = 0     # replica+leadership moves accepted this round
ROUND_COL_VIOLATED = 1    # violated-broker count after the round
ROUND_COL_STRANDED = 2    # offline replicas still stranded
ROUND_COL_METRIC = 3      # goal stats metric after the round
ROUND_COL_RESYNC = 4      # 1.0 when this round re-synced carried aggregates
ROUND_COL_STALL = 5       # consecutive non-improving rounds, post-update
ROUND_STATS_COLS = 6

_IDS = itertools.count(1)


def curve_stats(curve, metric_before: float) -> Dict[str, Any]:
    """Derived statistics for one goal's (rounds, cols) round-stats array."""
    rounds_total = len(curve)
    if rounds_total == 0:
        return {"rounds_total": 0, "stall_rounds": 0, "rounds_to_90pct": 0,
                "acceptance_rate": 0.0, "moves_total": 0}
    applied = [float(r[ROUND_COL_APPLIED]) for r in curve]
    metric = [float(r[ROUND_COL_METRIC]) for r in curve]
    stall_rounds = sum(1 for r in curve if float(r[ROUND_COL_STALL]) > 0)
    peak = max(applied)
    acceptance = (sum(applied) / (rounds_total * peak)) if peak > 0 else 0.0
    # First round reaching 90% of the total metric improvement; a solve with
    # no metric improvement (pure violation repair) converges "at the end".
    total_gain = metric_before - metric[-1]
    rounds_to_90 = rounds_total
    if total_gain > 0:
        for i, m in enumerate(metric):
            if metric_before - m >= 0.9 * total_gain:
                rounds_to_90 = i + 1
                break
    return {
        "rounds_total": rounds_total,
        "stall_rounds": stall_rounds,
        "rounds_to_90pct": rounds_to_90,
        "acceptance_rate": round(acceptance, 4),
        "moves_total": int(sum(applied)),
    }


def _curve_rows(curve) -> List[Dict[str, float]]:
    return [{
        "applied": int(r[ROUND_COL_APPLIED]),
        "violated": int(r[ROUND_COL_VIOLATED]),
        "stranded": int(r[ROUND_COL_STRANDED]),
        "metric": round(float(r[ROUND_COL_METRIC]), 6),
        "resync": bool(r[ROUND_COL_RESYNC]),
        "stall": int(r[ROUND_COL_STALL]),
    } for r in curve]


class ConvergenceRecorder:
    """Bounded flight-recorder ring of per-solve convergence records."""

    def __init__(self, enabled: bool = False, ring_size: int = 64):
        self.enabled = enabled
        self._ring: deque = deque(maxlen=ring_size)
        self._pending: List[Dict[str, Any]] = []   # drained by bench.py rows
        self._lock = threading.Lock()
        self._recorded = 0

    def configure(self, enabled: bool, ring_size: int) -> None:
        """Reconfigure in place (the singleton is referenced widely)."""
        with self._lock:
            self.enabled = enabled
            if ring_size != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=ring_size)

    # -- write side --------------------------------------------------------

    def record_solve(self, goal_curves: Sequence[Dict[str, Any]],
                     kind: str = "propose",
                     attrs: Optional[Dict[str, Any]] = None) -> Optional[int]:
        """One sequential optimization run.  ``goal_curves`` entries carry
        {goal, curve (np array), metric_before, rounds, moves} — curves come
        from ``GoalOptimizationInfo.round_curve``."""
        if not self.enabled:
            return None
        goals = []
        for gc in goal_curves:
            curve = gc.get("curve")
            entry = {
                "goal": gc["goal"],
                "rounds": int(gc.get("rounds", 0)),
                "moves": int(gc.get("moves", 0)),
            }
            # Relax-vs-greedy telemetry: solves that took the convex-
            # relaxation fast path report its wall time and how many greedy
            # repair rounds the rounded warm start still needed.
            if "relax_ms" in gc:
                entry["relax_ms"] = float(gc["relax_ms"])
                entry["repair_rounds"] = int(gc.get("repair_rounds", 0))
                if gc.get("relax_fallback"):
                    entry["relax_fallback"] = True
            if curve is not None:
                entry["stats"] = curve_stats(curve,
                                             float(gc.get("metric_before", 0.0)))
                entry["curve"] = _curve_rows(curve)
            goals.append(entry)
        rec = {
            "id": next(_IDS),
            "timestampMs": round(time.time() * 1000.0, 1),
            "kind": kind,
            "goals": goals,
        }
        if attrs:
            rec.update(attrs)
        with self._lock:
            self._ring.append(rec)
            self._pending.append(rec)
            self._recorded += 1
        return rec["id"]

    def record_batch(self, goal_names: Sequence[str], rounds_matrix,
                     warm_start: bool = False,
                     attrs: Optional[Dict[str, Any]] = None) -> Optional[int]:
        """One vmapped what-if batch: per-lane early-exit rounds per goal.
        ``rounds_matrix`` is the i32[S, G] per-lane/per-goal round counts the
        batch solve already returns."""
        if not self.enabled:
            return None
        lane_rounds = {
            name: [int(rounds_matrix[s][g])
                   for s in range(len(rounds_matrix))]
            for g, name in enumerate(goal_names)
        }
        rec = {
            "id": next(_IDS),
            "timestampMs": round(time.time() * 1000.0, 1),
            "kind": "what_if",
            "lanes": len(rounds_matrix),
            "warmStart": bool(warm_start),
            "laneRounds": lane_rounds,
        }
        if attrs:
            rec.update(attrs)
        with self._lock:
            self._ring.append(rec)
            self._pending.append(rec)
            self._recorded += 1
        return rec["id"]

    # -- read side ---------------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first."""
        with self._lock:
            return list(self._ring)

    def drain(self) -> List[Dict[str, Any]]:
        """Records added since the last drain (bench.py per-row attribution);
        the ring itself is untouched."""
        with self._lock:
            out, self._pending = self._pending, []
        return out

    def state_summary(self) -> Dict[str, Any]:
        """The ``convergence`` section of GET /state."""
        with self._lock:
            ring = list(self._ring)
            recorded = self._recorded
            maxlen = self._ring.maxlen
        last = None
        for rec in reversed(ring):
            if rec.get("goals"):
                last = {
                    "id": rec["id"],
                    "kind": rec["kind"],
                    "goals": {g["goal"]: g.get("stats", {"rounds_total":
                                                         g["rounds"]})
                              for g in rec["goals"]},
                }
                break
        return {"enabled": self.enabled, "recorded": recorded,
                "retained": len(ring), "ringSize": maxlen,
                "lastSolve": last}

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pending.clear()
            self._recorded = 0


_RECORDER = ConvergenceRecorder()


def convergence() -> ConvergenceRecorder:
    return _RECORDER
