"""Contextvar-propagated span tracer.

The reference answers "where did the time go" with ~40 flat Dropwizard
sensors; a single ``proposal-computation-timer`` number cannot split a
15-goal optimization round into per-goal compile vs execute time.  This
module adds the missing dimension: a tree of spans per logical operation
(HTTP request, precompute tick, executor batch), propagated across the
servlet's worker threads with :mod:`contextvars` so async user tasks
inherit the request's root span.

Design constraints:

* **Near-zero overhead when off.**  ``Tracer.span()`` returns a shared
  no-op context manager when disabled — no allocation beyond the call's
  own f-string/kwargs, no contextvar traffic, no locking.
* **Late children render.**  A ``/rebalance`` request returns 202 while
  the optimization keeps running in a user-task thread.  Root spans are
  appended to the ring when *they* close; children mutate the tree in
  place afterwards, so ``/trace`` read time always sees the latest
  picture (in-progress spans render with ``wall_ms: null``).
* **Rollups ride the flat registry.**  Every completed span also updates
  a ``Trace.<name>`` timer in the global :func:`~cruise_control_tpu.common.metrics.registry`,
  so Prometheus scrapes see phase attribution without a new pipeline,
  and keeps a phase accumulator that ``bench.py --trace`` drains per row.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextvars import ContextVar
from typing import Any, Dict, List, Optional

from cruise_control_tpu.common.metrics import registry

_CURRENT: ContextVar[Optional["Span"]] = ContextVar("cc_trace_span",
                                                    default=None)
_IDS = itertools.count(1)


class Span:
    """One timed phase.  Mutable in place until ``wall_ms`` is set."""

    __slots__ = ("span_id", "parent_id", "name", "attrs", "children",
                 "start_ms", "wall_ms", "_t0")

    def __init__(self, name: str, parent: Optional["Span"],
                 attrs: Dict[str, Any]):
        self.span_id = next(_IDS)
        self.parent_id = parent.span_id if parent is not None else None
        self.name = name
        self.attrs = attrs
        self.children: List["Span"] = []
        self.start_ms = time.time() * 1000.0
        self.wall_ms: Optional[float] = None
        self._t0 = time.monotonic()

    def set(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def add_ms(self, key: str, ms: float) -> None:
        self.attrs[key] = self.attrs.get(key, 0.0) + ms

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ms": round(self.start_ms, 3),
            "wall_ms": None if self.wall_ms is None else round(
                self.wall_ms, 3),
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        # Compile/execute split: a span annotated with compile_ms (from
        # compilesvc telemetry deltas) splits its own wall time.
        cm = self.attrs.get("compile_ms")
        if cm is not None and self.wall_ms is not None:
            d["attrs"]["execute_ms"] = round(max(self.wall_ms - cm, 0.0), 3)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class _NoopSpan:
    __slots__ = ()

    def set(self, key: str, value: Any) -> None:
        pass

    def add_ms(self, key: str, ms: float) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class _NoopCtx:
    __slots__ = ()

    def __enter__(self) -> _NoopSpan:
        return _NOOP_SPAN

    def __exit__(self, *exc: Any) -> bool:
        return False


_NOOP_CTX = _NoopCtx()


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_attrs", "_span", "_token")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> Span:
        parent = _CURRENT.get()
        span = Span(self._name, parent, self._attrs)
        if parent is not None:
            parent.children.append(span)
        self._span = span
        self._token = _CURRENT.set(span)
        return span

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        _CURRENT.reset(self._token)
        span = self._span
        span.wall_ms = (time.monotonic() - span._t0) * 1000.0
        if exc_type is not None:
            span.attrs["error"] = exc_type.__name__
        self._tracer._on_end(span)
        return False


class Tracer:
    """Process tracer: span factory + bounded ring of root traces."""

    def __init__(self, enabled: bool = False, ring_size: int = 32):
        self.enabled = enabled
        self._ring: deque = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._rollup: Dict[str, Dict[str, float]] = {}

    def configure(self, enabled: bool, ring_size: int) -> None:
        """Reconfigure in place (the singleton is referenced widely)."""
        with self._lock:
            self.enabled = enabled
            if ring_size != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=ring_size)

    # -- span creation -----------------------------------------------------
    def span(self, name: str, **attrs: Any):
        """Context manager for a timed phase; no-op when tracing is off."""
        if not self.enabled:
            return _NOOP_CTX
        return _SpanCtx(self, name, attrs)

    def current(self) -> Optional[Span]:
        if not self.enabled:
            return None
        return _CURRENT.get()

    # -- completion / read side -------------------------------------------
    def _on_end(self, span: Span) -> None:
        # A span that was opened while tracing was on but closes after it
        # was switched off (a straggling background thread) records
        # nothing — disable means stop collecting, immediately.
        if not self.enabled:
            return
        wall = span.wall_ms or 0.0
        with self._lock:
            row = self._rollup.setdefault(
                span.name, {"count": 0, "total_ms": 0.0})
            row["count"] += 1
            row["total_ms"] += wall
            if span.parent_id is None:
                self._ring.append(span)
        registry().timer(f"Trace.{span.name}").update_ms(wall)

    def traces(self) -> List[Dict[str, Any]]:
        """Recent root span trees, oldest first (children may still run)."""
        with self._lock:
            roots = list(self._ring)
        return [r.to_dict() for r in roots]

    def rollup(self, reset: bool = False) -> Dict[str, Dict[str, float]]:
        """Per-phase {count, total_ms, mean_ms} since start (or last reset)."""
        with self._lock:
            rows = {k: dict(v) for k, v in self._rollup.items()}
            if reset:
                self._rollup.clear()
        for v in rows.values():
            v["total_ms"] = round(v["total_ms"], 3)
            v["mean_ms"] = round(v["total_ms"] / max(v["count"], 1), 3)
        return rows

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._rollup.clear()


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER
