"""Per-optimization options.

Reference: ``analyzer/OptimizationOptions.java:16-129`` — excluded topics,
brokers excluded from receiving leadership / replicas, goal-violation trigger
flag, requested destination brokers, and the only-move-immigrant-replicas
restriction used by the goal-violation detector.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import FrozenSet, Optional, Set

import numpy as np

from cruise_control_tpu.model.state import ClusterMeta


@dataclass(frozen=True)
class OptimizationOptions:
    excluded_topics: FrozenSet[str] = frozenset()
    excluded_topics_pattern: Optional[str] = None
    excluded_brokers_for_leadership: FrozenSet[int] = frozenset()
    excluded_brokers_for_replica_move: FrozenSet[int] = frozenset()
    # Empty = any alive broker may receive replicas.
    requested_destination_broker_ids: FrozenSet[int] = frozenset()
    is_triggered_by_goal_violation: bool = False
    only_move_immigrant_replicas: bool = False
    fast_mode: bool = False

    def excluded_topic_mask(self, meta: ClusterMeta) -> np.ndarray:
        """bool[T] (true = excluded) from the explicit set + regex pattern."""
        mask = np.zeros(meta.num_topics, dtype=bool)
        pat = re.compile(self.excluded_topics_pattern) if self.excluded_topics_pattern else None
        for i, t in enumerate(meta.topics):
            if t in self.excluded_topics or (pat is not None and pat.fullmatch(t)):
                mask[i] = True
        return mask

    def _broker_mask(self, meta: ClusterMeta, ids: FrozenSet[int], padded: int) -> np.ndarray:
        mask = np.zeros(padded, dtype=bool)
        for b in ids:
            if b in meta.broker_index:
                mask[meta.broker_index[b]] = True
        return mask

    def leadership_exclusion_mask(self, meta: ClusterMeta, padded: int) -> np.ndarray:
        return self._broker_mask(meta, self.excluded_brokers_for_leadership, padded)

    def replica_move_exclusion_mask(self, meta: ClusterMeta, padded: int) -> np.ndarray:
        return self._broker_mask(meta, self.excluded_brokers_for_replica_move, padded)

    def destination_mask(self, meta: ClusterMeta, padded: int) -> np.ndarray:
        """bool[B] of allowed destinations; all-true when no explicit request."""
        if not self.requested_destination_broker_ids:
            return np.ones(padded, dtype=bool)
        return self._broker_mask(meta, self.requested_destination_broker_ids, padded)


def merge_excluded_topics(options: OptimizationOptions, extra: Set[str]) -> OptimizationOptions:
    return OptimizationOptions(
        excluded_topics=frozenset(options.excluded_topics | extra),
        excluded_topics_pattern=options.excluded_topics_pattern,
        excluded_brokers_for_leadership=options.excluded_brokers_for_leadership,
        excluded_brokers_for_replica_move=options.excluded_brokers_for_replica_move,
        requested_destination_broker_ids=options.requested_destination_broker_ids,
        is_triggered_by_goal_violation=options.is_triggered_by_goal_violation,
        only_move_immigrant_replicas=options.only_move_immigrant_replicas,
        fast_mode=options.fast_mode,
    )
