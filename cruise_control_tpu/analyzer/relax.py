"""Convex-relaxation fast path: fractional solve + wave rounding, greedy
demoted to integer repair.

The greedy kernel converges a distribution goal by iterated batched rounds —
tens of dispatches of a C×B feasibility tile at north-star scale.  For the
resource- and count-distribution families the objective is analytically
simple: each broker carries one scalar channel (a resource's load, a replica
count) and the goal wants every alive broker's channel near the cluster
average.  That lowers to a CONTINUOUS assignment problem (the CvxCluster /
GOMA observation in PAPERS.md — granular allocation relaxed to a convex
program is orders of magnitude cheaper than discrete search):

1. **Fractional solve** — pick the K highest-priority movable replicas (the
   same candidate score the greedy phase uses, so over-band brokers shed
   first), give each a row of fractional mass ``X[k, b] ≥ 0, Σ_b X[k,b] = 1``
   over its structurally-feasible destinations (``base_replica_move_ok``
   plus its own broker), and minimize the capacity-normalized squared
   residual ``Σ_b ((fixed_b + Σ_k w_k X[k,b] − target_b) / scale_b)²`` by
   entropic mirror descent (exponentiated gradient: logits accumulate the
   normalized rank-1 gradient, softmax projects back onto the simplex — no
   per-iteration sort).  One fixed-iteration ``lax.while_loop`` with the
   iteration bound a traced scalar, so one executable serves every
   configured depth.

2. **Wave rounding** — transport-style conservative rounding: each wave
   sends every unsettled candidate to its argmax-mass destination, but only
   where the move passes the SAME acceptance stack the greedy kernel
   enforces (structural + every prior goal's acceptance + this goal's
   self-check, against current aggregates) and wins its partition /
   destination / source / host group (one move per group per wave, so no
   cumulative-headroom bookkeeping is needed for priors that don't compose).
   Vetoed destinations are masked and the next wave tries the runner-up.
   Rounding therefore can never worsen a previously-optimized goal.

3. **Greedy repair** — the rounded placement goes to the EXISTING fused
   greedy solve as a warm start.  The placement is a traced input, so repair
   reuses the normal per-goal executable with zero new compiles; the loop's
   own convergence/stall cutoffs bound the pass.

Wired behind ``solver.relaxation.enabled`` + per-goal ``relax_eligible``
(goals/registry.py): ineligible goals — and every goal when the flag is off —
take the current path bit-for-bit (no relax executables are ever built, no
cache keys change; the PR 9/10 parity discipline).  Compilesvc buckets for
the relax executables get an ``-X`` suffix via :meth:`GoalSolver.relax_cached`
so their cache keys stay disjoint from the greedy family's.

Sensors: ``Solver.relax.attempts`` / ``Solver.relax.fallbacks`` counters,
``Solver.relax.repair-rounds`` / ``Solver.relax.quality-delta`` /
``Solver.relax.fractional-moves`` gauges.  Spans: ``solve.relax`` around the
fractional+rounding dispatch (the repair keeps its normal ``goal.*`` span
accounting).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import (
    Aggregates,
    GoalContext,
    apply_replica_moves_batch,
    base_replica_move_ok,
    compute_aggregates,
)
from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.analyzer.solver import (
    _SCORE_FLOOR,
    _chain_accept_replica,
    _group_winners,
    _pick_dst_disk,
    GoalOptimizationInfo,
    GoalSolver,
)
from cruise_control_tpu.model.state import Placement
from cruise_control_tpu.obsvc.tracer import tracer as _obsvc_tracer

ATTEMPTS_SENSOR = "Solver.relax.attempts"
FALLBACKS_SENSOR = "Solver.relax.fallbacks"
REPAIR_ROUNDS_SENSOR = "Solver.relax.repair-rounds"
QUALITY_DELTA_SENSOR = "Solver.relax.quality-delta"
FRACTIONAL_MOVES_SENSOR = "Solver.relax.fractional-moves"

# Mirror-descent step in logit space per (normalized) iteration.  The
# gradient is normalized to unit max, so total logit travel is bounded by
# eta * iterations — enough to fully commit a row at the default depth while
# keeping early iterations exploratory.
_MD_STEP = 1.0
# Initial preference for staying home: softmax(±bias) keeps the start near
# the current placement instead of uniform, so barely-over brokers shed only
# what the objective actually asks for.
_HOME_BIAS = 1.0
_NEG_INF = -1e30

# ---------------------------------------------------------------------------
# Process-wide config (wired by main.build_app from solver.relaxation.*).
# Defaults match config/cruise_control_config.py; enabled stays False so a
# bare import is always byte-identical to the pre-relaxation solver.

_RELAXATION = {
    "enabled": False,
    "iterations": 48,
    "candidates": 4096,
    "waves": 4,
    "tolerance": 0.05,
}


def set_relaxation(enabled: bool, iterations: Optional[int] = None,
                   candidates: Optional[int] = None,
                   waves: Optional[int] = None,
                   tolerance: Optional[float] = None) -> None:
    """Process-wide relaxation switch + knobs (solver.relaxation.*)."""
    _RELAXATION["enabled"] = bool(enabled)
    if iterations is not None:
        _RELAXATION["iterations"] = max(1, int(iterations))
    if candidates is not None:
        _RELAXATION["candidates"] = max(1, int(candidates))
    if waves is not None:
        _RELAXATION["waves"] = max(1, int(waves))
    if tolerance is not None:
        _RELAXATION["tolerance"] = max(0.0, float(tolerance))


def relaxation_enabled() -> bool:
    return bool(_RELAXATION["enabled"])


def relaxation_params() -> Tuple[int, int, int, float]:
    """(iterations, candidates, waves, tolerance) — the proposal-cache key
    fragment when the fast path is on."""
    return (int(_RELAXATION["iterations"]), int(_RELAXATION["candidates"]),
            int(_RELAXATION["waves"]), float(_RELAXATION["tolerance"]))


def relaxation_tolerance() -> float:
    return float(_RELAXATION["tolerance"])


def relax_sensors() -> None:
    """Materialize the Solver.relax.* family at boot so /metrics and the
    docs/SENSORS.md drift guard see it before the first relaxed solve."""
    from cruise_control_tpu.common.metrics import registry
    reg = registry()
    reg.counter(ATTEMPTS_SENSOR)
    reg.counter(FALLBACKS_SENSOR)
    reg.settable_gauge(REPAIR_ROUNDS_SENSOR)
    reg.settable_gauge(QUALITY_DELTA_SENSOR)
    reg.settable_gauge(FRACTIONAL_MOVES_SENSOR)


# ---------------------------------------------------------------------------
# The jitted fractional solve + wave rounding.


def _relax_body(goal: Goal, priors: Tuple[Goal, ...], k: int, waves: int):
    """(gctx, placement, agg0, iters) ->
    (placement, agg, frac_moves, violated0, metric0).

    ``iters`` is a traced int32 so the mirror-descent depth is a config
    knob, not a compile trigger.  ``agg`` in the output is a FRESH full
    recompute — the repair pass starts from exact aggregates."""
    accept = _chain_accept_replica(priors)

    def relaxed(gctx: GoalContext, placement: Placement, agg0: Aggregates,
                iters):
        state = gctx.state
        b = state.num_brokers_padded
        # Pre-relax residuals: free here, and exactly what the repair's
        # GoalOptimizationInfo must report as its "before" numbers.
        violated0 = jnp.sum(goal.violated_brokers(gctx, placement, agg0)
                            .astype(jnp.int32))
        metric0 = goal.stats_metric(gctx, placement, agg0)

        # --- candidate tile (same priority order as the greedy move phase)
        score = goal.candidate_score(gctx, placement, agg0)
        top_score, cand = jax.lax.top_k(score, k)
        is_cand = top_score > _SCORE_FLOOR
        src0 = placement.broker[cand]
        w = jnp.where(is_cand, goal.relax_weights(gctx, placement)[cand], 0.0)

        # --- the channel: fixed load excludes the candidates' movable mass
        load, target, scale = goal.relax_channel(gctx, agg0)
        fixed = load - jax.ops.segment_sum(w, src0, num_segments=b)
        inv_s2 = 1.0 / jnp.maximum(scale, 1e-9) ** 2

        # --- feasible-destination mask: structural legitMove ∪ stay-home.
        b_ids = jnp.arange(b, dtype=jnp.int32)
        feas = base_replica_move_ok(gctx, placement, cand[:, None],
                                    b_ids[None, :]) & is_cand[:, None]
        home = b_ids[None, :] == src0[:, None]
        mask = feas | home                        # home row keeps softmax finite
        z0 = jnp.where(mask, jnp.where(home, _HOME_BIAS, 0.0), _NEG_INF)
        w_max = jnp.maximum(jnp.max(w), 1e-9)

        # --- entropic mirror descent on the row simplexes
        def md_cond(carry):
            return carry[0] < iters

        def md_body(carry):
            i, z = carry
            x = jax.nn.softmax(z, axis=-1)
            chan = fixed + jnp.matmul(w, x)                       # f32[B]
            g = 2.0 * (chan - target) * inv_s2
            g = g / jnp.maximum(jnp.max(jnp.abs(g)), 1e-12)
            z = z - _MD_STEP * (w[:, None] / w_max) * g[None, :]
            return i + 1, jnp.where(mask, z, _NEG_INF)

        _, z = jax.lax.while_loop(md_cond, md_body, (jnp.int32(0), z0))

        # --- wave rounding against the live acceptance stack
        agg = agg0
        settled = ~is_cand
        moves = jnp.int32(0)
        kidx = jnp.arange(k, dtype=jnp.int32)
        for _ in range(waves):
            dst = jnp.argmax(z, axis=-1).astype(jnp.int32)
            src = placement.broker[cand]
            want = ~settled & (dst != src)
            ok = (want
                  & accept(gctx, placement, agg, cand, dst)
                  & goal.self_ok(gctx, placement, agg, cand, dst))
            order = jnp.where(ok, kidx, k)
            keep = (ok
                    & _group_winners(order, state.partition[cand],
                                     gctx.num_partitions)
                    & _group_winners(order, dst, b)
                    & _group_winners(order, src, b)
                    & _group_winners(order, state.host[dst], gctx.num_hosts))
            dd = _pick_dst_disk(gctx, agg, dst)
            dst_eff = jnp.where(keep, dst, src)
            dd_eff = jnp.where(keep, dd, placement.disk[cand])
            placement, agg = apply_replica_moves_batch(
                gctx, placement, agg, cand, dst_eff, dd_eff)
            moves = moves + jnp.sum(keep.astype(jnp.int32))
            # Settled: moved, or the mass already prefers home.  Vetoed
            # destinations are masked so the next wave tries the runner-up
            # (home stays finite, so rows can always resolve to a no-op).
            settled = settled | keep | (dst == src)
            veto = want & ~keep
            z = jnp.where(veto[:, None] & (b_ids[None, :] == dst[:, None]),
                          _NEG_INF, z)

        # Fresh aggregates clear the waves' incremental scatter drift before
        # the repair pass reads its "before" residuals from them.
        return (placement, compute_aggregates(gctx, placement), moves,
                violated0, metric0)

    return relaxed


def _relax_fn(solver: GoalSolver, goal: Goal, priors: Tuple[Goal, ...],
              num_replicas_padded: int, k: int, waves: int):
    """The sequential-path relax executable, cached under the ``-X`` bucket
    family (disjoint from every greedy cache key by construction)."""
    key = ("frac", goal.key(), tuple(g.key() for g in priors), k, waves)
    return solver.relax_cached(
        key, f"R{num_replicas_padded}-C{k}",
        lambda: jax.jit(_relax_body(goal, priors, k, waves)))


def _relax_batch_fn(solver: GoalSolver, goal: Goal, priors: Tuple[Goal, ...],
                    num_replicas_padded: int, k: int, waves: int):
    """Vmapped relax over what-if lanes: every lane rebuilds its own
    liveness/exclusion context (mirroring ``_batch_solve_fn``) and returns
    only the rounded placement — the existing vmapped greedy solve then runs
    as the repair pass with no new executable."""
    key = ("frac-batch", goal.key(), tuple(g.key() for g in priors), k, waves)

    def build():
        body = _relax_body(goal, priors, k, waves)

        @jax.jit
        def batch(gctx: GoalContext, alive_s, excl_move_s, excl_lead_s,
                  placement_s, iters):
            def one(alive, excl_move, excl_lead, placement):
                state = gctx.state.replace(alive=alive)
                ok = alive & state.broker_valid
                host_cap = jax.ops.segment_sum(
                    jnp.where(ok[:, None], state.capacity, 0.0),
                    state.host, num_segments=gctx.num_hosts)
                g2 = gctx.replace(
                    state=state, host_capacity=host_cap,
                    excluded_for_replica_move=excl_move,
                    excluded_for_leadership=excl_lead)
                out = body(g2, placement,
                           compute_aggregates(g2, placement), iters)
                return out[0]
            return jax.vmap(one, in_axes=(0, 0, 0, 0))(
                alive_s, excl_move_s, excl_lead_s, placement_s)
        return batch

    return solver.relax_cached(
        key, f"R{num_replicas_padded}-C{k}", build,
        label_fn=lambda gctx, alive_s, *a, **kw:
            f"R{num_replicas_padded}-C{k}-X-L{alive_s.shape[0]}")


# ---------------------------------------------------------------------------
# Sequential-path entry point.


def optimize_goal_relaxed(solver: GoalSolver, goal: Goal,
                          priors: Sequence[Goal], gctx: GoalContext,
                          placement: Placement,
                          agg: Optional[Aggregates] = None,
                          ) -> Tuple[Placement, Aggregates,
                                     GoalOptimizationInfo]:
    """Relax → round → greedy repair for one eligible goal; drop-in for
    :meth:`GoalSolver.optimize_goal` on the unbudgeted sequential path.

    The returned info reports the WHOLE pass against the pre-relax placement
    (metric/violated "before" come from the original state, moves include the
    rounding waves' moves, ``rounds`` is the repair's round count) so the
    optimizer's hard-goal and no-worsen verdicts keep their meaning.  If the
    relaxed result regresses the goal vs the original placement, the pass
    falls back to pure greedy from the ORIGINAL placement
    (``Solver.relax.fallbacks``) — the fast path may only ever win.
    """
    from cruise_control_tpu.common.metrics import registry

    if agg is None:
        agg = solver.aggregates(gctx, placement)
    iters, k_cfg, waves, _tol = relaxation_params()
    r_pad = gctx.state.num_replicas_padded
    k = min(k_cfg, r_pad)
    fn = _relax_fn(solver, goal, tuple(priors), r_pad, k, waves)
    tr = _obsvc_tracer()
    t0 = time.monotonic()
    if tr.enabled:
        with tr.span("solve.relax", goal=goal.name, candidates=k,
                     waves=waves, iterations=iters) as sp:
            with jax.profiler.TraceAnnotation(f"cc.relax.{goal.name}"):
                out = jax.block_until_ready(
                    fn(gctx, placement, agg, jnp.int32(iters)))
            sp.set("frac_moves", int(out[2]))
            sp.add_ms("device_ms",
                      round((time.monotonic() - t0) * 1000.0, 3))
    else:
        out = fn(gctx, placement, agg, jnp.int32(iters))
    rounded_pl, rounded_agg, frac_moves, violated0, metric0 = out
    relax_ms = (time.monotonic() - t0) * 1000.0
    registry().counter(ATTEMPTS_SENSOR).inc()

    # Execution observatory: park the post-rounding placement so the
    # optimizer can split relax-stage moves from greedy-repair moves with a
    # three-way diff.  Host-side only (the optimizer syncs it lazily);
    # nothing here touches the solve executables or their cache keys.
    from cruise_control_tpu.obsvc.execution import execution as _execution
    if _execution().enabled:
        _execution().stash_rounded(goal.name, rounded_pl)

    # Greedy repair from the rounded placement: the placement is a traced
    # input of the normal solve executable, so this compiles nothing new.
    pl2, agg2, info = solver.optimize_goal(goal, priors, gctx, rounded_pl,
                                           rounded_agg)
    regressed = (
        info.violated_brokers_after > int(violated0)
        or info.metric_after > float(metric0) * (1 + 1e-5) + 1e-9)
    if regressed:
        # The relaxation hurt this goal (possible when rounding's per-wave
        # conservatism strands mass) — discard it entirely.  The stashed
        # rounding placement is void with it: the fallback pass is pure
        # greedy from the original placement.
        _execution().pop_rounded(goal.name)
        registry().counter(FALLBACKS_SENSOR).inc()
        pl2, agg2, info = solver.optimize_goal(goal, priors, gctx, placement,
                                               agg)
        info.relaxed = True
        info.relax_fallback = True
        info.relax_ms = relax_ms
        return pl2, agg2, info

    # Re-anchor the info at the pre-relax state so the optimizer's verdicts
    # (and the convergence recorder) judge the whole relax+repair pass.
    info.relaxed = True
    info.relax_ms = relax_ms
    info.repair_rounds = info.rounds
    info.moves_applied += int(frac_moves)
    info.violated_brokers_before = int(violated0)
    info.metric_before = float(metric0)
    registry().settable_gauge(REPAIR_ROUNDS_SENSOR).set(info.repair_rounds)
    registry().settable_gauge(FRACTIONAL_MOVES_SENSOR).set(int(frac_moves))
    denom = max(abs(float(metric0)), 1e-9)
    registry().settable_gauge(QUALITY_DELTA_SENSOR).set(
        (float(metric0) - info.metric_after) / denom)
    return pl2, agg2, info
