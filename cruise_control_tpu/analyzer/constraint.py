"""Numeric balancing thresholds.

Reference: ``analyzer/BalancingConstraint.java:20-100`` — the single holder of
every tunable the goals consult: per-resource balance percentages, capacity
thresholds, low-utilization floors, replica-count limits, topic-replica gap
factors, and overprovisioning parameters.  Defaults mirror
``config/cruisecontrol.properties:114-138`` and the AnalyzerConfig defaults.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource


def _per_resource(cpu: float, nw_in: float, nw_out: float, disk: float) -> np.ndarray:
    return np.array([cpu, nw_in, nw_out, disk], dtype=np.float32)


@dataclass
class BalancingConstraint:
    """All numeric thresholds used by the goals.

    ``balance_threshold[r]`` ≥ 1: a broker is balanced for resource r when its
    utilization is within ``[avg*(2-T), avg*T]`` (ResourceDistributionGoal
    :236-263).  ``capacity_threshold[r]`` ≤ 1: hard cap fraction of capacity
    (CapacityGoal).  ``low_utilization_threshold[r]``: below this cluster-avg
    utilization a resource is not worth balancing.
    """

    balance_threshold: np.ndarray = field(
        default_factory=lambda: _per_resource(1.1, 1.1, 1.1, 1.1))
    capacity_threshold: np.ndarray = field(
        default_factory=lambda: _per_resource(0.7, 0.8, 0.8, 0.8))
    low_utilization_threshold: np.ndarray = field(
        default_factory=lambda: _per_resource(0.0, 0.0, 0.0, 0.0))
    # ReplicaCapacityGoal: max replicas per (alive) broker.
    max_replicas_per_broker: int = 10_000
    # ReplicaDistributionGoal / LeaderReplicaDistributionGoal band factor.
    replica_balance_threshold: float = 1.1
    leader_replica_balance_threshold: float = 1.1
    # TopicReplicaDistributionGoal: gap factor + minimum absolute gap.
    topic_replica_balance_threshold: float = 3.0
    topic_replica_balance_min_gap: int = 2
    # MinTopicLeadersPerBrokerGoal: topics that must keep >= N leaders on every
    # alive broker (reference: topic.names.with.min.leaders.per.broker).
    min_topic_leaders_per_broker: int = 1
    min_leader_topic_names: tuple = ()
    # Goal-violation-triggered runs widen the balance band by this multiplier
    # (AnalyzerConfig goal.violation.distribution.threshold.multiplier).
    goal_violation_distribution_threshold_multiplier: float = 1.0
    # Overprovisioning detection (OptimizerResult provision status).
    overprovisioned_max_replicas_per_broker: int = 1500
    # Solver knobs (no reference equivalent: kernel batch sizing).
    max_candidates_per_round: int = 4096
    max_rounds_per_goal: int = 96

    def balance_band(self, triggered_by_goal_violation: bool = False) -> np.ndarray:
        t = self.balance_threshold.astype(np.float32)
        if triggered_by_goal_violation:
            t = 1.0 + (t - 1.0) * self.goal_violation_distribution_threshold_multiplier
        return t

    def to_dict(self) -> Dict:
        return {
            "balanceThreshold": {r.resource: float(self.balance_threshold[r]) for r in Resource},
            "capacityThreshold": {r.resource: float(self.capacity_threshold[r]) for r in Resource},
            "lowUtilizationThreshold": {
                r.resource: float(self.low_utilization_threshold[r]) for r in Resource},
            "maxReplicasPerBroker": self.max_replicas_per_broker,
            "replicaBalanceThreshold": self.replica_balance_threshold,
            "topicReplicaBalanceThreshold": self.topic_replica_balance_threshold,
        }
